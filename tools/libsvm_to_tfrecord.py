#!/usr/bin/env python
"""LibSVM -> TFRecord converter CLI (reference: tools/libsvm_to_tfrecord.py).

Usage:
    python tools/libsvm_to_tfrecord.py --input tr.libsvm --output tr.tfrecords \
        [--field-size 39] [--num-shards 1]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepfm_tpu.data import libsvm  # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--input", required=True, help="LibSVM text file")
    p.add_argument("--output", required=True, help="output TFRecord path")
    p.add_argument("--field-size", type=int, default=None,
                   help="validate every line has this many features")
    p.add_argument("--num-shards", type=int, default=1)
    args = p.parse_args()
    n = libsvm.convert_libsvm_file(
        args.input, args.output, field_size=args.field_size,
        num_shards=args.num_shards)
    print(f"wrote {n} records to {args.output} ({args.num_shards} shard(s))")


if __name__ == "__main__":
    main()
