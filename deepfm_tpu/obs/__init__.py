"""Unified observability plane: span tracing, one metrics registry.

Stdlib-only at import time — ``obs.trace`` and ``obs.metrics`` are imported
by the spawned input-worker processes, which must not pay (or race on) a
jax import. ``obs.tensorboard`` pulls in the parallel bootstrap and is
therefore NOT re-exported here; import it directly where needed.
"""

from . import metrics, trace  # noqa: F401
