"""Chief-only TF-summary scalar writer (extracted from ``train/tasks.py``).

The Estimator summary-writer analog (the reference emitted loss summaries
every ``log_steps``, flag 1-ps-cpu/...py:47). No-op off-chief or when TF is
unavailable. Beyond the training ``health/*`` scalars it now also carries
the serving and publisher planes: :meth:`scalar_dict` writes any flat
stats/summary dict under a prefix (``serving/``, ``publish/``), filtering
to numeric values so the existing dict surfaces feed it unchanged.

Imports the jax-side bootstrap (chief check) — keep this module OUT of
``obs/__init__`` so the stdlib-only ``obs.trace``/``obs.metrics`` stay
importable from spawned worker processes.
"""

from __future__ import annotations

from ..parallel import bootstrap
from ..utils import logging as ulog


class TensorBoardWriter:
    """Chief-only TF-summary scalar writer — see module docstring."""

    def __init__(self, logdir: str):
        self._writer = None
        if not logdir or not bootstrap.is_chief():
            return
        try:
            import tensorflow as tf  # noqa: PLC0415 (lazy, heavy)
            try:
                # TF must not claim accelerators in the JAX process (JAX
                # preallocates; a TF CUDA init here could OOM the run).
                tf.config.set_visible_devices([], "GPU")
            except Exception:
                pass
            self._tf = tf
            self._writer = tf.summary.create_file_writer(logdir)
        except ImportError:
            ulog.warning("tensorboard_dir set but tensorflow unavailable; "
                         "summaries disabled")

    def scalars(self, step: int, **values: float) -> None:
        if self._writer is None:
            return
        with self._writer.as_default(step=step):
            for name, v in values.items():
                self._tf.summary.scalar(name, v)

    def scalar_dict(self, step: int, prefix: str, values: dict) -> None:
        """Write every numeric entry of a stats/summary dict as
        ``<prefix><key>`` (non-numeric values — policy strings, per-file
        maps, None — are skipped, so the existing serving ``summary()``
        and publisher ``stats()`` dicts feed straight through)."""
        if self._writer is None:
            return
        with self._writer.as_default(step=step):
            for name, v in values.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                self._tf.summary.scalar(f"{prefix}{name}", v)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
