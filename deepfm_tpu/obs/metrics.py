"""One process-global metrics registry over the existing stat surfaces.

Two layers:

- Typed primitives — :class:`Counter` / :class:`Gauge` / :class:`Histogram`
  — for NEW metrics, created via ``REGISTRY.counter("name")`` etc.
- Collector adapters — the five stat classes the repo already has
  (``DataHealth``, ``TrainHealth``, ``ServingStats``, ``HostStageStats``,
  ``Publisher``) self-register in ``__init__`` via :func:`auto_register`,
  and :func:`Registry.snapshot` calls their EXISTING snapshot/summary
  methods. Their result-dict and summary keys are untouched (pinned by
  tests); the registry is a read-side union, not a rewrite.

Collectors hold the instrumented object by weakref: registering costs one
dict entry, a dead object prunes itself on the next register/snapshot, and
short-lived instances (per-test engines, per-epoch pipelines) never leak.

:class:`SnapshotWriter` is the ``--metrics_snapshot_secs`` surface: a
daemon thread appending one JSON line per period to a file, plus a final
line on close. Stdlib-only (imported by worker processes).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

_KIND_METHOD = {
    "data_health": "snapshot",      # data.health.DataHealth
    "train_health": "snapshot",     # train.guard.TrainHealth
    "serving": "summary",           # serve.stats.ServingStats
    "host_stage": "ns_per_record",  # utils.profiling.HostStageStats
    "publisher": "stats",           # train.publish.Publisher
    "loop_health": "snapshot",      # loop.health.LoopHealth
    "experiment": "summary",        # serve.experiment.ExperimentRouter
    "promotion": "stats",           # train.promote.PromotionController
}


class Counter:
    """Monotonic int counter."""

    __slots__ = ("name", "_lock", "_v")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._v = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += int(n)

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


class Gauge:
    """Last-write-wins float."""

    __slots__ = ("name", "_lock", "_v")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Bounded-reservoir value distribution (keeps the newest ``cap``
    observations; count/sum stay exact over the full stream)."""

    __slots__ = ("name", "_lock", "_vals", "_cap", "_next", "count", "sum")

    def __init__(self, name: str, cap: int = 4096):
        self.name = name
        self._lock = threading.Lock()
        self._vals: List[float] = []
        self._cap = max(int(cap), 1)
        self._next = 0
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if len(self._vals) < self._cap:
                self._vals.append(v)
            else:
                self._vals[self._next] = v
                self._next = (self._next + 1) % self._cap

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._vals:
                return None
            vals = sorted(self._vals)
        # nearest-rank
        idx = min(len(vals) - 1,
                  max(0, -(-int(q * 100) * len(vals) // 100) - 1))
        return vals[idx]

    def summary(self) -> Dict[str, Optional[float]]:
        with self._lock:
            n, s = self.count, self.sum
        return {"count": n, "sum": s,
                "p50": self.percentile(0.50), "p99": self.percentile(0.99)}


class Registry:
    """Process-global union of typed metrics and stat-class collectors."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # name -> (weakref-or-None, callable). With a weakref the callable
        # takes the live object; with None it takes no arguments.
        self._collectors: Dict[str, tuple] = {}

    def _typed(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._typed(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._typed(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._typed(name, Histogram)

    def register_collector(self, name: str, fn: Callable,
                           obj: Optional[object] = None) -> str:
        """Attach a snapshot source. With ``obj``, ``fn(obj)`` is called at
        snapshot time and the registration dies with the object (weakref).
        Returns the (possibly suffixed) unique name used."""
        with self._lock:
            self._prune_locked()
            base, n = name, 2
            while name in self._collectors:
                name = f"{base}#{n}"
                n += 1
            ref = weakref.ref(obj) if obj is not None else None
            self._collectors[name] = (ref, fn)
            return name

    def _prune_locked(self) -> None:
        dead = [k for k, (ref, _) in self._collectors.items()
                if ref is not None and ref() is None]
        for k in dead:
            del self._collectors[k]

    def snapshot(self) -> Dict[str, object]:
        """Flat dict: typed metrics under their names, collector outputs
        namespaced ``<collector>.<key>``."""
        with self._lock:
            self._prune_locked()
            metrics = dict(self._metrics)
            collectors = dict(self._collectors)
        out: Dict[str, object] = {}
        for name, m in sorted(metrics.items()):
            if isinstance(m, Histogram):
                for k, v in m.summary().items():
                    out[f"{name}.{k}"] = v
            else:
                out[name] = m.value
        for name, (ref, fn) in sorted(collectors.items()):
            try:
                if ref is not None:
                    obj = ref()
                    if obj is None:
                        continue
                    snap = fn(obj)
                else:
                    snap = fn()
            except Exception as e:  # a broken collector must not sink the rest
                out[f"{name}.error"] = str(e)[:200]
                continue
            if not isinstance(snap, dict):
                out[name] = snap
                continue
            for k, v in snap.items():
                if isinstance(v, (int, float, str, bool)) or v is None:
                    out[f"{name}.{k}"] = v
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()


REGISTRY = Registry()


def auto_register(kind: str, obj: object) -> str:
    """Stat-class ``__init__`` hook: register ``obj``'s existing snapshot
    method under its kind name (``data_health``, ``serving``, ...). Costs
    one weakref'd dict entry; nothing is called until a snapshot is taken."""
    method = _KIND_METHOD.get(kind)
    if method is None:
        raise ValueError(f"unknown collector kind {kind!r}; "
                         f"known: {sorted(_KIND_METHOD)}")
    fn = getattr(type(obj), method)
    return REGISTRY.register_collector(kind, fn, obj=obj)


class SnapshotWriter:
    """Periodic JSONL dump of ``REGISTRY.snapshot()`` to ``path``.

    A daemon thread appends ``{"t": <wall>, "metrics": {...}}`` every
    ``period_secs`` and once more on :meth:`close` (so a short run still
    leaves one line). ``writes``/``write_s`` expose its own cost for the
    bench series."""

    def __init__(self, path: str, period_secs: float,
                 registry: Optional[Registry] = None):
        if period_secs <= 0:
            raise ValueError(
                f"period_secs must be > 0, got {period_secs}")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self.period_secs = float(period_secs)
        self._registry = registry if registry is not None else REGISTRY
        self.writes = 0
        self.write_s = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="metrics-snapshot", daemon=True)
        self._thread.start()

    def _write_once(self) -> None:
        t0 = time.perf_counter()
        line = json.dumps({"t": time.time(),
                           "metrics": self._registry.snapshot()},
                          default=str)
        with open(self.path, "a") as f:
            f.write(line + "\n")
        self.writes += 1
        self.write_s += time.perf_counter() - t0

    def _run(self) -> None:
        while not self._stop.wait(self.period_secs):
            try:
                self._write_once()
            except Exception:
                pass  # metrics must never take down the host process

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self._write_once()  # final flush so short runs leave evidence
        except Exception:
            pass
