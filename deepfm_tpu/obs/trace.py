"""Near-zero-overhead span tracing, exported as Chrome ``trace_event`` JSON.

One process-global :class:`Tracer` records span events into a preallocated
ring (``--trace ring``; wraparound overwrites the oldest events and COUNTS
them — never a silent loss) or an unbounded list (``--trace full``). Off
(the default) every instrumentation site costs one attribute load and a
falsy check: ``span()`` returns a shared no-op singleton, ``begin()``
returns ``None``, and no event object is ever built.

Three event shapes, all Perfetto/chrome://tracing loadable:

- ``span("name", **attrs)`` — a ``with``-block producing one complete
  ("X") event on the calling thread; nesting reconstructs from ts/dur
  containment per (pid, tid).
- ``begin("name", **attrs)`` / ``end(handle, **attrs)`` — an async
  ("b"/"e") pair sharing an id, for spans that start on one thread and
  finish on another (ring waits, executor handoffs).
- ``instant("name", **attrs)`` — a point ("i") event (spills, swaps).

The clock is ``time.time_ns()`` (wall), NOT ``perf_counter_ns``: traces
from several processes (trainer, input workers, drill) merge into ONE
timeline, so timestamps must share an epoch.

Correlation ids: :func:`new_trace_id` mints process-unique int ids
(``pid << 20 | counter``) that ride request paths as plain ints — they
work even when tracing is off, so flag-off call sites need no branches.

Child processes inherit the configuration through ``DEEPFM_TPU_TRACE*``
env vars (set by :func:`configure`, read by :func:`configure_from_env`);
each process exports its own ``trace-<pid>.json`` and :func:`merge`
concatenates them into one file.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple, Union

MODES = ("off", "ring", "full")
DEFAULT_CAPACITY = 65536

ENV_MODE = "DEEPFM_TPU_TRACE"
ENV_DIR = "DEEPFM_TPU_TRACE_DIR"
ENV_BUFFER = "DEEPFM_TPU_TRACE_BUFFER"


class _NullSpan:
    """Shared no-op span: what ``span()`` hands out when tracing is off."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def add(self, **attrs) -> "_NullSpan":
        return self


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Dict):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._t0 = 0

    def __enter__(self) -> "_Span":
        self._t0 = time.time_ns()
        return self

    def add(self, **attrs) -> "_Span":
        """Attach attributes discovered mid-span (e.g. rows after batching)."""
        self._args.update(attrs)
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.time_ns()
        ev = {"name": self._name, "ph": "X", "ts": self._t0 / 1e3,
              "dur": (t1 - self._t0) / 1e3, "pid": os.getpid(),
              "tid": threading.get_ident()}
        if self._args:
            ev["args"] = self._args
        self._tracer._emit(ev)
        return False


class Tracer:
    """Ring- or list-buffered span recorder. Thread-safe; one per process."""

    def __init__(self, mode: str = "off",
                 capacity: int = DEFAULT_CAPACITY) -> None:
        if mode not in MODES:
            raise ValueError(f"trace mode must be one of {MODES}, got {mode!r}")
        self.mode = mode
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._buf: List[Dict] = []
        self._head = 0          # ring overwrite cursor (oldest event)
        self.dropped = 0        # ring wraparound overwrites, counted
        self._ids = itertools.count(1)

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def _emit(self, ev: Dict) -> None:
        with self._lock:
            if self.mode == "ring" and len(self._buf) >= self.capacity:
                self._buf[self._head] = ev
                self._head = (self._head + 1) % self.capacity
                self.dropped += 1
            else:
                self._buf.append(ev)

    def span(self, name: str, **attrs) -> Union[_Span, _NullSpan]:
        if self.mode == "off":
            return _NULL
        return _Span(self, name, attrs)

    def begin(self, name: str, **attrs) -> Optional[Tuple[str, int]]:
        """Open an async span; finish it with :meth:`end` from ANY thread.
        Returns an opaque handle (None when tracing is off)."""
        if self.mode == "off":
            return None
        hid = next(self._ids)
        ev = {"name": name, "cat": name.split(".", 1)[0], "ph": "b",
              "id": hid, "ts": time.time_ns() / 1e3, "pid": os.getpid(),
              "tid": threading.get_ident()}
        if attrs:
            ev["args"] = attrs
        self._emit(ev)
        return (name, hid)

    def end(self, handle: Optional[Tuple[str, int]], **attrs) -> None:
        if handle is None:
            return
        name, hid = handle
        ev = {"name": name, "cat": name.split(".", 1)[0], "ph": "e",
              "id": hid, "ts": time.time_ns() / 1e3, "pid": os.getpid(),
              "tid": threading.get_ident()}
        if attrs:
            ev["args"] = attrs
        self._emit(ev)

    def instant(self, name: str, **attrs) -> None:
        if self.mode == "off":
            return
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": time.time_ns() / 1e3, "pid": os.getpid(),
              "tid": threading.get_ident()}
        if attrs:
            ev["args"] = attrs
        self._emit(ev)

    def events(self) -> List[Dict]:
        """Chronological snapshot (ring order unrolled oldest-first)."""
        with self._lock:
            if self.mode == "ring" and len(self._buf) >= self.capacity:
                return self._buf[self._head:] + self._buf[:self._head]
            return list(self._buf)


# --------------------------------------------------------------------------
# Process-global tracer + module-level API (what call sites import).
# --------------------------------------------------------------------------

_tracer = Tracer()
_trace_dir = ""
_id_counter = itertools.count(1)


def configure(mode: str, *, capacity: int = DEFAULT_CAPACITY,
              trace_dir: str = "", export_env: bool = True) -> None:
    """Install the process-global tracer. With ``export_env`` (default) the
    settings also land in ``DEEPFM_TPU_TRACE*`` so spawned child processes
    (input workers, drill trainer) inherit them via
    :func:`configure_from_env`."""
    global _tracer, _trace_dir
    _tracer = Tracer(mode, capacity)
    _trace_dir = trace_dir or ""
    if export_env:
        os.environ[ENV_MODE] = mode
        os.environ[ENV_BUFFER] = str(int(capacity))
        if trace_dir:
            os.environ[ENV_DIR] = trace_dir
        else:
            os.environ.pop(ENV_DIR, None)


def configure_from_env() -> None:
    """Child-process entry: adopt the parent's trace settings (no-op when
    the parent never configured tracing)."""
    mode = os.environ.get(ENV_MODE, "off")
    if mode == "off":
        return
    try:
        capacity = int(os.environ.get(ENV_BUFFER, DEFAULT_CAPACITY))
    except ValueError:
        capacity = DEFAULT_CAPACITY
    configure(mode, capacity=capacity,
              trace_dir=os.environ.get(ENV_DIR, ""), export_env=False)


def reset() -> None:
    """Back to off + empty buffers (tests)."""
    global _tracer, _trace_dir
    _tracer = Tracer()
    _trace_dir = ""
    for k in (ENV_MODE, ENV_DIR, ENV_BUFFER):
        os.environ.pop(k, None)


def enabled() -> bool:
    return _tracer.enabled


def span(name: str, **attrs) -> Union[_Span, _NullSpan]:
    return _tracer.span(name, **attrs)


def begin(name: str, **attrs) -> Optional[Tuple[str, int]]:
    return _tracer.begin(name, **attrs)


def end(handle: Optional[Tuple[str, int]], **attrs) -> None:
    _tracer.end(handle, **attrs)


def instant(name: str, **attrs) -> None:
    _tracer.instant(name, **attrs)


def dropped() -> int:
    return _tracer.dropped


def new_trace_id() -> int:
    """Mint a correlation id unique across the processes of one run
    (pid-tagged). Works with tracing off — call sites never branch."""
    return (os.getpid() << 20) | (next(_id_counter) & 0xFFFFF)


def export(path: Optional[str] = None) -> Optional[str]:
    """Write this process's events as a Chrome trace JSON; returns the path
    (None when tracing is off). Default path: ``<trace_dir>/trace-<pid>.json``."""
    if not _tracer.enabled:
        return None
    pid = os.getpid()
    if path is None:
        d = _trace_dir or "."
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"trace-{pid}.json")
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": f"deepfm_tpu[{pid}]"}}]
    events.extend(_tracer.events())
    doc = {"traceEvents": events,
           "otherData": {"pid": pid, "mode": _tracer.mode,
                         "dropped_spans": _tracer.dropped}}
    tmp = f"{path}.tmp-{pid}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def merge(src: Union[str, Iterable[str]], out: str) -> str:
    """Concatenate per-process trace files (a directory of
    ``trace-*.json`` or an explicit path list) into one loadable trace;
    per-process drop counts are summed into ``otherData``."""
    if isinstance(src, str):
        paths = sorted(
            os.path.join(src, f) for f in os.listdir(src)
            if f.startswith("trace-") and f.endswith(".json"))
    else:
        paths = list(src)
    events: List[Dict] = []
    total_dropped = 0
    pids: List[int] = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
        other = doc.get("otherData", {})
        total_dropped += int(other.get("dropped_spans", 0))
        if "pid" in other:
            pids.append(int(other["pid"]))
    doc = {"traceEvents": events,
           "otherData": {"merged_from": len(paths), "pids": pids,
                         "dropped_spans": total_dropped}}
    tmp = f"{out}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    return out
