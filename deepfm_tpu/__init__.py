"""deepfm_tpu: a TPU-native distributed CTR-training framework.

Brand-new JAX/XLA/pjit framework with the capabilities of the SageMaker
DeepFM distributed-training reference (async parameter-server CPU recipe +
Horovod/NCCL GPU recipe), re-designed TPU-first: synchronous data parallelism
and embedding-table row-sharding over a `jax.sharding.Mesh`, with XLA
collectives replacing both the gRPC parameter server and NCCL allreduce.
"""

__version__ = "0.4.0"  # round 5

from .config import Config, parse_args  # noqa: F401
