"""Streaming, psum-reducible evaluation metrics.

The reference's sole quality metric is ``tf.metrics.auc(labels, pred)``
(``1-ps-cpu/...py:249-251``) — a streaming *binned* AUC over
``num_thresholds`` buckets with trapezoidal interpolation. This module
implements the same approximation as a pure-JAX accumulator whose state is a
pair of histograms — additive, so cross-host/device reduction is a plain
``psum`` (SURVEY.md hard-part #2), and jit-compatible (fixed shapes).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class AucState(NamedTuple):
    """Histogram of prediction scores split by label. Additive under psum."""
    pos: jnp.ndarray   # f64-safe f32 [num_bins]
    neg: jnp.ndarray   # [num_bins]


def auc_init(num_bins: int = 200) -> AucState:
    return AucState(pos=jnp.zeros((num_bins,), jnp.float32),
                    neg=jnp.zeros((num_bins,), jnp.float32))


def auc_update(state: AucState, probs: jnp.ndarray, labels: jnp.ndarray,
               weights: jnp.ndarray | None = None) -> AucState:
    """Accumulate a batch. probs/labels: [B] or [B,1] in [0,1]."""
    num_bins = state.pos.shape[0]
    probs = probs.reshape(-1).astype(jnp.float32)
    labels = labels.reshape(-1).astype(jnp.float32)
    w = jnp.ones_like(probs) if weights is None else weights.reshape(-1).astype(jnp.float32)
    bins = jnp.clip((probs * num_bins).astype(jnp.int32), 0, num_bins - 1)
    pos = state.pos + jnp.zeros_like(state.pos).at[bins].add(w * labels)
    neg = state.neg + jnp.zeros_like(state.neg).at[bins].add(w * (1.0 - labels))
    return AucState(pos=pos, neg=neg)


def auc_merge(a: AucState, b: AucState) -> AucState:
    return AucState(pos=a.pos + b.pos, neg=a.neg + b.neg)


def auc_psum(state: AucState, axis_name: str) -> AucState:
    return AucState(pos=jax.lax.psum(state.pos, axis_name),
                    neg=jax.lax.psum(state.neg, axis_name))


def auc_compute(state: AucState) -> jnp.ndarray:
    """Trapezoidal AUC over the ROC curve swept across bin thresholds.

    Threshold k = "predict positive iff score >= bin k"; TPR/FPR from suffix
    sums of the histograms; trapezoid over consecutive thresholds — the same
    estimator family as tf.metrics.auc(curve='ROC',
    summation_method='trapezoidal').
    """
    total_pos = jnp.sum(state.pos)
    total_neg = jnp.sum(state.neg)
    # Suffix cumulative: tp[k] = #pos with bin >= k; include k=0 (all) and
    # k=num_bins (none) endpoints.
    tp = jnp.concatenate([jnp.cumsum(state.pos[::-1])[::-1], jnp.zeros((1,))])
    fp = jnp.concatenate([jnp.cumsum(state.neg[::-1])[::-1], jnp.zeros((1,))])
    tpr = tp / jnp.maximum(total_pos, 1.0)
    fpr = fp / jnp.maximum(total_neg, 1.0)
    # ROC swept from threshold high->low is (fpr,tpr) increasing; integrate.
    auc = jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) * 0.5)
    # Degenerate state (empty, or one class only): AUC is undefined — NaN,
    # never a fake 0.0/0.5 that could silently gate a model promotion.
    return jnp.where((total_pos > 0) & (total_neg > 0), auc,
                     jnp.float32(jnp.nan))


class MeanState(NamedTuple):
    total: jnp.ndarray  # scalar
    count: jnp.ndarray  # scalar


def mean_init() -> MeanState:
    return MeanState(total=jnp.zeros((), jnp.float32),
                     count=jnp.zeros((), jnp.float32))


def mean_update(state: MeanState, value: jnp.ndarray,
                count: jnp.ndarray | float = 1.0) -> MeanState:
    return MeanState(total=state.total + value.astype(jnp.float32) * count,
                     count=state.count + count)


def mean_compute(state: MeanState) -> jnp.ndarray:
    return state.total / jnp.maximum(state.count, 1.0)


class WindowedAuc:
    """Sliding-window streaming AUC for the online trainer.

    A batch job evaluates once over a held-out set; a job that trains for
    weeks needs "AUC over the last N steps of traffic" instead. Each
    :meth:`update` bins one eval slice into the same pos/neg histograms as
    :func:`auc_update` (host-side numpy — eval slices arrive as host arrays
    off the predict path) and tags it with the training step; slices older
    than ``window_steps`` are evicted. The window aggregate is therefore a
    histogram pair — additive, so multi-process reduction stays a plain
    psum/allreduce over ``histograms`` before :meth:`compute`, exactly like
    the batch AUC (SURVEY.md hard-part #2).
    """

    def __init__(self, window_steps: int, num_bins: int = 200):
        if window_steps <= 0:
            raise ValueError(f"window_steps must be > 0, got {window_steps}")
        self.window_steps = int(window_steps)
        self.num_bins = int(num_bins)
        from collections import deque
        self._slices = deque()  # (step, pos_hist, neg_hist) np.float64
        self._pos = None  # running window sums (lazy numpy import pattern)
        self._neg = None
        self.examples = 0  # examples currently inside the window

    def _hist(self, probs, labels):
        import numpy as np
        probs = np.asarray(probs, dtype=np.float64).reshape(-1)
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        bins = np.clip((probs * self.num_bins).astype(np.int64),
                       0, self.num_bins - 1)
        pos = np.bincount(bins, weights=labels, minlength=self.num_bins)
        neg = np.bincount(bins, weights=1.0 - labels,
                          minlength=self.num_bins)
        return pos, neg

    def update(self, step: int, probs, labels) -> None:
        """Fold one eval slice (taken at training ``step``) into the window."""
        import numpy as np
        pos, neg = self._hist(probs, labels)
        if self._pos is None:
            self._pos = np.zeros((self.num_bins,), np.float64)
            self._neg = np.zeros((self.num_bins,), np.float64)
        self._slices.append((int(step), pos, neg))
        self._pos += pos
        self._neg += neg
        self.examples += int(pos.sum() + neg.sum())
        self.evict(int(step))

    def evict(self, current_step: int) -> None:
        """Drop slices taken more than ``window_steps`` before ``current_step``."""
        floor = int(current_step) - self.window_steps
        while self._slices and self._slices[0][0] <= floor:
            _, pos, neg = self._slices.popleft()
            self._pos -= pos
            self._neg -= neg
            self.examples -= int(pos.sum() + neg.sum())

    def histograms(self):
        """(pos, neg) window-aggregate histograms — reduce these across
        processes (psum/allreduce) before :meth:`compute` for a global AUC."""
        import numpy as np
        if self._pos is None:
            z = np.zeros((self.num_bins,), np.float64)
            return z, z.copy()
        return self._pos.copy(), self._neg.copy()

    def compute(self, histograms=None) -> float:
        """Windowed AUC (same trapezoidal estimator as :func:`auc_compute`);
        NaN while the window is empty or lacks one class, mirroring the
        batch path — undefined is reported as undefined."""
        pos, neg = self.histograms() if histograms is None else histograms
        return float(auc_compute(AucState(
            pos=jnp.asarray(pos, jnp.float32),
            neg=jnp.asarray(neg, jnp.float32))))


class WindowedAucDict:
    """Per-task :class:`WindowedAuc`: one window per named task, one API.

    ``update`` takes per-task probability/label COLUMNS ([B, T] in
    ``task_names`` order, or [B] when there is one task); ``compute``
    returns ``{task: windowed_auc}``. Each per-task window remains a
    psum-reducible histogram pair (see :meth:`WindowedAuc.histograms`)."""

    def __init__(self, task_names, window_steps: int, num_bins: int = 200):
        self.task_names = tuple(task_names)
        if not self.task_names:
            raise ValueError("task_names must name at least one task")
        self._windows = {t: WindowedAuc(window_steps, num_bins)
                         for t in self.task_names}

    def __getitem__(self, task: str) -> WindowedAuc:
        return self._windows[task]

    @property
    def examples(self) -> int:
        """Examples inside the window (identical across tasks — every
        update feeds all columns)."""
        return self._windows[self.task_names[0]].examples

    def update(self, step: int, probs, labels) -> None:
        import numpy as np
        probs = np.asarray(probs)
        labels = np.asarray(labels)
        if probs.ndim == 1:
            probs = probs[:, None]
        if labels.ndim == 1:
            labels = labels[:, None]
        for i, t in enumerate(self.task_names):
            self._windows[t].update(step, probs[:, i], labels[:, i])

    def compute(self) -> Dict[str, float]:
        return {t: w.compute() for t, w in self._windows.items()}


def auc_numpy_reference(probs, labels) -> float:
    """Exact (rank-based) AUC on host — test oracle for the binned estimator."""
    import numpy as np
    probs = np.asarray(probs).reshape(-1)
    labels = np.asarray(labels).reshape(-1)
    order = np.argsort(probs, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(probs) + 1)
    # average ranks for ties
    sorted_p = probs[order]
    i = 0
    while i < len(sorted_p):
        j = i
        while j + 1 < len(sorted_p) and sorted_p[j + 1] == sorted_p[i]:
            j += 1
        if j > i:
            avg = (i + 1 + j + 1) / 2.0
            ranks[order[i:j + 1]] = avg
        i = j + 1
    n_pos = labels.sum()
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")  # undefined, matching auc_compute
    return float((ranks[labels == 1].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))
