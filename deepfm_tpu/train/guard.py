"""Training-runtime numerical guard + stall watchdog + health accounting.

The reference had no defense between a poisoned batch and the optimizer
state: a single non-finite loss silently corrupted the parameters and the
job trained garbage until someone read the logs. This module is the
training-plane counterpart of ``data/health.py``:

  * :class:`TrainHealth` — thread-safe counters for every runtime fault the
    loop survived (preemptions, non-finite skips, rollbacks, watchdog
    aborts, loss spikes, corrupt resume sidecars), logged per epoch, merged
    into the train-task result dict and emitted to TensorBoard.
  * :class:`NonFiniteGuard` — per-dispatch non-finite loss/param detection
    plus an EMA z-score loss-spike detector, with the configurable
    ``--on_nonfinite {abort,skip,rollback}`` policy. ``skip`` drops the
    poisoned dispatch's update (the next superbatch trains against the
    pre-update state); ``rollback`` asks the task driver (via
    :class:`RollbackSignal`) to restore the last checkpoint and replay from
    its recorded offset. Both are bounded by ``--max_rollbacks``.
  * :class:`StallWatchdog` — a monitor thread that aborts the process with
    a diagnostic dump (current step, last progress time, per-worker
    ``DataHealth`` snapshot) when no dispatch completes within
    ``--dispatch_timeout_s`` — the defense against a hung peer or wedged
    input worker blocking a multi-process job forever.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..obs import metrics as metrics_lib
from ..utils import logging as ulog
from ..utils import preempt as preempt_lib


class TrainHealth:
    """Thread-safe counters for runtime faults survived by the train loop.

    The training-plane mirror of ``data.health.DataHealth`` — same
    snapshot/merge/summary surface so the task driver folds both into one
    result dict.
    """

    COUNTERS = ("preemptions", "nonfinite_skips", "rollbacks",
                "watchdog_aborts", "loss_spikes", "resume_meta_corrupt")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.preemptions = 0          # preempt saves taken (then exited 42)
        self.nonfinite_skips = 0      # poisoned dispatch updates dropped
        self.rollbacks = 0            # checkpoint restores after non-finite
        self.watchdog_aborts = 0      # dispatch-timeout aborts fired
        self.loss_spikes = 0          # EMA z-score outliers (warned only)
        self.resume_meta_corrupt = 0  # unreadable resume sidecars tolerated
        self._dirty = False
        # Unified registry (obs.metrics): snapshot() is the metric surface.
        metrics_lib.auto_register("train_health", self)

    def _bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)
            self._dirty = True

    def record_preemption(self) -> None:
        self._bump("preemptions")

    def record_nonfinite_skip(self) -> None:
        self._bump("nonfinite_skips")

    def record_rollback(self) -> None:
        self._bump("rollbacks")

    def record_watchdog_abort(self) -> None:
        self._bump("watchdog_aborts")

    def record_loss_spike(self) -> None:
        self._bump("loss_spikes")

    def record_resume_meta_corrupt(self) -> None:
        self._bump("resume_meta_corrupt")

    @property
    def total_events(self) -> int:
        with self._lock:
            return sum(getattr(self, k) for k in self.COUNTERS)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: int(getattr(self, k)) for k in self.COUNTERS}

    def merge_into(self, totals: Dict[str, float]) -> None:
        """Accumulate counters into ``totals`` (the train-task result dict)."""
        for k, v in self.snapshot().items():
            totals[k] = totals.get(k, 0) + v

    def summary(self) -> str:
        snap = self.snapshot()
        return " ".join(f"{k}={v}" for k, v in snap.items())

    def consume_dirty(self) -> bool:
        with self._lock:
            dirty, self._dirty = self._dirty, False
            return dirty


class NonFiniteError(RuntimeError):
    """A non-finite loss/params under ``on_nonfinite=abort`` (or a skip/
    rollback budget exhausted). The message carries the step number."""


class RollbackSignal(Exception):
    """Internal control flow: the fit loop requests a checkpoint rollback.

    Caught by the train-task driver, which restores the latest checkpoint
    and replays from its recorded resume offset.
    """

    def __init__(self, step: int, detail: str = ""):
        super().__init__(f"rollback requested at step {step}"
                         + (f": {detail}" if detail else ""))
        self.step = int(step)


POLICIES = ("abort", "skip", "rollback")


class NonFiniteGuard:
    """Per-dispatch non-finite detection + EMA z-score spike detector.

    ``observe(loss, step, params_bad=...)`` classifies one dispatch and
    returns ``"ok"`` / ``"skip"`` / ``"rollback"``; under ``abort`` (or
    once the shared skip/rollback budget ``max_events`` is spent) it raises
    :class:`NonFiniteError` naming the step.

    Cost note (TUNING §2.8): ``skip``/``rollback`` must intercept the
    poisoned state before the next dispatch consumes it, so the fit loop
    syncs the loss scalar once per dispatch — trading a little dispatch
    pipelining for the guarantee. ``abort`` piggybacks on the log-cadence
    sync instead and adds zero per-dispatch cost.

    The spike detector is advisory: it maintains an exponential moving
    mean/variance of the (finite) loss and warns + counts when
    ``|loss - ema| / std`` exceeds ``spike_zscore`` after ``spike_warmup``
    observations. It never skips or aborts — a genuine loss spike with
    finite values is information, not corruption.
    """

    def __init__(self, policy: str = "abort", max_events: int = 3,
                 health: Optional[TrainHealth] = None,
                 spike_zscore: float = 0.0, spike_warmup: int = 20,
                 ema_alpha: float = 0.1):
        if policy not in POLICIES:
            raise ValueError(
                f"on_nonfinite must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.max_events = int(max_events)
        self.health = health if health is not None else TrainHealth()
        self.spike_zscore = float(spike_zscore)
        self.spike_warmup = int(spike_warmup)
        self._alpha = float(ema_alpha)
        self._events = 0
        self._ema = 0.0
        self._var = 0.0
        self._n_obs = 0
        self._params_check: Optional[Callable] = None

    @property
    def per_dispatch(self) -> bool:
        """True when the fit loop must sync + check every dispatch."""
        return self.policy in ("skip", "rollback")

    @property
    def events(self) -> int:
        return self._events

    @classmethod
    def from_config(cls, cfg: Any, health: Optional[TrainHealth] = None
                    ) -> "NonFiniteGuard":
        return cls(policy=cfg.on_nonfinite, max_events=cfg.max_rollbacks,
                   health=health, spike_zscore=cfg.loss_spike_zscore)

    # -- param check -----------------------------------------------------
    def params_nonfinite(self, state: Any) -> bool:
        """True when any inexact param leaf holds a non-finite value. One
        fused on-device all-isfinite reduction; the bool fetch is cheap
        because the caller has already synced the dispatch's loss."""
        import jax  # noqa: PLC0415 (keep module importable without jax)
        import jax.numpy as jnp  # noqa: PLC0415

        if self._params_check is None:
            def all_finite(params):
                ok = jnp.bool_(True)
                for leaf in jax.tree.leaves(params):
                    if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
                return ok
            self._params_check = jax.jit(all_finite)
        return not bool(self._params_check(state.params))

    # -- spike detector --------------------------------------------------
    def _observe_spike(self, loss: float, step: int) -> None:
        if self.spike_zscore <= 0.0:
            return
        self._n_obs += 1
        if self._n_obs == 1:
            self._ema = loss
            self._var = 0.0
            return
        dev = loss - self._ema
        if self._n_obs > self.spike_warmup:
            std = math.sqrt(max(self._var, 1e-12))
            z = abs(dev) / std
            if z > self.spike_zscore:
                self.health.record_loss_spike()
                ulog.warning(
                    f"loss spike at step {step}: loss={loss:.5f} is "
                    f"{z:.1f} sigma from EMA {self._ema:.5f} "
                    f"(threshold {self.spike_zscore}); continuing")
                # A spike must not poison its own baseline.
                return
        self._ema += self._alpha * dev
        self._var = (1 - self._alpha) * (self._var + self._alpha * dev * dev)

    # -- the per-dispatch verdict ---------------------------------------
    def observe(self, loss: float, step: int, *,
                params_bad: bool = False) -> str:
        """Classify one completed dispatch. Returns 'ok' | 'skip' |
        'rollback'; raises :class:`NonFiniteError` for abort or a spent
        budget. ``step`` is the global step AFTER the dispatch."""
        bad = (not math.isfinite(loss)) or params_bad
        if not bad:
            self._observe_spike(loss, step)
            return "ok"
        what = (f"non-finite loss ({loss})" if not math.isfinite(loss)
                else "non-finite parameters")
        if self.policy == "abort":
            raise NonFiniteError(
                f"{what} at step {step} (on_nonfinite=abort)")
        self._events += 1
        if self._events > self.max_events:
            raise NonFiniteError(
                f"{what} at step {step}: non-finite budget exhausted "
                f"({self._events} events > max_rollbacks={self.max_events})")
        if self.policy == "skip":
            self.health.record_nonfinite_skip()
            ulog.warning(
                f"{what} at step {step}: dropping this dispatch's update "
                f"(on_nonfinite=skip, event {self._events}/"
                f"{self.max_events})")
            return "skip"
        ulog.warning(
            f"{what} at step {step}: rolling back to the last checkpoint "
            f"(on_nonfinite=rollback, event {self._events}/"
            f"{self.max_events})")
        return "rollback"


class StallWatchdog:
    """Abort-with-diagnostics when no dispatch completes within the timeout.

    The fit loop calls :meth:`beat` after every completed dispatch; a
    monitor thread checks the time since the last beat and, past
    ``timeout_s``, logs a diagnostic dump — current step, seconds since
    progress, and the input pipeline's per-worker ``DataHealth`` snapshot —
    then calls ``abort`` (default: ``os._exit(EXIT_WATCHDOG)``, because a
    stalled dispatch is usually blocked in native code where an in-thread
    exception cannot land). ``clock`` is injectable for sleep-free tests.
    """

    def __init__(self, timeout_s: float, *,
                 health: Optional[TrainHealth] = None,
                 data_health: Any = None,
                 abort: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 poll_s: Optional[float] = None,
                 name: str = "train"):
        self.timeout_s = float(timeout_s)
        self.health = health
        self._data_health = data_health
        self._abort = abort if abort is not None else self._default_abort
        self._clock = clock
        self._poll = (poll_s if poll_s is not None
                      else max(min(self.timeout_s / 4.0, 1.0), 0.01))
        self._name = name
        self._lock = threading.Lock()
        self._last = self._clock()
        self._step = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    @staticmethod
    def _default_abort(dump: str) -> None:  # noqa: ARG004
        os._exit(preempt_lib.EXIT_WATCHDOG)

    def beat(self, step: int) -> None:
        with self._lock:
            self._last = self._clock()
            self._step = int(step)

    def _dump(self, waited: float) -> str:
        lines = [f"stall watchdog ({self._name}): no dispatch completed in "
                 f"{waited:.1f}s (dispatch_timeout_s={self.timeout_s})",
                 f"  last progress: step {self._step}, {waited:.1f}s ago"]
        dh = self._data_health
        if dh is not None:
            try:
                lines.append(f"  data health: {dh.summary()}")
            except Exception:
                pass
        if self.health is not None:
            lines.append(f"  train health: {self.health.summary()}")
        return "\n".join(lines)

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                waited = self._clock() - self._last
            if waited >= self.timeout_s:
                self.fired = True
                if self.health is not None:
                    self.health.record_watchdog_abort()
                dump = self._dump(waited)
                ulog.error(dump)
                self._abort(dump)
                return

    def start(self) -> "StallWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"stall-watchdog-{self._name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
