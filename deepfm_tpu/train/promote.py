"""Guardrail-gated promotion: the controller that turns publish into
prove-promote-or-rollback.

The :class:`~deepfm_tpu.train.publish.Publisher` makes artifacts atomic;
this module makes them *earned*. A candidate version is ``offer()``-ed, its
per-arm online health (``loop.metrics.arm_health`` windows, computed from
the impression log + joiner) is ``observe()``-d window by window, and the
controller advances the serving ``LATEST`` pointer only after the candidate
passes EVERY gate for ``windows_required`` consecutive windows. One breach
demotes it (typed reason, counted, span-traced, pointer history appended);
a version that fails twice is quarantined and refuses further candidacy.

Every pointer move rides the same append-then-move protocol as the
Publisher (``export.append_pointer_event`` → crash seam → ``write_latest``),
so the whole deployment story — publish, promote, rollback, quarantine — is
replayable from ``pointer_history.jsonl`` alone, and a crash between the
history append and the pointer move heals idempotently on retry.

Gate evaluation is a pure function (:func:`evaluate_gates`) over two plain
metric dicts, so tests and the bench series drive it without any serving
stack behind it.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import metrics as metrics_lib
from ..obs import trace as trace_lib
from ..utils import export as export_lib
from ..utils import faults as faults_lib

# Typed breach reasons — the vocabulary the audit sidecar, the counters, and
# the drill's assertions share. Strings, not an enum, so they serialize
# into history lines and reports untouched.
REASON_NONFINITE = "nonfinite_predictions"
REASON_AUC = "auc_regression"
REASON_LATENCY = "latency_p99"
REASON_CALIBRATION = "calibration_drift"
REASON_STALE = "stale_candidate"
REASON_QUARANTINED = "quarantined"
#: Hold (not breach) reason: the window is too thin to judge either way.
REASON_SAMPLES = "insufficient_samples"

BREACH_REASONS = (REASON_NONFINITE, REASON_AUC, REASON_LATENCY,
                  REASON_CALIBRATION, REASON_STALE)

#: How many gate breaches quarantine a candidate version for good.
QUARANTINE_FAILURES = 2


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """Promotion guardrails (see TUNING §2.19 for sizing guidance).

    ``min_samples`` gates the *judgment*, not the candidate: a thinner
    window is a hold. ``min_auc_delta`` is challenger-minus-control (a
    small negative tolerance absorbs window noise); ``max_p99_ratio``
    bounds challenger p99 as a multiple of control p99, and
    ``max_p99_ms`` > 0 adds an ABSOLUTE p99 ceiling on top (the ratio
    judges relative regressions, the ceiling judges "too slow to serve,
    period" — a sleeping challenger breaches it no matter how noisy the
    control's own tail was); ``max_nonfinite`` is an absolute count
    (default 0: one NaN is a breach); ``max_calibration_err`` bounds
    |mean predicted − observed CTR|; ``max_candidate_age_s`` > 0 adds
    the staleness gate (a frozen candidate that stops refreshing
    breaches on age alone)."""

    min_samples: int = 50
    min_auc_delta: float = -0.02
    max_p99_ratio: float = 1.5
    max_p99_ms: float = 0.0
    max_nonfinite: int = 0
    max_calibration_err: float = 0.2
    max_candidate_age_s: float = 0.0
    windows_required: int = 2

    def __post_init__(self):
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}")
        if self.max_p99_ratio <= 0:
            raise ValueError(
                f"max_p99_ratio must be > 0, got {self.max_p99_ratio}")
        if self.max_p99_ms < 0:
            raise ValueError(
                f"max_p99_ms must be >= 0, got {self.max_p99_ms}")
        if self.max_nonfinite < 0:
            raise ValueError(
                f"max_nonfinite must be >= 0, got {self.max_nonfinite}")
        if self.max_calibration_err < 0:
            raise ValueError(f"max_calibration_err must be >= 0, got "
                             f"{self.max_calibration_err}")
        if self.max_candidate_age_s < 0:
            raise ValueError(f"max_candidate_age_s must be >= 0, got "
                             f"{self.max_candidate_age_s}")
        if self.windows_required < 1:
            raise ValueError(
                f"windows_required must be >= 1, got {self.windows_required}")

    @classmethod
    def from_config(cls, cfg) -> "GateConfig":
        """Build from the ``--experiment_*`` flags (``deepfm_tpu.config``)."""
        return cls(
            min_samples=cfg.experiment_min_samples,
            min_auc_delta=cfg.experiment_min_auc_delta,
            max_p99_ratio=cfg.experiment_max_p99_ratio,
            max_p99_ms=cfg.experiment_max_p99_ms,
            max_nonfinite=cfg.experiment_max_nonfinite,
            max_calibration_err=cfg.experiment_max_calibration_err,
            max_candidate_age_s=cfg.experiment_max_candidate_age_s,
            windows_required=cfg.experiment_gate_windows)


def _finite(x: Any) -> bool:
    return x is not None and isinstance(x, (int, float)) \
        and math.isfinite(float(x))


def evaluate_gates(challenger: Dict[str, Any], control: Dict[str, Any],
                   gates: GateConfig, *,
                   candidate_age_s: float = 0.0
                   ) -> Tuple[bool, List[str], List[str]]:
    """Judge one health window: ``(passed, breaches, holds)``.

    ``challenger`` / ``control`` are per-arm dicts from
    ``loop.metrics.arm_health`` (keys ``n``, ``auc``, ``p99_latency_ms``,
    ``nonfinite``, ``calibration_err``). Breaches are typed reasons (the
    candidate is bad); holds mean the window cannot judge (too thin, or a
    one-class AUC) — a hold neither advances nor demotes. Gates whose
    inputs are unavailable on one side (e.g. no control p99) are skipped
    rather than guessed; the nonfinite gate never skips, because a NaN
    prediction is evidence all by itself."""
    breaches: List[str] = []
    holds: List[str] = []
    if int(challenger.get("nonfinite", 0)) > gates.max_nonfinite:
        breaches.append(REASON_NONFINITE)
    if gates.max_candidate_age_s > 0 \
            and candidate_age_s > gates.max_candidate_age_s:
        breaches.append(REASON_STALE)
    if int(challenger.get("n", 0)) < gates.min_samples:
        holds.append(REASON_SAMPLES)
        return (False, breaches, holds)
    c_auc, b_auc = challenger.get("auc"), control.get("auc")
    if _finite(c_auc) and _finite(b_auc):
        if float(c_auc) - float(b_auc) < gates.min_auc_delta:
            breaches.append(REASON_AUC)
    c_p99, b_p99 = challenger.get("p99_latency_ms"), \
        control.get("p99_latency_ms")
    if _finite(c_p99) and _finite(b_p99) and float(b_p99) > 0:
        if float(c_p99) > gates.max_p99_ratio * float(b_p99):
            breaches.append(REASON_LATENCY)
    if gates.max_p99_ms > 0 and _finite(c_p99) \
            and float(c_p99) > gates.max_p99_ms \
            and REASON_LATENCY not in breaches:
        breaches.append(REASON_LATENCY)
    cal = challenger.get("calibration_err")
    if _finite(cal) and float(cal) > gates.max_calibration_err:
        breaches.append(REASON_CALIBRATION)
    return (not breaches, breaches, holds)


@dataclasses.dataclass(frozen=True)
class Decision:
    """One ``observe()`` outcome. ``action`` ∈ hold | pass | promote |
    rollback | quarantine (quarantine implies the rollback already
    happened); ``reasons`` are the typed breach/hold reasons that drove
    it; ``version`` is the candidate it concerns."""
    action: str
    version: Optional[str]
    reasons: Tuple[str, ...] = ()


class PromotionController:
    """Advance / demote the serving pointer on windowed per-arm health.

    One controller owns one publish dir's deployment state: the stable
    version (what LATEST points at between experiments), at most one
    candidate under evaluation, per-version failure counts, and the
    quarantine set. All pointer moves go through the audited
    append-then-move protocol; ``on_rollback`` is the kill-switch hook
    (the drill wires it to ``ExperimentRouter.kill``) and fires BEFORE the
    pointer moves back, so traffic stops reaching the bad arm first.
    """

    def __init__(self, publish_dir: str, *, gates: GateConfig,
                 stable_version: Optional[str] = None,
                 on_rollback: Optional[Callable[[str, str], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_time: Optional[Callable[[], float]] = None):
        self._dir = publish_dir
        self.gates = gates
        self._on_rollback = on_rollback
        self._clock = clock
        self._wall_time = wall_time
        if stable_version is None:
            current = export_lib.read_latest(publish_dir)
            if current is None:
                raise ValueError(
                    f"no stable_version given and {publish_dir} has no "
                    f"LATEST pointer yet")
            stable_version = os.path.basename(current)
        self.stable_version = str(stable_version)
        self.candidate: Optional[str] = None
        self._candidate_since: Optional[float] = None
        self.passing_windows = 0
        self.failures: Dict[str, int] = {}
        self.quarantined: set = set()
        # Counters (the controller's metric surface).
        self.promotions = 0
        self.rollbacks = 0
        self.quarantines = 0
        self.offers_refused = 0
        self.windows_observed = 0
        self.holds = 0
        self.breaches_by_reason: Dict[str, int] = {}
        metrics_lib.auto_register("promotion", self)

    # -------------------------------------------------------------- offers
    def offer(self, version: str, *, now_s: Optional[float] = None) -> bool:
        """Register ``version`` as the candidate under evaluation. False
        (and counted) when it is quarantined or already stable — the caller
        must not route traffic to a refused candidate."""
        version = str(version)
        if version in self.quarantined or version == self.stable_version:
            self.offers_refused += 1
            trace_lib.instant("promote.offer_refused", version=version,
                              reason=(REASON_QUARANTINED
                                      if version in self.quarantined
                                      else "already_stable"))
            return False
        self.candidate = version
        self._candidate_since = self._clock() if now_s is None else now_s
        self.passing_windows = 0
        trace_lib.instant("promote.offer", version=version)
        return True

    def candidate_age_s(self, now_s: Optional[float] = None) -> float:
        if self._candidate_since is None:
            return 0.0
        now = self._clock() if now_s is None else now_s
        return max(0.0, now - self._candidate_since)

    # ------------------------------------------------------------- observe
    def observe(self, challenger: Dict[str, Any], control: Dict[str, Any],
                *, now_s: Optional[float] = None) -> Decision:
        """Feed one completed health window; returns the typed decision and
        performs any pointer move it implies."""
        if self.candidate is None:
            return Decision("hold", None, (REASON_SAMPLES,))
        self.windows_observed += 1
        passed, breaches, holds = evaluate_gates(
            challenger, control, self.gates,
            candidate_age_s=self.candidate_age_s(now_s))
        version = self.candidate
        if breaches:
            for r in breaches:
                self.breaches_by_reason[r] = \
                    self.breaches_by_reason.get(r, 0) + 1
            return self._demote(version, breaches)
        if holds:
            self.holds += 1
            trace_lib.instant("promote.hold", version=version,
                              reasons=",".join(holds))
            return Decision("hold", version, tuple(holds))
        self.passing_windows += 1
        if self.passing_windows >= self.gates.windows_required:
            return self._promote(version)
        trace_lib.instant("promote.window_pass", version=version,
                          passing=self.passing_windows,
                          required=self.gates.windows_required)
        return Decision("pass", version)

    # ------------------------------------------------------- pointer moves
    def _wall(self) -> Optional[float]:
        return self._wall_time() if self._wall_time is not None else None

    def _promote(self, version: str) -> Decision:
        with trace_lib.span("promote.advance", version=version,
                            windows=self.passing_windows):
            export_lib.append_pointer_event(
                self._dir, version, "promote",
                f"passed {self.passing_windows} windows",
                wall_time=self._wall())
            faults_lib.check_publish_crash("after_history_before_latest")
            export_lib.write_latest(self._dir, version)
        self.stable_version = version
        self.candidate = None
        self._candidate_since = None
        self.passing_windows = 0
        self.promotions += 1
        return Decision("promote", version)

    def _demote(self, version: str, breaches: List[str]) -> Decision:
        reason = ",".join(breaches)
        if self._on_rollback is not None:
            try:
                self._on_rollback(version, reason)   # kill-switch first
            except Exception:  # noqa: BLE001 — a bad hook must not stop it
                pass
        with trace_lib.span("promote.rollback", version=version,
                            reason=reason):
            export_lib.append_pointer_event(
                self._dir, self.stable_version, "rollback",
                f"{version}: {reason}", wall_time=self._wall())
            faults_lib.check_publish_crash("after_history_before_latest")
            export_lib.write_latest(self._dir, self.stable_version)
        self.rollbacks += 1
        self.candidate = None
        self._candidate_since = None
        self.passing_windows = 0
        self.failures[version] = self.failures.get(version, 0) + 1
        if self.failures[version] >= QUARANTINE_FAILURES:
            self.quarantined.add(version)
            self.quarantines += 1
            export_lib.append_pointer_event(
                self._dir, version, "quarantine",
                f"failed {self.failures[version]}x: {reason}",
                wall_time=self._wall())
            trace_lib.instant("promote.quarantine", version=version,
                              reason=reason)
            return Decision("quarantine", version, tuple(breaches))
        return Decision("rollback", version, tuple(breaches))

    # ------------------------------------------------------------- surface
    def history(self) -> List[Dict[str, Any]]:
        return export_lib.pointer_history(self._dir)

    def stats(self) -> Dict[str, Any]:
        return {
            "stable_version": self.stable_version,
            "candidate_version": self.candidate,
            "passing_windows": self.passing_windows,
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "quarantines": self.quarantines,
            "quarantined_versions": sorted(self.quarantined),
            "offers_refused": self.offers_refused,
            "windows_observed": self.windows_observed,
            "gate_holds": self.holds,
            "gate_breaches_by_reason": dict(self.breaches_by_reason),
        }
