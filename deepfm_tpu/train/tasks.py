"""Task dispatch driver: train / eval / infer / export (the L3 layer).

Reimplements the reference ``main()`` dispatch (``1-ps-cpu/...py:341-467``,
``2-hvd-gpu/...py:289-431``) TPU-first:

  * ``train`` — per-epoch train loop with post-epoch eval (the Horovod
    file-mode shape, ``2-hvd-gpu/...py:390-394``), checkpoint every
    ``save_checkpoints_steps``, auto-resume from ``model_dir``, final
    serving export (train also exports, reference ``:451-467``).
  * ``eval`` — AUC + loss on the eval files (``DeepFM.evaluate`` analog).
  * ``infer`` — batch prediction writing one probability per line to
    ``pred.txt`` (reference ``:445-449``).
  * ``export`` — write the servable artifact (reference ``:451-467``).

File resolution follows the reference glob conventions (``tr*`` / ``va*`` /
``te*`` + ``.tfrecords``, reference ``:373-377``) with a fallback to all
``*.tfrecords`` in the directory.
"""

from __future__ import annotations

import glob as _glob
import os
import time
import warnings
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..config import Config
from ..data import cache as cache_lib
from ..data import fileio
from ..data import pipeline as pipe_lib
from ..data import sharding as shard_lib
from ..data import stream as stream_lib
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..obs.tensorboard import TensorBoardWriter as _TensorBoardWriter
from ..parallel import bootstrap
from ..utils import checkpoint as ckpt_lib
from ..utils import export as export_lib
from ..utils import faults as faults_lib
from ..utils import logging as ulog
from ..utils import preempt as preempt_lib
from ..utils import profiling as prof_lib
from ..utils import retry as retry_lib
from . import guard as guard_lib
from . import metrics as metrics_lib
from . import publish as publish_lib
from .loop import Trainer, pad_batch
from .state import TrainState


def resolve_files(directory: str, prefix: str) -> List[str]:
    """Glob `{prefix}*.tfrecords`; fall back to all *.tfrecords.
    Supports local dirs and object-store URLs (gs://...)."""
    if not directory:
        return []
    files = fileio.glob(fileio.join(directory, f"{prefix}*.tfrecords"))
    if not files:
        files = fileio.glob(fileio.join(directory, "*.tfrecords"))
    return files


def _channel_path(cfg: Config, name: str, *, require: bool = False) -> str:
    """Resolve a channel name to a directory: the SageMaker-contract env var
    ``SM_CHANNEL_<NAME>`` when set, else a ``<data_dir>/<name>`` subdirectory,
    else ``data_dir`` itself (single-dir layouts).

    ``require=True`` (multi-path channels) turns the fallback into an error:
    silently resolving every worker's private channel to the shared
    ``data_dir`` would make all local workers train identical records."""
    env_key = "SM_CHANNEL_" + "".join(
        c if c.isalnum() else "_" for c in name).upper()
    if os.environ.get(env_key):
        return os.environ[env_key]
    sub = fileio.join(cfg.data_dir, name) if cfg.data_dir else ""
    if sub and fileio.isdir(sub):
        return sub
    if require:
        raise FileNotFoundError(
            f"channel {name!r} resolves to neither ${env_key} nor "
            f"{sub or '<data_dir>/' + name!r}; enable_data_multi_path needs "
            f"a real private directory per training channel")
    return cfg.data_dir


def resolve_channel_dirs(cfg: Config, *, process_index: Optional[int] = None
                         ) -> Tuple[str, str]:
    """(train_dir, eval_dir) for this process from the channel layout.

    Reference semantics (``2-hvd-gpu/...py:376-380,403`` + README-EN.md:78-84):
    SM_CHANNELS arrives sorted with the eval channel FIRST; under
    ``enable_data_multi_path`` each local worker reads its own private
    training channel — ``channel_names[1 + local_rank]``. Without channels
    configured this degenerates to the plain data_dir/val_data_dir pair.
    """
    names = cfg.channel_names
    eval_default = cfg.val_data_dir or cfg.data_dir
    if not names:
        return cfg.data_dir, eval_default
    eval_dir = (_channel_path(cfg, names[0])
                if len(names) > 1 else eval_default)
    train_names = names[1:] if len(names) > 1 else names
    wph = max(cfg.worker_per_host, 1)
    if cfg.enable_data_multi_path:
        if len(train_names) < wph:
            raise ValueError(
                f"enable_data_multi_path needs one training channel per "
                f"local worker: have {len(train_names)} channels "
                f"{train_names} for worker_per_host={wph} "
                f"(reference contract, README-EN.md:82)")
        rank = jax.process_index() if process_index is None else process_index
        train_dir = _channel_path(cfg, train_names[rank % wph], require=True)
    else:
        train_dir = _channel_path(cfg, train_names[0])
    return train_dir, eval_dir


def _local_batch_size(cfg: Config) -> int:
    nproc = jax.process_count()
    if cfg.batch_size % max(nproc, 1) != 0:
        raise ValueError(
            f"global batch_size={cfg.batch_size} not divisible by "
            f"process_count={nproc}")
    return cfg.batch_size // nproc


def _shard_spec(cfg: Config, files: List[str],
                rank: Optional[int] = None) -> shard_lib.ShardSpec:
    rank = jax.process_index() if rank is None else rank
    return shard_lib.shard_files(
        files,
        enable_data_multi_path=cfg.enable_data_multi_path,
        enable_s3_shard=cfg.enable_s3_shard,
        rank=rank,
        local_rank=rank % max(cfg.worker_per_host, 1),
        world_size=jax.process_count(),
        workers_per_host=cfg.worker_per_host,
    )


def _validate_shard_coverage(cfg: Config, files: List[str]) -> None:
    """Startup guard for multi-process jobs: the per-rank shard specs must
    jointly cover every training file exactly once (the property the
    README decision table guarantees). Pure policy computation — every rank
    derives all ranks' specs and checks the same thing. Only meaningful
    when all ranks see the same file list (not multi-path private dirs)."""
    world = jax.process_count()
    if world <= 1 or cfg.enable_data_multi_path:
        return
    if cfg.enable_s3_shard:
        # Storage pre-sharded per host: this host's local workers must cover
        # THIS host's file list (other hosts hold other files).
        ranks = range(min(max(cfg.worker_per_host, 1), world))
    else:
        ranks = range(world)
    specs = [_shard_spec(cfg, files, rank=r) for r in ranks]
    shard_lib.validate_shard_coverage(specs, sorted(files))


def _fault_tolerance_kwargs(cfg: Config) -> Dict:
    """Bad-record policy + I/O retry knobs shared by every pipeline build."""
    return dict(
        on_bad_record=cfg.on_bad_record,
        max_bad_records=cfg.max_bad_records,
        retry_policy=retry_lib.policy_from_config(cfg),
    )


def _decoded_cache_dir(cfg: Config) -> str:
    """Disk-cache location: explicit flag, else a model_dir subdirectory
    (keeps the slabs next to the artifacts they trained)."""
    if cfg.decoded_cache != "disk":
        return ""
    if cfg.decoded_cache_dir:
        return cfg.decoded_cache_dir
    if cfg.model_dir:
        return os.path.join(cfg.model_dir, "decoded_cache")
    raise ValueError("--decoded_cache disk needs --decoded_cache_dir "
                     "or --model_dir")


def make_pipeline(cfg: Config, files: List[str], *, epochs: int = 1,
                  shuffle: bool = True, sharded: bool = True,
                  drop_remainder: Optional[bool] = None,
                  epoch_offset: int = 0,
                  skip_batches: int = 0) -> pipe_lib.CtrPipeline:
    return pipe_lib.CtrPipeline(
        files,
        decoded_cache=cfg.decoded_cache,
        decoded_cache_dir=_decoded_cache_dir(cfg),
        epoch_offset=epoch_offset,
        skip_batches=skip_batches,
        field_size=cfg.field_size,
        batch_size=_local_batch_size(cfg),
        num_epochs=epochs,
        shuffle=shuffle,
        shuffle_files=shuffle and cfg.shuffle_files,
        shuffle_buffer=cfg.shuffle_buffer,
        drop_remainder=cfg.drop_remainder if drop_remainder is None else drop_remainder,
        seed=cfg.seed,
        shard=_shard_spec(cfg, files) if sharded else None,
        prefetch_batches=cfg.prefetch_batches,
        use_native_decoder=cfg.use_native_decoder,
        native_assembly=cfg.native_assembly,
        reader_threads=cfg.reader_threads,
        input_workers=cfg.input_workers,
        stall_timeout_s=cfg.dispatch_timeout_s,
        verify_crc=cfg.verify_crc,
        num_labels=cfg.num_tasks,
        history=cfg.history_max_len > 0,
        history_max_len=max(1, cfg.history_max_len),
        **_fault_tolerance_kwargs(cfg),
    )


def _eval_pipeline(cfg: Config, va_files: List[str]) -> pipe_lib.CtrPipeline:
    """Eval reads every record: no shuffle, keep the tail batch — the
    weighted eval step pads it to the compiled shape with zero-weight rows,
    so drop_remainder would only lose data, never save a recompile."""
    return make_pipeline(cfg, va_files, shuffle=False, drop_remainder=False)


def make_streaming_pipeline(cfg: Config, files: List[str], *, epochs: int = 1,
                            skip_batches: int = 0, epoch_offset: int = 0
                            ) -> pipe_lib.StreamingCtrPipeline:
    """Pipe-mode analog (``--pipe_mode 1``): one sequential single-pass
    stream over this process's file shard, epochs replayed producer-side
    (the reference's FIFO shape, ``2-hvd-gpu/...py:403-405``). The shard's
    record-level component carries through — when ranks share the same files
    (fewer files than processes), each keeps every world-th record."""
    shard = _shard_spec(cfg, files)
    # One DataHealth shared by producer and consumer: the chained stream
    # heals transient read faults per file (so retries carry file names),
    # the consumer counts bad records against the same stats object.
    health = pipe_lib.DataHealth()
    stream = pipe_lib.ChainedFileStream(
        list(shard.files), num_epochs=epochs,
        shuffle_each_epoch=cfg.shuffle_files, seed=cfg.seed,
        epoch_offset=epoch_offset,
        retry_policy=retry_lib.policy_from_config(cfg), health=health)
    return pipe_lib.StreamingCtrPipeline(
        stream,
        field_size=cfg.field_size,
        batch_size=_local_batch_size(cfg),
        drop_remainder=cfg.drop_remainder,
        prefetch_batches=cfg.prefetch_batches,
        use_native_decoder=cfg.use_native_decoder,
        record_shard=shard.record_shard,
        skip_batches=skip_batches,
        verify_crc=cfg.verify_crc,
        on_bad_record=cfg.on_bad_record,
        max_bad_records=cfg.max_bad_records,
        num_labels=cfg.num_tasks,
        health=health,
    )


# High-water-mark sidecar for the online stream source, next to the
# checkpoints it must stay consistent with.
_STREAM_SIDECAR = "stream_manifest.json"

# Stable files-digest sentinel for online mode: the live directory listing
# grows by design, so the resume gate cannot fingerprint WHAT will be read —
# the stream's high-water-mark sidecar carries that contract instead, and
# this constant keeps the resume_meta digest comparison from spuriously
# invalidating a perfectly replayable skip.
_ONLINE_FILES_DIGEST = "online-stream-v1"


def make_online_pipeline(cfg: Config, train_dir: str, *, skip_batches: int = 0
                         ) -> Tuple[pipe_lib.StreamingCtrPipeline,
                                    stream_lib.UnboundedFileStream]:
    """Unbounded-stream producer for ``--online_mode``: the watcher tails
    ``tr*.tfrecords`` under ``train_dir`` (see data/stream.py for the
    admission/heal protocol) and the unchanged streaming consumer decodes
    it. Returns (pipeline, stream) — the stream handle lets the preemption
    path wake a blocked poll wait. Single-process for now: multi-process
    online mode needs chief-coordinated admission so every rank replays the
    same order (ROADMAP item 1's serving work is the priority first)."""
    if jax.process_count() > 1:
        raise NotImplementedError(
            "online_mode is single-process for now: shard admission order "
            "must be chief-coordinated before ranks can record-shard an "
            "unbounded stream consistently")
    health = pipe_lib.DataHealth()
    sidecar = (fileio.join(cfg.model_dir, _STREAM_SIDECAR)
               if cfg.model_dir else "")
    stream = stream_lib.UnboundedFileStream(
        train_dir, pattern="tr*.tfrecords", sidecar_path=sidecar,
        poll_secs=cfg.stream_poll_secs,
        idle_timeout_secs=cfg.stream_idle_timeout_secs,
        retry_policy=retry_lib.policy_from_config(cfg), health=health)
    pipeline = pipe_lib.StreamingCtrPipeline(
        stream,
        field_size=cfg.field_size,
        batch_size=_local_batch_size(cfg),
        drop_remainder=cfg.drop_remainder,
        prefetch_batches=cfg.prefetch_batches,
        use_native_decoder=cfg.use_native_decoder,
        skip_batches=skip_batches,
        verify_crc=cfg.verify_crc,
        on_bad_record=cfg.on_bad_record,
        max_bad_records=cfg.max_bad_records,
        num_labels=cfg.num_tasks,
        stream_label=f"<online:{train_dir}>",
        health=health,
    )
    return pipeline, stream


def _fit_epoch(trainer: Trainer, cfg: Config, state: TrainState, pipeline,
               hooks, on_log, guard=None
               ) -> Tuple[TrainState, Dict[str, float]]:
    """One epoch of training: device-resident when ``--device_dataset`` is
    set and the run qualifies, otherwise the staged host pipeline. The
    fallback warns with the disqualifier so an operator expecting device
    residency learns why the run is staged."""
    if cfg.device_dataset:
        reason = trainer.device_dataset_ineligible(pipeline)
        if reason is None:
            return trainer.fit_device_resident(
                state, pipeline, hooks=hooks, on_log=on_log, guard=guard)
        warnings.warn(
            f"--device_dataset fell back to the staged input path: {reason}",
            RuntimeWarning, stacklevel=2)
    return trainer.fit(state, pipeline, hooks=hooks, on_log=on_log,
                       guard=guard)


def _restore_or_init(trainer: Trainer, cfg: Config, require: bool,
                     mgr: Optional[ckpt_lib.CheckpointManager] = None
                     ) -> TrainState:
    """Init state, restoring from the latest checkpoint when one exists.

    For require=True tasks (eval/infer/export) a missing/empty model_dir is
    an error — checked by filesystem probe BEFORE any manager is built so a
    mistyped path is not created as a side effect. (The probe outcome is
    identical on all ranks: nothing creates the dir before this point.)
    For train, the caller passes its manager in — manager construction runs
    a cross-process barrier, so every rank must build the same managers in
    the same order; an isdir-gated construction would race.

    Hot/cold tiering: checkpoints are written DENSIFIED
    (``TieredEmbeddingRuntime.checkpoint_state``), so the restore template
    is the dense state (``init_state(tiered=False)``) and adoption into the
    hot cache happens after the restore — the restored Adam moments seed
    the cold tiers, making the round-trip bit-exact in both directions.
    """
    tier = getattr(trainer, "_tier", None)
    state = (trainer.init_state(tiered=False) if tier is not None
             else trainer.init_state())

    def _adopted(s: TrainState) -> TrainState:
        return tier.adopt(s) if tier is not None else s

    if not cfg.model_dir:
        if require:
            raise FileNotFoundError(
                f"task '{cfg.task_type}' requires model_dir")
        return _adopted(state)
    if require and not fileio.isdir(cfg.model_dir):
        raise FileNotFoundError(
            f"task '{cfg.task_type}' needs a checkpoint in model_dir="
            f"{cfg.model_dir!r}")
    own = mgr is None
    if own:
        mgr = ckpt_lib.CheckpointManager(
            cfg.model_dir, max_to_keep=cfg.keep_checkpoint_max,
            retry_policy=retry_lib.policy_from_config(cfg))
    try:
        if mgr.latest_step() is not None:
            state = mgr.restore(state)
        elif require:
            raise FileNotFoundError(
                f"task '{cfg.task_type}' needs a checkpoint in model_dir="
                f"{cfg.model_dir!r}")
    finally:
        if own:
            mgr.close()
    return _adopted(state)


def _ckpt_state(trainer: Trainer, state: TrainState) -> TrainState:
    """What goes INTO every checkpoint save: under hot/cold tiering the hot
    window is flushed and the tables + Adam slots densified to full shape,
    so the artifact restores bit-exactly into untiered (or differently
    sized) configs. Dense runs pass through untouched."""
    tier = getattr(trainer, "_tier", None)
    return tier.checkpoint_state(state) if tier is not None else state


def _servable_state(trainer: Trainer, state: TrainState) -> TrainState:
    """Export-time analog of :func:`_ckpt_state`: the serving artifact
    needs the full dense tables, not the hot window."""
    tier = getattr(trainer, "_tier", None)
    return tier.densified(state) if tier is not None else state


def run(cfg: Config) -> Dict[str, float]:
    """Entry point: bootstrap, dispatch on task_type, return result metrics."""
    bootstrap.initialize(cfg)
    # Config-driven retry for every fileio op (glob/stat/open + the resume
    # sidecar reads) — not just the pipelines' own streams.
    fileio.set_retry_policy(retry_lib.policy_from_config(cfg))
    # Drill seam: env-scripted read faults reach a LAUNCHED subprocess,
    # where the in-process FlakyFS context manager can't (online_drill.py).
    faults_lib.install_env_faults()
    # Telemetry plane: span tracing (exported as Chrome-trace JSON on the
    # way out, even on preemption) plus the periodic metrics snapshotter.
    # configure() also exports env vars so spawned input workers inherit
    # the mode and write sibling per-pid trace files for merge().
    obs_dir = cfg.trace_dir or cfg.model_dir or "."
    obs_trace.configure(cfg.trace, capacity=cfg.trace_buffer,
                        trace_dir=obs_dir)
    snap_writer = None
    if cfg.metrics_snapshot_secs > 0:
        fileio.makedirs(obs_dir)
        snap_writer = obs_metrics.SnapshotWriter(
            os.path.join(obs_dir, f"metrics-{os.getpid()}.jsonl"),
            cfg.metrics_snapshot_secs)
    ulog.info(
        f"task={cfg.task_type} model={cfg.model} processes="
        f"{jax.process_count()} devices={len(jax.devices())}")
    trainer = Trainer(cfg)
    try:
        if cfg.task_type == "train":
            return _task_train(trainer, cfg)
        if cfg.task_type == "eval":
            return _task_eval(trainer, cfg)
        if cfg.task_type == "infer":
            return _task_infer(trainer, cfg)
        if cfg.task_type == "export":
            return _task_export(trainer, cfg)
        raise ValueError(f"unknown task_type {cfg.task_type!r}")
    finally:
        if snap_writer is not None:
            snap_writer.close()
        obs_trace.export()


# Multi-process ranks only consult the (rank-local) clock at agreed dispatch
# counts, then adopt the chief's verdict — keeping the eval collective in
# lockstep across processes without a per-dispatch sync.
_EVAL_CHECK_DISPATCHES = 50


def _eval_check_due(n_dispatch: int) -> bool:
    """Deterministic (rank-independent) schedule of clock-check dispatches:
    powers of two early so short runs still get mid-train evals, then every
    _EVAL_CHECK_DISPATCHES to bound sync frequency."""
    if n_dispatch < _EVAL_CHECK_DISPATCHES:
        return n_dispatch & (n_dispatch - 1) == 0  # 1, 2, 4, 8, 16, 32
    return n_dispatch % _EVAL_CHECK_DISPATCHES == 0


def _make_throttled_eval_hook(trainer: Trainer, cfg: Config,
                              va_files: List[str], result: Dict[str, float],
                              on_eval=None, evaluate=None):
    """Mid-train eval hook with TrainSpec/EvalSpec timing semantics
    (start_delay_secs / throttle_secs, reference 1-ps-cpu/...py:440-441).

    Multi-process safety: dispatch counts are identical across ranks because
    ``Trainer.fit`` min-truncates ragged shards (``_stage_multiprocess``), so every
    rank reaches each agreed check dispatch — the chief's clock verdict is
    then broadcast and the eval collective entered (or skipped) in lockstep."""
    import time as _time

    t_start = _time.time()
    last_eval_t: List[Optional[float]] = [None]
    n_dispatch = [0]
    result["mid_train_evals"] = 0.0

    def hook(state, m) -> None:
        n_dispatch[0] += 1
        multi = jax.process_count() > 1
        if multi and not _eval_check_due(n_dispatch[0]):
            return  # between agreed check points
        now = _time.time()
        due = (now - t_start >= cfg.eval_start_delay_secs
               and (last_eval_t[0] is None
                    or now - last_eval_t[0] >= cfg.eval_throttle_secs))
        if multi:
            from jax.experimental import multihost_utils  # noqa: PLC0415
            due = bool(multihost_utils.broadcast_one_to_all(
                np.asarray(due)))
        if not due:
            return
        last_eval_t[0] = _time.time()
        ev = (evaluate(state) if evaluate is not None
              else trainer.evaluate(state, _eval_pipeline(cfg, va_files)))
        result["mid_train_evals"] += 1
        result.update({"auc": ev["auc"], "eval_loss": ev["loss"],
                       "eval_examples_per_sec": ev["examples_per_sec"]})
        result.update({k: v for k, v in ev.items() if k.startswith("auc_")})
        ulog.info(f"throttled eval @ step {int(state.step)}: "
                  f"auc={ev['auc']:.5f} loss={ev['loss']:.5f}")
        if on_eval is not None:
            on_eval(ev, state)

    return hook


def _make_online_eval(trainer: Trainer, cfg: Config, va_files: List[str],
                      window, step_fn):
    """Online-mode evaluate fn: one predict pass over the held-out set,
    folded into a sliding :class:`~deepfm_tpu.train.metrics.WindowedAuc`
    tagged with the current training step — "AUC over the last N steps of
    traffic" rather than the batch job's cumulative AUC. Single-process
    (online mode is; see make_online_pipeline)."""
    import time as _time

    local_bs = _local_batch_size(cfg)
    task_names = cfg.task_names
    num_tasks = len(task_names)
    weights = cfg.task_weight_values

    def evaluate(state: TrainState) -> Dict[str, float]:
        pipeline = _eval_pipeline(cfg, va_files)
        probs: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        real_rows: List[int] = []
        t0 = _time.time()

        def feed():
            for batch in pipeline:
                n = batch["label"].shape[0]
                real_rows.append(n)
                cols = [np.asarray(batch["label"]).reshape(-1)[:n]]
                if num_tasks > 1:
                    cols.append(
                        np.asarray(batch["label2"]).reshape(-1)[:n])
                labels.append(np.stack(cols, axis=1))
                yield (pad_batch(batch, local_bs)  # pad tail, trim after
                       if n < local_bs else batch)

        for i, p in enumerate(trainer.predict(state, feed())):
            arr = np.asarray(p)
            if arr.ndim == 1:
                arr = arr[:, None]
            probs.append(arr[:real_rows[i]])
        elapsed = max(_time.time() - t0, 1e-9)
        p = (np.concatenate(probs) if probs
             else np.zeros((0, num_tasks), np.float64)).astype(np.float64)
        y = (np.concatenate(labels) if labels
             else np.zeros((0, num_tasks), np.float64)).astype(np.float64)
        window.update(int(step_fn()), p, y)
        pc = np.clip(p, 1e-7, 1.0 - 1e-7)
        if len(y):
            per_task = -(y * np.log(pc)
                         + (1.0 - y) * np.log1p(-pc)).mean(axis=0)
            loss = float(sum(w * per_task[t]
                             for t, w in enumerate(weights)))
        else:
            loss = 0.0
        auc = window.compute()
        result = {"loss": loss,
                  "examples_per_sec": len(y) / elapsed,
                  "window_examples": float(window.examples)}
        if isinstance(auc, dict):
            result["auc"] = auc[task_names[0]]
            result.update({f"auc_{t}": v for t, v in auc.items()})
        else:
            result["auc"] = auc
        return result

    return evaluate


_RESUME_META = "resume_meta.json"


def _write_resume_meta(model_dir: str, meta: Dict) -> None:
    """Chief-only sidecar recording data-pipeline position alongside each
    checkpoint — the step-accurate-resume half the checkpoint itself can't
    carry (SURVEY hard-part #5; the reference punts and replays the epoch)."""
    if not bootstrap.is_chief():
        return
    import json  # noqa: PLC0415
    with fileio.open_stream(fileio.join(model_dir, _RESUME_META), "w") as f:
        json.dump(meta, f)


def _read_resume_meta(model_dir: str,
                      health: Optional[guard_lib.TrainHealth] = None
                      ) -> Optional[Dict]:
    """Read the resume sidecar; a corrupt/truncated file (a preemption can
    land mid-json.dump) degrades to checkpoint-step-only resume — warn and
    count it, never raise: the checkpoint itself is still good."""
    import json  # noqa: PLC0415
    path = fileio.join(model_dir, _RESUME_META)
    if not fileio.exists(path):
        return None
    try:
        with fileio.open_stream(path, "r") as f:
            return json.load(f)
    except (ValueError, OSError) as exc:  # torn write / unreadable
        ulog.warning(
            f"resume sidecar {path} unreadable ({exc!r}); falling back to "
            f"checkpoint-step-only resume (the interrupted epoch replays)")
        if health is not None:
            health.record_resume_meta_corrupt()
        return None


def _files_fingerprint(cfg: Config, files: List[str]) -> str:
    """Digest of WHAT the pipeline would read: the resolved training file
    list (basenames + byte sizes — robust to moving the directory wholesale,
    sensitive to any add/remove/rename/rewrite) plus the shard-mapping flags.
    If either changes between the interrupted run and the resume, the
    per-epoch shuffle order / per-rank shard assignment changes and a
    mid-epoch ``skip_batches`` would silently skip the WRONG prefix (records
    double-trained or never trained) — so ``_resume_position`` requires this
    digest to match and falls back to epoch-replay otherwise (ADVICE r3).

    Computed on the chief only (see ``_task_train``: the resume decision is
    broadcast, never derived per-rank). Under ``enable_data_multi_path``
    ``files`` (the chief's own private channel) is ignored and the digest
    covers EVERY local worker's training channel — SageMaker downloads all
    channels to every instance (README-EN.md:82), so the chief can resolve
    its siblings' channels and a sibling-channel edit invalidates the skip
    even though the chief's channel is unchanged (ADVICE r4 high).

    Stat/resolve failures degrade to a stable sentinel rather than crashing:
    ``tf.io.gfile`` raises ``tf.errors.OpError`` (an ``Exception``, NOT an
    ``OSError``) for remote paths, e.g. a file deleted between glob and
    fingerprint (ADVICE r4 low)."""
    import hashlib  # noqa: PLC0415

    h = hashlib.sha256()
    h.update(f"v1|{int(cfg.enable_data_multi_path)}|"
             f"{int(cfg.enable_s3_shard)}|{cfg.worker_per_host}|".encode())
    if cfg.enable_data_multi_path:
        tagged = []
        for r in range(max(cfg.worker_per_host, 1)):
            try:
                chan_dir, _ = resolve_channel_dirs(cfg, process_index=r)
                tagged.extend((f"c{r}", p)
                              for p in resolve_files(chan_dir, "tr"))
            except Exception:  # unresolvable sibling channel: stable marker
                tagged.append((f"c{r}", "<unresolved>"))
    else:
        tagged = [("", p) for p in files]
    for tag, path in sorted(tagged):
        try:
            n = fileio.size(path) if path != "<unresolved>" else -2
        except Exception:  # transient stat failure / gfile OpError
            n = -1
        h.update(f"{tag}={os.path.basename(path)}:{n}|".encode())
    return h.hexdigest()[:32]


def _consumption_layout(cfg: Config) -> List[int]:
    """Fingerprint of HOW batches are consumed. The pooled emission order
    and geometry depend on all of these (k-group vs per-batch drains,
    per-rank sharding, batch/pool sizes, shuffle seed), so a mid-epoch skip
    is only exact when the resuming run consumes exactly the way the
    interrupted run did; any difference falls back to epoch-replay."""
    # Leading element is a PIPELINE FORMAT VERSION: bump it whenever the
    # emission order for identical config changes (e.g. the r3 scatter
    # permutation), so a resume across framework versions falls back to
    # epoch-replay instead of silently mis-skipping.
    # decoded_cache changes chunk-arrival boundaries and therefore the pool
    # drain points whenever the pool is smaller than the epoch, so a resume
    # across cache modes must fall back to epoch-replay.
    # native_assembly does NOT change emission bytes (fused and scatter
    # paths are bit-identical), but it is consumption surface all the same:
    # including it (a list-LENGTH change old sidecars can't match) makes a
    # resume across the flag fall back to epoch-replay rather than trusting
    # a fingerprint that never recorded which path ran.
    # online_mode swaps the producer (finite file chain -> unbounded stream
    # with its own admission order), so a resume across the flag must never
    # trust a prior skip count — the list-LENGTH change guarantees that for
    # sidecars written before the flag existed too.
    # grad_accum_steps does NOT change which batches a step count covers
    # (state.step counts microbatches), but it changes which optimizer
    # trajectory produced the checkpoint, so a resume across the flag falls
    # back to epoch-replay via the list-LENGTH change rather than splicing
    # two different accumulation regimes mid-epoch.
    return [2, jax.process_count(), cfg.steps_per_loop,
            int(cfg.use_native_decoder), cfg.batch_size,
            cfg.shuffle_buffer, cfg.seed, int(cfg.drop_remainder),
            int(cfg.shuffle_files), cache_lib.MODES.index(cfg.decoded_cache),
            int(cfg.native_assembly), int(cfg.online_mode),
            cfg.grad_accum_steps]


def _resume_position(cfg: Config, restored_step: int,
                     files_digest: str = "",
                     health: Optional[guard_lib.TrainHealth] = None
                     ) -> Tuple[int, int, int]:
    """(epoch_base, start_epoch, skip_batches) for this invocation.

    The sidecar applies only when its ``step`` matches the restored
    checkpoint exactly (an async save that never became durable leaves a
    stale sidecar -> ignored, degrading to the reference's replay-the-epoch
    behavior). A cleanly-completed prior invocation advances ``epoch_base``
    so shuffle orders never repeat across resume-for-more-epochs runs; an
    interrupted invocation with the same num_epochs/pipe_mode resumes
    mid-epoch, skipping the batches already trained."""
    meta = (_read_resume_meta(cfg.model_dir, health=health)
            if cfg.model_dir else None)
    if not meta or not restored_step:
        return 0, 0, 0
    base = int(meta.get("epoch_base", 0))
    # Epochs whose shuffle order the recorded invocation may have touched.
    # A pipe-mode meta always records epoch 0 (its position is steps into
    # the stream) while the producer may have replayed up to num_epochs
    # orders, so count the full epoch budget there.
    touched = (int(meta.get("num_epochs", 0)) if meta.get("pipe_mode")
               else int(meta.get("epoch", 0)) + 1)
    if meta.get("step") != restored_step:
        # Stale sidecar (e.g. a lost async save): the position is unusable,
        # but the epoch_base is still valid knowledge — keep advancing the
        # shuffle seeds past every epoch any prior invocation touched.
        return base + touched, 0, 0
    if meta.get("completed"):
        return base + int(meta.get("num_epochs", 0)), 0, 0
    if (int(meta.get("num_epochs", -1)) == cfg.num_epochs
            and bool(meta.get("pipe_mode")) == bool(cfg.pipe_mode)
            and meta.get("layout") == _consumption_layout(cfg)
            and meta.get("files") == files_digest):
        return (base, int(meta.get("epoch", 0)),
                int(meta.get("steps_into_epoch", 0)))
    # Different invocation shape: start a fresh run but keep seeds moving.
    return base + touched, 0, 0


# _TensorBoardWriter moved to obs/tensorboard.py (imported above under its
# old name — tests monkeypatch ``tasks._TensorBoardWriter``).


def _task_train(trainer: Trainer, cfg: Config) -> Dict[str, float]:
    train_dir, eval_dir = resolve_channel_dirs(cfg)
    tr_files = resolve_files(train_dir, "tr")
    va_files = resolve_files(eval_dir, "va")
    if not tr_files and not cfg.online_mode:
        # Online mode tails the directory: starting before the first shard
        # arrives is the normal case, not an error.
        raise FileNotFoundError(f"no training tfrecords in {train_dir!r}")
    _validate_shard_coverage(cfg, tr_files)
    ulog.info(f"train dir={train_dir} files={len(tr_files)} "
              f"eval files={len(va_files)}")

    if cfg.clear_existing_model and cfg.model_dir:
        ckpt_lib.clear_model_dir(cfg.model_dir)  # chief-only rmtree
        if jax.process_count() > 1:
            # Barrier: no rank may construct its CheckpointManager (which
            # re-creates the dir) until the chief's delete has completed.
            from jax.experimental import multihost_utils  # noqa: PLC0415
            multihost_utils.sync_global_devices("clear_model_dir")

    mgr = None
    if cfg.model_dir:
        mgr = ckpt_lib.CheckpointManager(
            cfg.model_dir, max_to_keep=cfg.keep_checkpoint_max,
            save_interval_steps=cfg.save_checkpoints_steps,
            max_save_failures=cfg.max_save_failures,
            retry_policy=retry_lib.policy_from_config(cfg))
    state = _restore_or_init(trainer, cfg, require=False, mgr=mgr)

    # Runtime-resilience plumbing: ONE TrainHealth + guard for the whole run
    # (the skip/rollback budget spans rollback attempts) and the
    # process-wide preemption listener. A flag already set (a notice that
    # arrived during startup) is honored at the first dispatch.
    train_health = guard_lib.TrainHealth()
    guard = guard_lib.NonFiniteGuard.from_config(cfg, health=train_health)
    listener = preempt_lib.get_listener()

    # The resume decision is computed on the CHIEF ONLY and broadcast to all
    # ranks: a rank deciding from its own filesystem view (transient stat
    # failure, eventually-consistent object-store metadata, or a multi-path
    # private channel) could derive a divergent (epoch_base, start_epoch,
    # skip_batches) and desynchronize the lockstep collectives — a hang or
    # silent mis-training (ADVICE r4 high+medium). restored_step itself is
    # rank-consistent (all ranks restore the same global checkpoint).
    files_digest = ""
    if bootstrap.is_chief():
        # Online mode: the listing grows by design — a stable sentinel keeps
        # the resume gate from invalidating a replayable skip; the stream
        # sidecar (not the digest) carries WHAT-will-be-read exactness.
        files_digest = (_ONLINE_FILES_DIGEST if cfg.online_mode
                        else _files_fingerprint(cfg, tr_files))

    def _resume_for(restored_step: int) -> Tuple[int, int, int]:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils  # noqa: PLC0415
            pos = (_resume_position(cfg, restored_step, files_digest,
                                    health=train_health)
                   if bootstrap.is_chief() else (0, 0, 0))
            pos = multihost_utils.broadcast_one_to_all(
                np.asarray(pos, np.int64))
            epoch_base, start_epoch, skip_batches = (int(x) for x in pos)
        else:
            epoch_base, start_epoch, skip_batches = _resume_position(
                cfg, restored_step, files_digest, health=train_health)
        if start_epoch or skip_batches:
            ulog.info(f"step-accurate resume: epoch {start_epoch} "
                      f"(+{skip_batches} batches already trained), "
                      f"epoch_base={epoch_base}")
        return epoch_base, start_epoch, skip_batches

    # train_and_evaluate semantics (reference 1-ps-cpu/...py:440-442,
    # REQUIRED there per README-EN.md:36-38): mid-train eval no earlier than
    # eval_start_delay_secs, then at most every eval_throttle_secs. With both
    # 0 (default) the loop keeps the Horovod file-mode shape instead:
    # eval after every epoch (2-hvd-gpu/...py:390-394).
    eval_throttled = bool(va_files) and (
        cfg.eval_start_delay_secs > 0 or cfg.eval_throttle_secs > 0)

    result: Dict[str, float] = {}

    # Cross-epoch fault accounting: each pipeline (train AND eval) owns a
    # DataHealth; fold them into one total so the run reports exact
    # retry/skip counts (asserted by scripts/fault_drill.py).
    health_totals: Dict[str, int] = {}

    def _log_health(pipeline, where: str) -> None:
        health = getattr(pipeline, "health", None)
        if health is None:
            return
        if health.total_events:
            ulog.info(f"data health ({where}): {health.summary()}")
        health.merge_into(health_totals)

    def _run_eval(at_state: TrainState, where: str) -> Dict[str, float]:
        pipe = _eval_pipeline(cfg, va_files)
        ev = trainer.evaluate(at_state, pipe)
        _log_health(pipe, where)
        return ev

    tb = _TensorBoardWriter(cfg.tensorboard_dir)

    def _tb_log(step: int, loss: float, eps: float) -> None:
        tb.scalars(step, loss=loss, examples_per_sec=eps)

    def _tb_eval(ev: Dict[str, float], at_state: TrainState) -> None:
        tb.scalars(int(at_state.step), eval_auc=ev["auc"],
                   eval_loss=ev["loss"])

    def _tb_health(step: int) -> None:
        tb.scalars(step, **{f"health/{name}": float(v)
                            for name, v in train_health.snapshot().items()})

    def _log_train_health(where: str) -> None:
        if train_health.consume_dirty():
            ulog.info(f"train health ({where}): {train_health.summary()}")

    def _maybe_poison(pipeline):
        """Test seam: an armed NaN plan (utils.faults.set_nan_plan) wraps
        the pipeline once; the plan is consumed on pickup, so a rollback
        replay (or the next epoch) trains clean data."""
        plan = faults_lib.take_nan_plan()
        if plan is not None:
            return faults_lib.BatchPoisoner(pipeline, **plan)
        return pipeline

    def _env_steps(name: str) -> int:
        raw = os.environ.get(name, "").strip()
        try:
            return int(raw) if raw else 0
        except ValueError:
            raise ValueError(
                f"{name} must be an integer step count, got {raw!r}"
            ) from None

    # Fault injection (drill hooks): DEEPFM_TPU_FAULT_AFTER_STEPS=N kills
    # training after >= N optimizer steps, AFTER the checkpoint hook has
    # run — a deterministic spot-kill for exercising the crash-resume path
    # end-to-end (the reference had no fault injection; SURVEY.md §5).
    # PREEMPT_AFTER pulls the injectable preemption trigger instead (the
    # graceful path: force-save + exit 42); PREEMPT_HOLD writes a sentinel
    # file and blocks until a real signal arrives — scripts/preempt_drill.py
    # uses it to SIGTERM a live run at a deterministic step. Every rank
    # reads the same env via the launcher, so each fault is cluster-wide
    # like a real slice preemption.
    fault_after = _env_steps("DEEPFM_TPU_FAULT_AFTER_STEPS")
    preempt_after = _env_steps("DEEPFM_TPU_PREEMPT_AFTER_STEPS")
    hold_after = _env_steps("DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS")

    def _attempt(state: TrainState) -> TrainState:
        """One full training attempt: resume-position computation, hook
        stack, train loops, final forced save. A RollbackSignal (guard
        policy ``rollback``) aborts the attempt; the driver loop below
        restores the latest checkpoint and calls back in — the fresh
        ``_resume_for`` then replays from that checkpoint's recorded
        offset."""
        restored_step = int(state.step)
        epoch_base, start_epoch, skip_batches = _resume_for(restored_step)

        # Data-pipeline position for the resume sidecar; epoch_start is the
        # global step at which the current epoch's batch 0 was (or would
        # have been) trained, so steps_into_epoch == batches consumed this
        # epoch.
        progress = {"epoch": start_epoch,
                    "epoch_start": restored_step - skip_batches}

        def _meta(step: int, completed: bool) -> Dict:
            return {"step": step, "epoch": progress["epoch"],
                    "steps_into_epoch": step - progress["epoch_start"],
                    "epoch_base": epoch_base, "num_epochs": cfg.num_epochs,
                    "pipe_mode": int(cfg.pipe_mode),
                    "layout": _consumption_layout(cfg),
                    "files": files_digest, "completed": completed}

        # Online hot publishing: per attempt, so a rollback replay starts
        # with a clean in-flight ledger (the publish DIR persists — already-
        # published versions are skipped idempotently).
        publisher = None
        online_stream = [None]  # UnboundedFileStream handle for preempt wake
        if cfg.online_mode and (cfg.publish_every_steps
                                or cfg.publish_every_secs):
            pdir = cfg.publish_dir or (
                fileio.join(cfg.model_dir, "publish") if cfg.model_dir
                else "")
            if not pdir:
                raise ValueError("--publish_every_steps/secs needs "
                                 "--publish_dir or --model_dir")
            publisher = publish_lib.Publisher(
                trainer.model, cfg, pdir,
                every_steps=cfg.publish_every_steps,
                every_secs=cfg.publish_every_secs,
                timeout_s=cfg.publish_timeout_s,
                health=train_health)
            # Resumed runs cross the same publish boundaries a fresh run
            # would (the drill's version-set determinism rests on this).
            publisher.seed_cadence(restored_step)

        hooks = []
        # Host-side step counter: reading s.step would force a device sync
        # every step (it blocks on the async-dispatched update), collapsing
        # throughput — one sync at restore time instead. First hook, so
        # every later hook reads the post-dispatch count.
        step_counter = [restored_step]
        hooks.append(lambda s, m: step_counter.__setitem__(
            0, step_counter[0] + int(m.get("steps_done", 1))))

        if publisher is not None:
            # Cadence check + host snapshot + async submit; never blocks on
            # publish I/O. Also the wedged-publish watchdog (exit 43).
            hooks.append(lambda s, m: publisher.maybe_publish(
                s, step_counter[0]))

        last_saved = [-1]
        if mgr is not None:
            def ckpt_hook(s: TrainState, m) -> None:
                if mgr.should_save(step_counter[0]):
                    if mgr.save(step_counter[0], _ckpt_state(trainer, s)):
                        last_saved[0] = step_counter[0]
                        _write_resume_meta(
                            cfg.model_dir, _meta(step_counter[0], False))
            hooks.append(ckpt_hook)

        if preempt_after:
            def trigger_hook(s: TrainState, m) -> None:
                if step_counter[0] - restored_step >= preempt_after:
                    listener.trigger(
                        f"env trigger after "
                        f"{step_counter[0] - restored_step} steps")
            hooks.append(trigger_hook)

        if hold_after:
            held = [False]

            def hold_hook(s: TrainState, m) -> None:
                if held[0] or step_counter[0] - restored_step < hold_after:
                    return
                held[0] = True
                sentinel = fileio.join(cfg.model_dir or ".", ".preempt_hold")
                with fileio.open_stream(sentinel, "w") as f:
                    f.write(str(step_counter[0]))
                deadline = time.time() + 120.0
                while not listener.triggered():
                    if time.time() > deadline:
                        raise RuntimeError(
                            "preempt hold: no signal arrived within 120s")
                    time.sleep(0.05)
            hooks.append(hold_hook)

        # Preemption poll: once per dispatch single-process; multi-process
        # ranks consult their local flag only at the agreed _eval_check_due
        # dispatches and OR it across ranks, so every rank checkpoints and
        # raises at the SAME dispatch — the lockstep collectives stay
        # aligned (same pattern as the throttled-eval clock checks).
        pc_dispatch = [0]

        def preempt_hook(s: TrainState, m) -> None:
            pc_dispatch[0] += 1
            trig = listener.triggered()
            if jax.process_count() > 1:
                if not _eval_check_due(pc_dispatch[0]):
                    return
                from jax.experimental import multihost_utils  # noqa: PLC0415
                trig = bool(np.asarray(multihost_utils.process_allgather(
                    np.asarray([trig]))).any())
            if not trig:
                return
            step = step_counter[0]
            train_health.record_preemption()
            ulog.warning(
                f"preemption ({listener.reason or 'peer rank'}): force-"
                f"saving checkpoint at step {step}, then exiting with code "
                f"{preempt_lib.EXIT_PREEMPTED}")
            if mgr is not None:
                # An interval save may have just landed on this exact step
                # (mgr.save dedups); the resume sidecar makes the mid-epoch
                # position replay-exact on restart.
                mgr.save(step, _ckpt_state(trainer, s), force=True)
                _write_resume_meta(cfg.model_dir, _meta(step, False))
            if online_stream[0] is not None:
                online_stream[0].request_stop()  # wake a blocked poll wait
            if publisher is not None:
                # Drain the in-flight publish before exit 42: a published
                # artifact must never be abandoned half-staged by a graceful
                # preemption (a wedged one still trips the 43 watchdog).
                publisher.drain(timeout=cfg.publish_timeout_s or None)
            raise preempt_lib.Preempted(step, listener.reason)
        hooks.append(preempt_hook)

        if fault_after:
            def fault_hook(s: TrainState, m) -> None:
                if step_counter[0] - restored_step >= fault_after:
                    raise RuntimeError(
                        f"fault injection: simulated preemption after "
                        f"{step_counter[0] - restored_step} steps")
            hooks.append(fault_hook)

        tracer = prof_lib.StepWindowTracer(
            cfg.profile_dir, num_steps=cfg.profile_steps)
        hooks.append(lambda s, m: tracer.on_step(int(m.get("steps_done", 1))))
        # Online windowed eval: the throttled-eval machinery drives WHEN;
        # the evaluate override swaps the cumulative batch AUC for the
        # sliding-window streaming AUC (metrics.WindowedAuc).
        online_eval_fn = None
        if (cfg.online_mode and va_files
                and cfg.online_eval_window_steps > 0):
            if cfg.num_tasks > 1:
                window = metrics_lib.WindowedAucDict(
                    cfg.task_names, cfg.online_eval_window_steps,
                    num_bins=cfg.auc_num_thresholds)
            else:
                window = metrics_lib.WindowedAuc(
                    cfg.online_eval_window_steps,
                    num_bins=cfg.auc_num_thresholds)
            online_eval_fn = _make_online_eval(
                trainer, cfg, va_files, window, lambda: step_counter[0])
        if eval_throttled:
            hooks.append(_make_throttled_eval_hook(
                trainer, cfg, va_files, result, on_eval=_tb_eval,
                evaluate=(online_eval_fn
                          or (lambda s: _run_eval(s, "throttled eval")))))
        try:
            if cfg.pipe_mode:
                # Streaming (Pipe-mode analog): ONE train call consuming a
                # single-pass stream with all epochs replayed producer-side —
                # the reference pipe-mode shape (``2-hvd-gpu/...py:403-405``,
                # FIFO not reusable per epoch). Eval afterwards, file-mode.
                # Resume: the already-trained stream prefix is skipped
                # (epoch index stays 0 — position is steps into the stream).
                # online_mode swaps the finite file chain for the unbounded
                # directory watcher; the consumer is identical.
                if cfg.online_mode:
                    pipeline, ustream = make_online_pipeline(
                        cfg, train_dir, skip_batches=skip_batches)
                    online_stream[0] = ustream
                    pipeline = _maybe_poison(pipeline)
                else:
                    pipeline = _maybe_poison(make_streaming_pipeline(
                        cfg, tr_files, epochs=cfg.num_epochs,
                        skip_batches=skip_batches, epoch_offset=epoch_base))
                state, fit_m = trainer.fit(state, pipeline, hooks=hooks,
                                           on_log=_tb_log, guard=guard)
                _log_health(pipeline, "stream end")
                _log_train_health("stream end")
                if fit_m["steps"]:
                    result["loss"] = fit_m["loss"]
                    result["examples_per_sec"] = fit_m.get(
                        "examples_per_sec", 0.0)
                    result.update(
                        {k: v for k, v in fit_m.items()
                         if k.startswith(("staging_", "collective_"))})
                if publisher is not None:
                    # Stream ended (idle timeout / stop): force one final
                    # publish at the terminal step. Deterministic — both an
                    # interrupted-and-resumed run and a clean run end at the
                    # same step over the same admitted shards, so the drill
                    # always has a common version to bit-compare.
                    publisher.drain(timeout=cfg.publish_timeout_s or None)
                    final_step = step_counter[0]
                    if final_step and final_step not in publisher.published:
                        publisher.publish_now(state, final_step)
                        publisher.drain(
                            timeout=cfg.publish_timeout_s or None)
                    pub_stats = publisher.stats()
                    result.update(pub_stats)
                    # Publisher scalars ride the same TB writer as training
                    # loss/eval (obs.tensorboard) — one place to look.
                    tb.scalar_dict(final_step, "publish/", pub_stats)
                if va_files:
                    ev = (online_eval_fn(state) if online_eval_fn is not None
                          else _run_eval(state, "stream eval"))
                    ulog.info(f"streaming train done: eval auc={ev['auc']:.5f} "
                              f"loss={ev['loss']:.5f}")
                    result.update({"auc": ev["auc"], "eval_loss": ev["loss"],
                                   "eval_examples_per_sec":
                                       ev["examples_per_sec"]})
                    result.update({k: v for k, v in ev.items()
                                   if k.startswith("auc_")})
                    if "window_examples" in ev:  # online windowed AUC
                        result["window_examples"] = ev["window_examples"]
                    _tb_eval(ev, state)
            else:
                for epoch in range(start_epoch, cfg.num_epochs):
                    # Per-epoch loop in the driver, per the reference's
                    # file-mode shape (``2-hvd-gpu/...py:390-394``). The
                    # epoch index (offset by epoch_base across invocations)
                    # feeds the shuffle seed so each epoch sees a fresh
                    # order (tf.data reshuffle_each_iteration analog) —
                    # which is also what makes mid-epoch resume exact: the
                    # resumed epoch replays the identical permutation and
                    # skips the already-trained prefix.
                    progress["epoch"] = epoch
                    progress["epoch_start"] = step_counter[0] - (
                        skip_batches if epoch == start_epoch else 0)
                    pipeline = _maybe_poison(make_pipeline(
                        cfg, tr_files, epochs=1, shuffle=True,
                        epoch_offset=epoch_base + epoch,
                        skip_batches=(skip_batches if epoch == start_epoch
                                      else 0)))
                    state, fit_m = _fit_epoch(trainer, cfg, state, pipeline,
                                              hooks, _tb_log, guard=guard)
                    _log_health(pipeline, f"epoch {epoch + 1} end")
                    _log_train_health(f"epoch {epoch + 1}")
                    if fit_m["steps"]:
                        # (a fully-skipped resumed epoch reports no loss)
                        result["loss"] = fit_m["loss"]
                        result["examples_per_sec"] = fit_m.get(
                            "examples_per_sec", 0.0)
                        result.update(
                            {k: v for k, v in fit_m.items()
                             if k.startswith(("staging_", "collective_"))})
                    if (mgr is not None and last_saved[0] == step_counter[0]
                            and epoch + 1 < cfg.num_epochs):
                        # A checkpoint landed exactly on this epoch's last
                        # step: roll the sidecar to the next epoch so resume
                        # starts there instead of decode-skipping a fully
                        # trained epoch.
                        progress["epoch"] = epoch + 1
                        progress["epoch_start"] = step_counter[0]
                        _write_resume_meta(
                            cfg.model_dir, _meta(step_counter[0], False))
                    if va_files and not eval_throttled:
                        ev = _run_eval(state, f"epoch {epoch + 1} eval")
                        ulog.info(
                            f"epoch {epoch + 1}/{cfg.num_epochs}: eval auc="
                            f"{ev['auc']:.5f} loss={ev['loss']:.5f}")
                        result.update({"auc": ev["auc"], "eval_loss": ev["loss"],
                                       "eval_examples_per_sec":
                                           ev["examples_per_sec"]})
                        result.update({k: v for k, v in ev.items()
                                       if k.startswith("auc_")})
                        _tb_eval(ev, state)
                if va_files and eval_throttled:
                    # Final eval at completion (train_and_evaluate does one).
                    ev = _run_eval(state, "final eval")
                    ulog.info(f"final eval: auc={ev['auc']:.5f} "
                              f"loss={ev['loss']:.5f}")
                    result.update({"auc": ev["auc"], "eval_loss": ev["loss"],
                                   "eval_examples_per_sec":
                                       ev["examples_per_sec"]})
                    result.update({k: v for k, v in ev.items()
                                   if k.startswith("auc_")})
                    _tb_eval(ev, state)
        finally:
            tracer.close()
            if publisher is not None:
                publisher.close()
            if online_stream[0] is not None:
                online_stream[0].close()
                online_stream[0] = None
        if mgr is not None:
            final_step = int(state.step)
            mgr.save(final_step, _ckpt_state(trainer, state), force=True)
            _write_resume_meta(cfg.model_dir, _meta(final_step, True))
        return state

    try:
        while True:
            try:
                state = _attempt(state)
                break
            except guard_lib.RollbackSignal as rs:
                # on_nonfinite=rollback: restore the latest checkpoint and
                # replay from its recorded offset. The guard's shared event
                # budget (max_rollbacks, spanning skips AND rollbacks)
                # already bounded how often we can get here — a run whose
                # data keeps poisoning the same step exhausts it and aborts.
                if mgr is None or mgr.latest_step() is None:
                    raise guard_lib.NonFiniteError(
                        f"rollback requested at step {rs.step} but no "
                        f"checkpoint exists to roll back to (set model_dir "
                        f"or use on_nonfinite=skip)") from rs
                train_health.record_rollback()
                mgr.wait()  # an async interval save may still be landing
                state = mgr.restore(trainer.init_state())
                ulog.warning(
                    f"rolled back: restored checkpoint step "
                    f"{int(state.step)} after non-finite at step {rs.step}; "
                    f"replaying from the recorded offset")
        _log_train_health("run end")
        _tb_health(int(state.step))
    finally:
        tb.close()
        if mgr is not None:
            mgr.close()

    if cfg.servable_model_dir and bootstrap.is_chief():
        out = fileio.join(cfg.servable_model_dir, str(int(state.step)))
        export_lib.export_serving(
            trainer.model, _servable_state(trainer, state), cfg, out)
    result["steps"] = float(int(state.step))
    result["read_retries"] = float(health_totals.get("read_retries", 0))
    result["bad_records"] = float(health_totals.get("bad_records", 0))
    for name, v in train_health.snapshot().items():
        result[name] = float(v)
    return result


def _task_eval(trainer: Trainer, cfg: Config) -> Dict[str, float]:
    _, eval_dir = resolve_channel_dirs(cfg)
    va_files = resolve_files(eval_dir, "va")
    if not va_files:
        raise FileNotFoundError("no eval tfrecords found")
    state = _restore_or_init(trainer, cfg, require=True)
    ev = trainer.evaluate(state, _eval_pipeline(cfg, va_files))
    ulog.info(f"eval: auc={ev['auc']:.5f} loss={ev['loss']:.5f}")
    return ev


def _interleave_rank_shards(gathered: np.ndarray, counts: np.ndarray
                            ) -> np.ndarray:
    """Reassemble global record order from per-rank record-sharded results:
    rank r held records r, r+world, r+2*world, ... so global index
    ``i * world + r`` maps to ``gathered[r, i]``. Trailing dims (per-task
    probability columns) carry through unchanged."""
    world = gathered.shape[0]
    out = np.empty((int(counts.sum()),) + gathered.shape[2:],
                   dtype=gathered.dtype)
    for r in range(world):
        n = int(counts[r])
        out[r:(n - 1) * world + r + 1:world] = gathered[r, :n]
    return out


def _task_infer(trainer: Trainer, cfg: Config) -> Dict[str, float]:
    te_files = resolve_files(cfg.val_data_dir or cfg.data_dir, "te")
    if not te_files:
        raise FileNotFoundError("no inference tfrecords found")
    state = _restore_or_init(trainer, cfg, require=True)
    world = jax.process_count()
    rank = jax.process_index()
    local_bs = _local_batch_size(cfg)
    files = tuple(sorted(te_files))
    # Record-level shard: each process predicts every world-th record (wall
    # clock ~1/world of the set) and the chief re-interleaves global order
    # before writing. (The reference had every worker predict the full set,
    # :445-449 — O(world) redundant compute at pod scale.)
    shard = shard_lib.ShardSpec(
        files, record_shard=(world, rank) if world > 1 else None)
    pipeline = pipe_lib.CtrPipeline(
        files, field_size=cfg.field_size, batch_size=local_bs, num_epochs=1,
        shuffle=False, shuffle_files=False, drop_remainder=False,
        seed=cfg.seed, shard=shard, prefetch_batches=cfg.prefetch_batches,
        use_native_decoder=cfg.use_native_decoder,
        reader_threads=cfg.reader_threads, verify_crc=cfg.verify_crc,
        num_labels=cfg.num_tasks, **_fault_tolerance_kwargs(cfg))

    # Collectives inside predict_step require every process to run the same
    # number of rounds, but per-rank record counts can differ by one. Rather
    # than a full counting pre-pass over the data (2x I/O), ranks advance in
    # lockstep rounds (Trainer.lockstep_batches — the same mechanism eval
    # uses); an exhausted rank feeds dummy batches whose output is discarded.
    # Batches are padded to the compiled shape and STREAMED through
    # Trainer.predict, which groups steps_per_loop of them into one stacked
    # transfer + one scanned program (VERDICT r3 #2 — previously one
    # program per batch). ``real_rows`` records each fed batch's true row
    # count; predict preserves per-batch yield order, and it only runs
    # ahead of the consumer by one group, so the list index is always
    # populated before its output arrives.
    probs: List[np.ndarray] = []
    n_local = 0
    real_rows: List[int] = []
    if world > 1:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        from .loop import zero_batch  # noqa: PLC0415

        def make_dummy():
            return zero_batch(cfg.field_size, local_bs,
                              num_labels=cfg.num_tasks)

        def feed():
            # Lockstep rounds keep every rank's fed-stream length identical
            # (dummies where a shard is exhausted), so predict's k-grouping
            # — and therefore its program sequence — aligns across ranks.
            for batch, real in trainer.lockstep_batches(pipeline, make_dummy):
                n = batch["label"].shape[0] if real else 0
                real_rows.append(n)
                yield (pad_batch(batch, local_bs)
                       if real and n < local_bs else batch)

        for i, p in enumerate(trainer.predict(state, feed())):
            n = real_rows[i]
            if n:
                probs.append(p[:n])
                n_local += n
        counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([n_local]))).reshape(-1)
    else:

        def feed():
            for batch in pipeline:
                n = batch["label"].shape[0]
                real_rows.append(n)
                yield (pad_batch(batch, local_bs)  # pad tail, trim after
                       if n < local_bs else batch)

        for i, p in enumerate(trainer.predict(state, feed())):
            n = real_rows[i]
            n_local += n
            probs.append(p[:n])
    # Single-task probs are [n]; multitask [n, T] (one column per task, in
    # cfg.task_names order).
    tail = (cfg.num_tasks,) if cfg.num_tasks > 1 else ()
    local = (np.concatenate(probs) if probs
             else np.zeros((0,) + tail, np.float32)).astype(np.float32)

    if world > 1:
        padded = np.zeros((max(int(counts.max()), 1),) + tail, np.float32)
        padded[:len(local)] = local
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        all_probs = _interleave_rank_shards(gathered, counts)
    else:
        all_probs = local

    out_path = fileio.join(cfg.val_data_dir or cfg.data_dir, "pred.txt")
    if bootstrap.is_chief():
        with fileio.open_stream(out_path, "w") as f:
            # One line per record (ref :447-449); multitask writes one
            # space-separated column per task.
            for p in all_probs:
                row = np.atleast_1d(p)
                f.write(" ".join(f"{float(v):.6f}" for v in row) + "\n")
        ulog.info(f"wrote {len(all_probs)} predictions to {out_path}")
    return {"num_predictions": float(len(all_probs))}


def _task_export(trainer: Trainer, cfg: Config) -> Dict[str, float]:
    if not cfg.servable_model_dir:
        raise ValueError("export task requires servable_model_dir")
    state = _restore_or_init(trainer, cfg, require=True)
    if bootstrap.is_chief():
        out = fileio.join(cfg.servable_model_dir, str(int(state.step)))
        export_lib.export_serving(
            trainer.model, _servable_state(trainer, state), cfg, out)
    return {"step": float(int(state.step))}
