"""Train state pytree: params + optimizer state + model (BN) state + PRNG."""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray          # int32 scalar (global_step analog)
    params: Any
    opt_state: Any
    model_state: Any           # BatchNorm running stats etc.
    rng: jax.Array             # base PRNG key; per-step keys are folded in

    @classmethod
    def create(cls, params: Any, opt_state: Any, model_state: Any,
               rng: jax.Array) -> "TrainState":
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=opt_state, model_state=model_state, rng=rng)
