from . import loop, metrics, optimizers  # noqa: F401
from .loop import Trainer  # noqa: F401
from .state import TrainState  # noqa: F401
