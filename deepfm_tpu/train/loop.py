"""Trainer: jitted/shard_mapped train-eval-predict step functions + fit loop.

TPU-native replacement for the reference's Estimator driver (L3):

  * One *synchronous SPMD* mechanism replaces both reference backends: the
    step function is ``shard_map``-ped over the ``('data','model')`` mesh —
    gradients are ``pmean``-ed over 'data' (vs Horovod's NCCL ring allreduce,
    X2) and embedding lookups are masked-gather + ``psum`` over 'model'
    row-shards (vs the gRPC parameter server, X1). On one device it's a plain
    ``jax.jit``.
  * Replicated initialization from one PRNG key == Horovod's
    ``BroadcastGlobalVariablesHook(0)`` (reference 2-hvd-gpu/...py:372).
  * Everything under jit is static-shaped; one compiled program per task.

The fit loop feeds host batches via ``jax.make_array_from_process_local_data``
(multi-host-correct) and logs loss/examples-per-sec every ``log_steps``
(reference flag :47).
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.5: experimental location, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # check_rep's static replication inference predates check_vma's and
        # rejects valid pmean-replicated outputs; disable rather than fail.
        del check_vma
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)

from ..config import Config
from ..models import get_model
from ..obs import trace as trace_lib
from ..ops import embedding as emb_ops
from ..ops import pallas_embedding as pemb
from ..parallel import mesh as mesh_lib
from ..utils import logging as ulog
from ..utils import profiling as prof_lib
from . import guard as guard_lib
from . import metrics as metrics_lib
from . import optimizers as opt_lib
from .state import TrainState


def pad_batch(batch: Dict[str, np.ndarray], bs: int) -> Dict[str, np.ndarray]:
    """Pad a short tail batch up to the compiled shape by repeating the last
    row. Callers either trim the padded rows from the output (predict) or
    mask them with a zero weight (evaluate)."""
    n = batch["label"].shape[0]
    pad = bs - n
    return {k: np.concatenate([v, np.tile(v[-1:], (pad,) + (1,) * (v.ndim - 1))])
            for k, v in batch.items()}


def zero_batch(field_size: int, bs: int, num_labels: int = 1,
               hist_len: int = 0) -> Dict[str, np.ndarray]:
    """All-zero batch with the canonical CTR schema — the single source of
    the batch keys/dtypes for dummy (lockstep filler) batches. Multi-task
    runs carry a second label column (``label2``); history runs carry the
    fixed-shape ``hist_ids``/``hist_mask`` pair (all-masked here, so the
    attention blocks see an empty history)."""
    batch = {
        "feat_ids": np.zeros((bs, field_size), np.int32),
        "feat_vals": np.zeros((bs, field_size), np.float32),
        "label": np.zeros((bs, 1), np.float32),
    }
    if num_labels > 1:
        batch["label2"] = np.zeros((bs, 1), np.float32)
    if hist_len > 0:
        batch["hist_ids"] = np.zeros((bs, hist_len), np.int32)
        batch["hist_mask"] = np.zeros((bs, hist_len), np.float32)
    return batch


def _with_weight(batch: Dict[str, np.ndarray], bs: int) -> Dict[str, np.ndarray]:
    """Attach a per-row validity weight and pad to the compiled batch shape.
    Real rows weigh 1, padding weighs 0 — the weights flow into the AUC
    histograms and the loss sum, so tail records count exactly once and
    padding not at all."""
    n = batch["label"].shape[0]
    bs = max(bs, n)  # oversize batches pass through un-padded (jit re-specializes)
    w = np.zeros((bs, 1), np.float32)
    w[:n] = 1.0
    if n < bs:
        batch = pad_batch(batch, bs)
    return {**batch, "weight": w}


def _staged_records(args) -> int:
    """Record count of a staged transfer's host-side payload (batch dict or
    list of batch dicts); 0 for layouts without a 'label' column (columnar
    input-service rows) — the synthetic stall then leaves them alone."""
    for a in args:
        if isinstance(a, dict) and "label" in a:
            return int(a["label"].shape[0])
        if isinstance(a, (list, tuple)) and a and isinstance(a[0], dict):
            return sum(int(b["label"].shape[0]) for b in a
                       if isinstance(b, dict) and "label" in b)
    return 0


class _StagingRing:
    """Bounded device staging area: at most ``n_slots`` superbatches may be
    transferred ahead of the dispatches that consume them (TUNING §2.13).

    The staging thread calls :meth:`put` around each host->device transfer;
    the fit loop calls :meth:`retire` with a device value from each dispatch
    (its readiness marks that dispatch complete ON DEVICE). Transfer j
    fences on dispatch j - n_slots: with 2 slots dispatch k+1's transfer
    runs while dispatch k computes (double buffering), with 1 slot every
    transfer waits out the previous dispatch — H2D serializes with compute
    (the A/B baseline, and the memory floor when two staged superbatches
    don't fit). Purely a scheduling constraint: the trajectory is
    bit-identical across slot counts.

    Also the overlap instrument: ``transfer_s`` is time inside transfers,
    ``wait_s`` time blocked on fences — ``overlap_fraction`` is the share
    of staging time doing useful transfer work (1.0 = never fenced).
    """

    # Test/bench-only: inflate each transfer by N ns per staged record. On
    # the CPU backend the host->device "transfer" is a core-local copy too
    # cheap to measure, so the 1-vs-2-slot A/B has nothing to overlap; the
    # synthetic stall stands in for a real PCIe/DMA leg (same spirit as the
    # pipeline's DEEPFM_TPU_SYNTH_HOST_NS_PER_RECORD). Never set in
    # production.
    SYNTH_TRANSFER_ENV = "DEEPFM_TPU_SYNTH_TRANSFER_NS_PER_RECORD"

    def __init__(self, n_slots: int):
        self.n_slots = max(int(n_slots), 1)
        self._fences: "queue.Queue[Any]" = queue.Queue()
        self._closed = threading.Event()
        self._staged = 0
        self.transfer_s = 0.0
        self.wait_s = 0.0
        self._synth_ns = int(os.environ.get(self.SYNTH_TRANSFER_ENV, "0"))

    def put(self, transfer: Callable[[], Any], n_records: int = 0) -> Any:
        """Run one transfer under the slot discipline (staging thread)."""
        self._staged += 1
        if self._staged > self.n_slots:
            with trace_lib.span("stage.wait", slot=self._staged):
                t0 = time.time()
                fence = None
                # Poll against close so an abandoned fit (exception, early
                # return) can never strand the staging thread on this queue.
                while not self._closed.is_set():
                    try:
                        fence = self._fences.get(timeout=0.1)
                        break
                    except queue.Empty:
                        continue
                if fence is not None:
                    jax.block_until_ready(fence)
                self.wait_s += time.time() - t0
        with trace_lib.span("stage.transfer", records=n_records):
            t0 = time.time()
            out = transfer()
            if self._synth_ns and n_records:
                time.sleep(self._synth_ns * n_records * 1e-9)
            self.transfer_s += time.time() - t0
        return out

    def retire(self, fence: Any) -> None:
        """Mark one dispatch's slot reusable once ``fence`` is ready
        (fit thread; the fence is any device value the dispatch produced)."""
        self._fences.put(fence)

    def close(self) -> None:
        self._closed.set()

    def overlap_fraction(self) -> float:
        total = self.transfer_s + self.wait_s
        return 1.0 if total <= 0 else self.transfer_s / total


class Trainer:
    """Builds and runs the compiled train/eval/predict step functions."""

    def __init__(self, cfg: Config, mesh_info: Optional[mesh_lib.MeshInfo] = None):
        self.cfg = cfg
        self.model = get_model(cfg)
        # Multi-task contract: the model emits [B, T] logits and owns the
        # per-task loss combination; single-task models keep the legacy [B]
        # path byte-for-byte (bit-exactness tests pin it).
        self._task_names = tuple(getattr(self.model, "task_names", ("ctr",)))
        self._multitask = len(self._task_names) > 1
        self.mesh_info = mesh_info if mesh_info is not None else mesh_lib.build_mesh(cfg)
        self.tx = opt_lib.build_optimizer(cfg, world_size=self.mesh_info.data_size)
        self._specs: Optional[Dict[str, Any]] = None
        self._train_step: Optional[Callable] = None
        self._multi_step: Optional[Callable] = None
        self._eval_step: Optional[Callable] = None
        self._eval_multi_step: Optional[Callable] = None
        self._predict_step: Optional[Callable] = None
        self._predict_multi_step: Optional[Callable] = None
        # Device-resident dataset mode: uploaded columns keyed by the
        # decoded-cache fingerprint, and one compiled program per
        # (steps, batch) shape.
        self._dd_cols: Optional[Tuple[str, Dict[str, jax.Array]]] = None
        self._dd_programs: Dict[Tuple[int, int], Callable] = {}
        # on_nonfinite=skip must keep the pre-dispatch state alive to drop a
        # poisoned update, so the step programs cannot donate their input
        # state buffer under that policy (the cost of the safety net; see
        # TUNING §2.8).
        self._donate_state = cfg.on_nonfinite != "skip"
        # Injectable watchdog abort (tests); None = os._exit(EXIT_WATCHDOG).
        self.watchdog_abort: Optional[Callable[[str], None]] = None
        # Sparse (touched-rows-only) embedding updates. Two legs: the
        # single-device jit path, and — with --embedding_shard rows — the
        # row-exchange mesh program (_sharded_sparse_step_impl), where
        # tables and Adam moments live sharded over 'model' and grads sync
        # over 'data' in owner-local table space. A mesh WITHOUT the rows
        # plane still falls back to dense: replicated tables with
        # per-shard sparse plans would desync.
        self.sparse_embed = cfg.embedding_update == "sparse"
        self._shard_rows = cfg.embedding_shard == "rows"
        if (self.sparse_embed and self.mesh_info.mesh is not None
                and not self._shard_rows):
            ulog.warning(
                "embedding_update=sparse under a mesh needs the row "
                "exchange plane (--embedding_shard rows) -> falling back "
                "to dense embedding updates")
            self.sparse_embed = False
        self._embed_names = tuple(self.model.embedding_param_names())
        # Embedding rows follow the same world-LR rule as the optax base
        # optimizer (opt_lib.build_optimizer).
        self._sparse_lr = cfg.learning_rate
        if cfg.scale_lr_by_world and self.mesh_info.data_size > 1:
            self._sparse_lr = cfg.learning_rate * self.mesh_info.data_size
        # Kernel-leg selection for the sparse embedding plane (see
        # ops.pallas_embedding): "off" is the kill switch that also
        # disables the fused one-leaf backward below.
        self._emb_kernels = cfg.embedding_kernels
        # Hot/cold tiered embedding storage (requires the sparse path).
        self._tier: Optional[Any] = None
        if cfg.embedding_tiering == "hot_cold":
            if not self.sparse_embed:
                raise ValueError(
                    "embedding_tiering=hot_cold requires the sparse "
                    "single-device update path (a mesh forced the dense "
                    "fallback)")
            from ..data import hot_cold  # noqa: PLC0415
            self._tier = hot_cold.TieredEmbeddingRuntime(cfg, self.model)
        # Gradient accumulation factor (config-validated; 1 = off). The
        # scanned dispatch regroups its K microbatches into K//a optimizer
        # applies plus K%a single-microbatch full steps for ragged tails.
        self._accum = max(cfg.grad_accum_steps, 1)
        # DCN-aware two-stage gradient reduction over 'data': derived from
        # the mesh's host layout — None on single-host meshes (every
        # virtual mesh included) and on layouts that don't decompose into
        # equal per-host blocks. Tests override this seam to exercise the
        # hierarchical program on a single-host virtual mesh.
        self._hier_groups = mesh_lib.data_axis_host_groups(self.mesh_info)
        # Active fit's device staging ring (slot fence + overlap timing);
        # None outside fit so eval/predict transfers pass through untouched.
        self._ring: Optional[_StagingRing] = None
        self._grad_bytes_cache: Optional[int] = None

    # ------------------------------------------------------------------
    # State creation / placement
    # ------------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None, *,
                   tiered: bool = True) -> TrainState:
        """Replicated-by-construction init: every process derives identical
        params from the same seed (broadcast-hook analog).

        ``tiered=False`` skips hot/cold adoption and returns the DENSE
        state — the restore template for tiered runs, whose checkpoints are
        written densified (``TieredEmbeddingRuntime.checkpoint_state``).
        The caller restores into it, then calls ``self._tier.adopt``."""
        seed = self.cfg.seed if seed is None else seed
        rng = jax.random.PRNGKey(seed)
        k_init, k_state = jax.random.split(rng)
        params, model_state = self.model.init(k_init)
        opt_state = self._init_opt_state(params)
        state = TrainState.create(params, opt_state, model_state, k_state)
        state = self._place(state)
        if tiered and self._tier is not None:
            state = self._tier.adopt(state)
        return state

    def _init_opt_state(self, params) -> Any:
        """Dense: the optax state over all params. Sparse: the optax state
        over the NON-embedding params plus per-table lazy-Adam slots
        (m/v/tau) and one global step counter for the embeddings."""
        if not self.sparse_embed:
            return self.tx.init(params)
        rest = {k: v for k, v in params.items()
                if k not in self._embed_names}
        embed = {
            name: {k: opt_lib.embed_adam_init(t)
                   for k, t in self.model.emb.tables(params[name]).items()}
            for name in self._embed_names}
        return {"base": self.tx.init(rest), "embed": embed,
                "count": jnp.zeros((), jnp.int32)}

    def _state_specs(self, state: TrainState) -> TrainState:
        param_specs = mesh_lib.param_pspecs(
            state.params, self.model.embedding_param_names(),
            self.mesh_info.model_size)
        if self.sparse_embed:
            # Sparse opt layout {"base", "embed", "count"}: the lazy-Adam
            # m/v mirror their table's spec; tau is a [rows] int vector
            # that shards with the rows — opt_state_pspecs's shape
            # matching would only catch it by accidental collision with a
            # 1-D param, so the layout is spelled out here.
            emb = self.model.emb
            rest = {k: v for k, v in state.params.items()
                    if k not in self._embed_names}
            rest_specs = {k: param_specs[k] for k in rest}
            row_spec = (P(mesh_lib.MODEL_AXIS)
                        if self.mesh_info.model_size > 1 else P())
            embed_specs = {
                name: {key: opt_lib.EmbedAdamEntry(m=s, v=s, tau=row_spec)
                       for key, s in emb.tables(param_specs[name]).items()}
                for name in self._embed_names}
            opt_specs = {
                "base": mesh_lib.opt_state_pspecs(
                    state.opt_state["base"], rest, rest_specs),
                "embed": embed_specs,
                "count": P(),
            }
        else:
            opt_specs = mesh_lib.opt_state_pspecs(
                state.opt_state, state.params, param_specs)
        mstate_specs = jax.tree.map(lambda _: P(), state.model_state)
        return TrainState(
            step=P(), params=param_specs, opt_state=opt_specs,
            model_state=mstate_specs, rng=P())

    def _place(self, state: TrainState) -> TrainState:
        """Apply NamedShardings (row-sharded embeddings, replicated rest)."""
        mi = self.mesh_info
        if mi.mesh is None:
            return jax.device_put(state)
        specs = self._state_specs(state)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, mi.sharding(s)), state, specs)

    def put_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        """Host numpy batch -> device array sharded over the data axis.

        Under multi-process each process passes its local shard of the global
        batch; ``make_array_from_process_local_data`` assembles the global
        array (the pod-sharded tf.data->device-iterator analog, X3)."""
        mi = self.mesh_info
        if mi.mesh is None:
            return jax.device_put(batch)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                mi.sharding(P(mesh_lib.DATA_AXIS, *([None] * (x.ndim - 1)))), x),
            dict(batch))

    # ------------------------------------------------------------------
    # Step functions
    # ------------------------------------------------------------------
    def _per_example_loss(self, logits, labels):
        """Per-example loss by cfg.loss_type — the ONE place the loss_type
        branch lives (train takes the mean; eval the weighted sum). Multi-
        task models own their weighted per-task combination ([B,T] -> [B])."""
        if self._multitask:
            return self.model.per_example_loss(logits, labels)
        if self.cfg.loss_type == "log_loss":
            return optax.sigmoid_binary_cross_entropy(logits, labels)
        return jnp.square(jax.nn.sigmoid(logits) - labels)  # square_loss

    def _batch_labels(self, batch):
        """[B] labels (single-task, legacy path) or the [B,T] label matrix:
        task 0 reads ``label``, task 1 the ``label2`` column."""
        if not self._multitask:
            return batch["label"].reshape(-1).astype(jnp.float32)
        cols = [batch["label"].reshape(-1), batch["label2"].reshape(-1)]
        return jnp.stack(cols[:len(self._task_names)],
                         axis=1).astype(jnp.float32)

    def _hist_kwargs(self, batch):
        """hist_ids/hist_mask forwarding for sequence models: only when the
        model opts in (``uses_history``) AND the batch carries the columns
        (zoo/dummy batches don't — the models then default to an empty
        history). Trace-time pytree-key check, jit-safe."""
        if getattr(self.model, "uses_history", False) and "hist_ids" in batch:
            return {"hist_ids": batch["hist_ids"],
                    "hist_mask": batch["hist_mask"]}
        return {}

    def _loss_terms(self, params, model_state, batch, *, train, rng,
                    shard_axis, data_axis):
        logits, new_mstate = self.model.apply(
            params, model_state, batch["feat_ids"], batch["feat_vals"],
            train=train, rng=rng, shard_axis=shard_axis, data_axis=data_axis,
            **self._hist_kwargs(batch))
        labels = self._batch_labels(batch)
        xent = jnp.mean(self._per_example_loss(logits, labels))
        return logits, xent, new_mstate

    def _step_impl(self, state: TrainState, batch, *, data_axis, shard_axis
                   ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        """One optimizer step (raw, mesh-axis-aware; wrapped by jit/shard_map
        in _make_train_step and scanned in _make_train_multi_step)."""
        if self.sparse_embed:
            if data_axis is None and shard_axis is None:
                return self._sparse_step_impl(state, batch)
            return self._sharded_sparse_step_impl(
                state, batch, data_axis=data_axis, shard_axis=shard_axis)
        rng = jax.random.fold_in(state.rng, state.step)
        if data_axis is not None:
            # Distinct dropout per data shard; identical across model
            # shards (keeps activations replicated over 'model').
            rng = jax.random.fold_in(rng, jax.lax.axis_index(data_axis))

        def loss_fn(params):
            _, xent, new_mstate = self._loss_terms(
                params, state.model_state, batch, train=True, rng=rng,
                shard_axis=shard_axis, data_axis=data_axis)
            if data_axis is not None and self._hier_groups is None:
                # THE gradient sync point: the loss is made a *global*
                # scalar (mean over the data axis); differentiating it
                # under shard_map's replication-aware AD yields gradients
                # with the cross-replica psum already inserted by XLA —
                # this replaces hvd.DistributedOptimizer's NCCL allreduce
                # (2-hvd-gpu/...py:262) and the PS push/pull (X1).
                xent = jax.lax.pmean(xent, data_axis)
            l2 = self.model.l2_loss(params)
            if shard_axis is not None:
                # l2 over the full row-sharded table (invariant scalar).
                l2 = jax.lax.psum(l2, shard_axis)
            return xent + l2, (xent, l2, new_mstate)

        (_, (xent, l2, new_mstate)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if data_axis is not None and self._hier_groups is not None:
            # Hierarchical sync point (TUNING §2.13): the loss stayed
            # per-shard above, so the raw grads carry no psum; average
            # them intra-host then inter-host — the DCN stage moves 1/L
            # of the flat-ring traffic (L = data rows per host). The l2
            # component is shard-invariant over 'data', so averaging it
            # too is a no-op up to reassociation.
            grads = mesh_lib.hierarchical_pmean(
                grads, data_axis, self._hier_groups,
                self.mesh_info.data_size)
            xent = jax.lax.pmean(xent, data_axis)  # metrics only
        # Structural guarantee: padded_vocab pad rows never receive a
        # gradient (they are zero already — unreachable ids, masked l2 —
        # so this is bit-neutral; the regression test pins it).
        grads = {**grads, **{
            n: self.model.emb.mask_pad_grads(grads[n], axis_name=shard_axis)
            for n in self._embed_names}}
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            model_state=new_mstate)
        return new_state, {"loss": xent + l2, "xent": xent}

    # -- sparse-plane helpers (fused vocab-space backward) --------------
    def _use_fused_backward(self) -> bool:
        """The fused formulation differentiates the [B, F, D] BATCH VIEWS
        of each embedding table (a direct gather — no plan, no inverse
        remap), accumulates all names' cotangents plus an occupancy column
        in ONE table-shaped scatter-add, and applies lazy Adam as a masked
        table-space sweep (optimizers.sparse_adam_masked). Structurally
        that is the dense step's cost profile with lazy-Adam semantics —
        it needs the monolithic layout, same-height 2-D tables, and a
        table small enough to sweep; ``--embedding_kernels off`` is the
        kill switch back to the plan-based seed formulation."""
        return self._emb_kernels != "off" and not self.model.emb.hashed

    def _fused_tables_ok(self, tabs: Dict[str, jax.Array]) -> bool:
        heights = {t.shape[0] for t in tabs.values()}
        return (len(heights) == 1
                and all(t.ndim in (1, 2) for t in tabs.values())
                and heights.pop() <= pemb.PLAN_COUNT_MAX_ROWS)

    def _fused_grad_ext(self, tabs, ids, g_views):
        """ONE table-shaped scatter-add for the whole embedding plane:
        column 0 accumulates an occupancy count (touch marks — exact
        integers in f32 up to 2^24 positions; a separate boolean
        scatter-set benches ~2 ms SLOWER than riding in the one scatter),
        the rest accumulate every name's per-position cotangents.
        Per-(row, column) addition order is batch-position order — the
        same order XLA's per-name gather transpose uses — so the per-name
        gradient slices are bit-identical to the seed path's
        segment-sums."""
        flat = ids.reshape(-1)
        n_pos = flat.shape[0]
        rows = next(iter(tabs.values())).shape[0]
        cols = [jnp.ones((n_pos, 1), jnp.float32)]
        cols += [g_views[n].reshape(n_pos, -1).astype(jnp.float32)
                 for n in self._embed_names]
        gcat = jnp.concatenate(cols, axis=1)
        gext = jnp.zeros((rows, gcat.shape[1]), jnp.float32)
        return gext.at[flat].add(gcat)

    def _fused_apply(self, state: TrainState, tabs, gext, count):
        """Masked lazy-Adam sweep per name over the gradient columns of
        ``gext`` (+ the touched-rows-only L2 term, added here exactly as
        AD adds it on the seed path). Returns (new_params_embed,
        new_embed_opt, l2_value)."""
        touched = gext[:, 0] > 0
        opt_embed = state.opt_state["embed"]
        emb = self.model.emb
        l2_reg = self.cfg.l2_reg
        new_params_embed: Dict[str, Any] = {}
        new_embed: Dict[str, Any] = {}
        l2 = jnp.zeros((), jnp.float32)
        # tau is identical across tables (same touched set every step), so
        # the lazy-decay pows — the sweep's hot spot — are computed once
        # and shared by every table (see sparse_adam_masked's decay note).
        # exp2 formulation: benches ~11x faster than jnp.power on XLA:CPU
        # (pow lowers to a libm call) at ~1 ULP from pow — inside the
        # tolerance already pinned for this leg (sparse_adam_masked doc).
        tau = opt_embed[self._embed_names[0]][emb.MONO].tau
        idle = (count - tau).astype(jnp.float32)
        decay = jax.lax.optimization_barrier(
            (jnp.exp2(idle * np.float32(np.log2(0.9))),
             jnp.exp2(idle * np.float32(np.log2(0.999)))))
        o = 1
        for name in self._embed_names:
            tab = tabs[name]
            d = 1 if tab.ndim == 1 else tab.shape[-1]
            g_eff = gext[:, o:o + d].reshape(tab.shape)
            if l2_reg:
                g_eff = g_eff + l2_reg * tab.astype(jnp.float32)
            o += d
            new_tab, new_oe = opt_lib.sparse_adam_masked(
                tab, g_eff, touched, opt_embed[name][emb.MONO], count,
                lr=self._sparse_lr, decay=decay)
            new_params_embed[name] = new_tab
            new_embed[name] = {emb.MONO: new_oe}
            if l2_reg:
                sq = jnp.square(tab.astype(jnp.float32))
                keep = touched.reshape(touched.shape + (1,) * (sq.ndim - 1))
                l2 = l2 + 0.5 * jnp.sum(
                    jnp.where(keep, sq, jnp.zeros((), sq.dtype)))
        return new_params_embed, new_embed, l2_reg * l2

    def _sparse_apply(self, state: TrainState, plan, rows0, g_rows, count):
        """Lazy-Adam apply + writeback for every (name, table): returns
        ({name: new_entry_params}, {name: new_opt_tables}).

        The counting plans' select-writeback companions are STRIPPED here:
        a vocab-shaped ``where`` in the update graph perturbs XLA:CPU's
        fusion of the model backward (~1 ULP cotangent drift), breaking
        the kill-switch bit-parity pin. The scatter writeback is
        bit-exact, so the trainer always takes it; the select leg stays
        available to the A/B bench through ``ops.embedding.scatter_rows``
        directly (recorded as a parity loss in EMBED_r02.json)."""
        plan = {key: e._replace(touched=None, rank=None)
                for key, e in plan.items()}
        emb = self.model.emb
        opt_embed = state.opt_state["embed"]
        new_params_embed: Dict[str, Any] = {}
        new_embed: Dict[str, Any] = {}
        for name in self._embed_names:
            tabs = emb.tables(state.params[name])
            new_tabs: Dict[str, jax.Array] = {}
            new_opt_t: Dict[str, Any] = {}
            for key, e in plan.items():
                new_tabs[key], new_opt_t[key] = opt_lib.sparse_apply_rows(
                    rows0[name][key], g_rows[name][key], e,
                    opt_embed[name][key], count, lr=self._sparse_lr,
                    table=tabs[key])
            new_params_embed[name] = emb.from_tables(new_tabs)
            new_embed[name] = new_opt_t
        return new_params_embed, new_embed

    def _sparse_step_impl(self, state: TrainState, batch
                          ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        """One sparse-update optimizer step (single-device path).

        The batch's ids are deduped into a per-table plan; the TOUCHED ROWS
        — not the tables — are the differentiated leaf, so AD of the
        inverse-index gather in the forward lowers to a batch-sized
        segment-sum scatter-add instead of a [vocab, ...] cotangent, and
        lazy timestamped Adam (optimizers.sparse_adam_rows) touches only
        those rows. Per-step cost scales with unique-ids-per-batch, never
        with vocab size (EMBED_r02.json pins the scaling curve).

        With the embedding kernels enabled (default) the monolithic layout
        takes the FUSED vocab-space formulation: the [B, F, D] batch views
        are the gradient leaves (no dedup plan at all), every name's
        cotangents land in one table-shaped scatter-add alongside an
        occupancy column, and lazy Adam runs as a masked table sweep —
        at the dense step's cost profile. Gradients are bit-identical to
        the seed formulation; the Adam tail rounds 1–2 ULP apart between
        the row-space and table-sweep programs (see sparse_adam_masked),
        so the kill-switch parity test pins a tight tolerance there and
        bit equality everywhere else."""
        emb = self.model.emb
        rng = jax.random.fold_in(state.rng, state.step)
        tabs = {n: state.params[n] for n in self._embed_names}
        rest0 = {k: v for k, v in state.params.items()
                 if k not in self._embed_names}
        fused = self._use_fused_backward() and self._fused_tables_ok(tabs)

        if fused:
            ids = batch["feat_ids"]
            views0 = {n: jnp.take(tabs[n], ids, axis=0)
                      for n in self._embed_names}

            def loss_fn(diff):
                views, rest = diff
                params = {**rest, **tabs}
                logits, new_mstate = self.model.apply(
                    params, state.model_state, batch["feat_ids"],
                    batch["feat_vals"], train=True, rng=rng,
                    shard_axis=None, data_axis=None,
                    emb_rows={n: {emb.MONO: views[n]}
                              for n in self._embed_names}, emb_plan=None)
                labels = self._batch_labels(batch)
                xent = jnp.mean(self._per_example_loss(logits, labels))
                return xent, (xent, new_mstate)

            (_, (xent, new_mstate)), (g_views, g_rest) = (
                jax.value_and_grad(loss_fn, has_aux=True)((views0, rest0)))
            gext = self._fused_grad_ext(tabs, ids, g_views)
        else:
            plan = emb.sparse_plan(batch["feat_ids"])
            rows0 = {n: emb.gather_rows(state.params[n], plan)
                     for n in self._embed_names}

            def loss_fn(diff):
                rows, rest = diff
                params = {**rest, **tabs}
                logits, new_mstate = self.model.apply(
                    params, state.model_state, batch["feat_ids"],
                    batch["feat_vals"], train=True, rng=rng,
                    shard_axis=None, data_axis=None,
                    emb_rows=rows, emb_plan=plan)
                labels = self._batch_labels(batch)
                xent = jnp.mean(self._per_example_loss(logits, labels))
                # Touched-rows-only L2 (deliberate deviation from dense L2
                # — idle rows do not decay between touches; TUNING §2.11).
                l2 = self.model.l2_loss(params, emb_rows=rows, emb_plan=plan)
                return xent + l2, (xent, l2, new_mstate)

            (_, (xent, l2, new_mstate)), (g_rows, g_rest) = (
                jax.value_and_grad(loss_fn, has_aux=True)((rows0, rest0)))

        opt = state.opt_state
        upd_rest, new_base = self.tx.update(g_rest, opt["base"], rest0)
        new_rest = optax.apply_updates(rest0, upd_rest)
        count = opt["count"] + 1
        new_params = dict(new_rest)
        if fused:
            emb_params, new_embed, l2 = self._fused_apply(
                state, tabs, gext, count)
        else:
            emb_params, new_embed = self._sparse_apply(
                state, plan, rows0, g_rows, count)
        new_params.update(emb_params)
        new_opt = {"base": new_base, "embed": new_embed, "count": count}
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            model_state=new_mstate)
        return new_state, {"loss": xent + l2, "xent": xent}

    def _sharded_sparse_step_impl(self, state: TrainState, batch, *,
                                  data_axis, shard_axis
                                  ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        """One sparse optimizer step under the ('data','model') mesh with
        row-sharded tables (``--embedding_shard rows``).

        Topology per step (runs inside shard_map; tables + Adam moments
        live as [rows/D, ...] shards over 'model', the batch is sharded
        over 'data' and replicated over 'model'):

          1. The local batch's dedup plan is built exactly as on the
             single-device path — model peers see the same batch, so the
             plan (and its sorted uid list) is model-replicated for free.
          2. ``build_exchange`` splits request responsibility by uid
             position across the D model peers (C = ceil(U/D) ids each),
             ``exchange_rows`` moves requests/responses via two tiled
             ``all_to_all``s and reassembles the [U, ...] row block with a
             psum — bit-identical to gathering from the full table.
          3. The TOUCHED ROWS are the gradient leaf (same AD shape as the
             single-device plan leg); the in-loss pmean over 'data' is THE
             gradient sync for the dense params, and scales the row
             cotangents by 1/dp.
          4. ``owner_scatter_add`` lands each replica's cotangents in
             owner-local table space; a psum over 'data' then sums the
             1/dp-scaled contributions — i.e. the cross-replica pmean —
             and unions the touched masks. Each owner lazy-Adam-sweeps
             only its own rows (sparse_adam_masked), so optimizer work
             and moment HBM both scale 1/D.

        Touched-rows L2 is applied post-hoc against the UNION touched mask
        (fused-apply style): putting it in the per-replica loss would
        weight a row by how many replicas touched it (k/dp), diverging
        from the single-device semantics this path is pinned against.

        Unlike the dense step, the loss here carries NO collectives at
        all: the gradients come out per-replica LOCAL and the pmeans are
        explicit, after AD (the hierarchical dense leg's idiom). That
        sidesteps the in-loss-pmean transpose entirely — whose scaling
        shifted between the legacy shard_map AD and the vma-typed one —
        so this program means the same thing on either. The hierarchical
        two-stage 'data' reduce is NOT composed with this path (grads
        never materialize as one dense tree to stage)."""
        emb = self.model.emb
        d = self.mesh_info.model_size if shard_axis is not None else 1
        rng = jax.random.fold_in(state.rng, state.step)
        if data_axis is not None:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(data_axis))
        tabs = {n: state.params[n] for n in self._embed_names}  # local shards
        rest0 = {k: v for k, v in state.params.items()
                 if k not in self._embed_names}
        plan = emb.sparse_plan(batch["feat_ids"])
        if d > 1:
            ex = {key: emb_ops.build_exchange(e, d, shard_axis)
                  for key, e in plan.items()}
            rows0 = {n: {key: emb_ops.exchange_rows(
                             emb.tables(tabs[n])[key], ex[key], shard_axis)
                         for key in plan}
                     for n in self._embed_names}
        else:
            rows0 = {n: emb.gather_rows(tabs[n], plan)
                     for n in self._embed_names}

        def loss_fn(diff):
            rows, rest = diff
            params = {**rest, **tabs}
            logits, new_mstate = self.model.apply(
                params, state.model_state, batch["feat_ids"],
                batch["feat_vals"], train=True, rng=rng,
                shard_axis=None, data_axis=data_axis,
                emb_rows=rows, emb_plan=plan, **self._hist_kwargs(batch))
            labels = self._batch_labels(batch)
            xent = jnp.mean(self._per_example_loss(logits, labels))
            return xent, (xent, new_mstate)

        (_, (xent, new_mstate)), (g_rows, g_rest) = (
            jax.value_and_grad(loss_fn, has_aux=True)((rows0, rest0)))
        if data_axis is not None:
            # THE gradient sync point, explicit and post-AD: per-replica
            # local-mean grads -> the global-batch mean (row leaves sync
            # below, in owner table space).
            g_rest = jax.tree.map(
                lambda g: jax.lax.pmean(g, data_axis), g_rest)
            xent = jax.lax.pmean(xent, data_axis)

        opt = state.opt_state
        upd_rest, new_base = self.tx.update(g_rest, opt["base"], rest0)
        new_rest = optax.apply_updates(rest0, upd_rest)
        count = opt["count"] + 1
        opt_embed = opt["embed"]
        l2_reg = self.cfg.l2_reg
        new_tabs: Dict[str, Dict[str, jax.Array]] = {
            n: {} for n in self._embed_names}
        new_embed: Dict[str, Dict[str, Any]] = {
            n: {} for n in self._embed_names}
        l2 = jnp.zeros((), jnp.float32)
        for key, e in plan.items():
            scat = {n: emb_ops.owner_scatter_add(
                        g_rows[n][key], e, d,
                        shard_axis if d > 1 else None)
                    for n in self._embed_names}
            grads = {n: scat[n][0] for n in self._embed_names}
            touched = scat[self._embed_names[0]][1]
            if data_axis is not None:
                # pmean of the owner-local scatters == the global-batch
                # mean grad per owned row; touched becomes the UNION.
                grads = jax.tree.map(
                    lambda g: jax.lax.pmean(g, data_axis), grads)
                touched = jax.lax.psum(
                    touched.astype(jnp.int32), data_axis) > 0
            # Shared lazy-decay pair per physical table (tau is identical
            # across names — same touched set every step); exp2 form and
            # barrier exactly as in _fused_apply.
            tau = opt_embed[self._embed_names[0]][key].tau
            idle = (count - tau).astype(jnp.float32)
            decay = jax.lax.optimization_barrier(
                (jnp.exp2(idle * np.float32(np.log2(0.9))),
                 jnp.exp2(idle * np.float32(np.log2(0.999)))))
            for name in self._embed_names:
                tab = emb.tables(tabs[name])[key]
                g_eff = grads[name]
                if l2_reg:
                    g_eff = g_eff + l2_reg * tab.astype(jnp.float32)
                new_tab, new_oe = opt_lib.sparse_adam_masked(
                    tab, g_eff, touched, opt_embed[name][key], count,
                    lr=self._sparse_lr, decay=decay)
                new_tabs[name][key] = new_tab
                new_embed[name][key] = new_oe
                if l2_reg:
                    sq = jnp.square(tab.astype(jnp.float32))
                    keep = touched.reshape(
                        touched.shape + (1,) * (sq.ndim - 1))
                    l2 = l2 + 0.5 * jnp.sum(
                        jnp.where(keep, sq, jnp.zeros((), sq.dtype)))
        l2 = l2_reg * l2
        if l2_reg and shard_axis is not None:
            # Per-shard partials -> the full-table touched-L2 scalar.
            l2 = jax.lax.psum(l2, shard_axis)
        new_params = dict(new_rest)
        for name in self._embed_names:
            new_params[name] = emb.from_tables(new_tabs[name])
        new_opt = {"base": new_base, "embed": new_embed, "count": count}
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            model_state=new_mstate)
        return new_state, {"loss": xent + l2, "xent": xent}

    def _accum_step_impl(self, state: TrainState, batches, *, data_axis,
                         shard_axis
                         ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        """ONE optimizer apply over ``a`` stacked microbatches [a, B, ...].

        The loss is the mean of per-microbatch mean losses — for equal-size
        microbatches exactly the big-batch mean over a*B examples — so the
        accumulated gradient equals the single big-batch gradient up to
        float reassociation (the parity test pins the tolerance). The inner
        scan re-walks the forward once per microbatch, so activation memory
        peaks at ONE microbatch while the effective batch is
        batch_size * a * data parallelism. ``state.step`` advances by ``a``
        (it counts MICROBATCHES: resume bookkeeping equates steps with
        batches consumed); the optimizer's count — Adam bias correction
        included — ticks ONCE per apply.
        """
        if self.sparse_embed and data_axis is None and shard_axis is None:
            return self._sparse_accum_step_impl(state, batches)
        a = batches["label"].shape[0]
        base_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            def micro(carry, inp):
                mstate, xent_sum = carry
                i, batch = inp
                rng = jax.random.fold_in(base_rng, i)
                if data_axis is not None:
                    rng = jax.random.fold_in(
                        rng, jax.lax.axis_index(data_axis))
                logits, new_mstate = self.model.apply(
                    params, mstate, batch["feat_ids"], batch["feat_vals"],
                    train=True, rng=rng, shard_axis=shard_axis,
                    data_axis=data_axis, **self._hist_kwargs(batch))
                labels = self._batch_labels(batch)
                xent = jnp.mean(self._per_example_loss(logits, labels))
                return (new_mstate, xent_sum + xent), None

            (new_mstate, xent_sum), _ = jax.lax.scan(
                micro, (state.model_state, jnp.zeros((), jnp.float32)),
                (jnp.arange(a), batches))
            xent = xent_sum / a
            if data_axis is not None and self._hier_groups is None:
                xent = jax.lax.pmean(xent, data_axis)
            # L2 charged once per APPLY, not per microbatch — matching the
            # equivalent big-batch step, where it also appears once.
            l2 = self.model.l2_loss(params)
            if shard_axis is not None:
                l2 = jax.lax.psum(l2, shard_axis)
            return xent + l2, (xent, l2, new_mstate)

        (_, (xent, l2, new_mstate)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if data_axis is not None and self._hier_groups is not None:
            grads = mesh_lib.hierarchical_pmean(
                grads, data_axis, self._hier_groups,
                self.mesh_info.data_size)
            xent = jax.lax.pmean(xent, data_axis)  # metrics only
        grads = {**grads, **{
            n: self.model.emb.mask_pad_grads(grads[n], axis_name=shard_axis)
            for n in self._embed_names}}
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + a, params=new_params, opt_state=new_opt,
            model_state=new_mstate)
        return new_state, {"loss": xent + l2, "xent": xent}

    def _sparse_accum_step_impl(self, state: TrainState, batches
                                ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        """Sparse-update accumulation: ONE merged plan across the group.

        The group's a*B batches of ids dedup into a single PlanEntry per
        table (``make_plan`` over the flattened group — the same machinery
        as the per-batch plan), so the touched-rows gradient leaf is
        gathered ONCE; each microbatch forward reads it through its [B, F]
        slice of the shared inverse index, and AD accumulates the
        per-microbatch cotangents into the same [U] row slots
        automatically. One ``sparse_adam_rows`` apply per group (count
        ticks once), touched-row L2 charged once per apply.
        """
        emb = self.model.emb
        a, bsz = batches["feat_ids"].shape[:2]
        base_rng = jax.random.fold_in(state.rng, state.step)
        tabs = {n: state.params[n] for n in self._embed_names}
        rest0 = {k: v for k, v in state.params.items()
                 if k not in self._embed_names}
        fused = self._use_fused_backward() and self._fused_tables_ok(tabs)

        if fused:
            # Fused vocab-space formulation over the whole group: the
            # [a, B, F, D] stacked views are the leaves; the scan slices
            # one microbatch's view per iteration and AD stacks the
            # per-microbatch cotangents back into [a, B, F, D] — flattened
            # into ONE table-shaped scatter-add below (group-position
            # order == the merged plan's segment-sum order, bit-for-bit).
            ids = batches["feat_ids"]
            views0 = {n: jnp.take(tabs[n], ids, axis=0)
                      for n in self._embed_names}

            def loss_fn(diff):
                views, rest = diff
                params = {**rest, **tabs}

                def micro(carry, inp):
                    mstate, xent_sum = carry
                    i, batch, views_i = inp
                    rng = jax.random.fold_in(base_rng, i)
                    logits, new_mstate = self.model.apply(
                        params, mstate, batch["feat_ids"],
                        batch["feat_vals"], train=True, rng=rng,
                        shard_axis=None, data_axis=None,
                        emb_rows={n: {emb.MONO: views_i[n]}
                                  for n in self._embed_names},
                        emb_plan=None)
                    labels = self._batch_labels(batch)
                    xent = jnp.mean(self._per_example_loss(logits, labels))
                    return (new_mstate, xent_sum + xent), None

                (new_mstate, xent_sum), _ = jax.lax.scan(
                    micro, (state.model_state, jnp.zeros((), jnp.float32)),
                    (jnp.arange(a), batches, views))
                xent = xent_sum / a
                return xent, (xent, new_mstate)

            (_, (xent, new_mstate)), (g_views, g_rest) = (
                jax.value_and_grad(loss_fn, has_aux=True)((views0, rest0)))
            gext = self._fused_grad_ext(tabs, ids, g_views)
        else:
            ids_flat = batches["feat_ids"].reshape(
                (a * bsz,) + batches["feat_ids"].shape[2:])
            plan = emb.sparse_plan(ids_flat)
            # Per-microbatch plan views: merged uids, inverse index (and
            # the hashed-mode position mask) sliced back to [B, F].
            inv_stack = {key: e.inv.reshape((a, bsz) + e.inv.shape[1:])
                         for key, e in plan.items()}
            mask_stack = {key: e.mask.reshape((a, bsz) + e.mask.shape[1:])
                          for key, e in plan.items() if e.mask is not None}
            rows0 = {n: emb.gather_rows(state.params[n], plan)
                     for n in self._embed_names}

            def loss_fn(diff):
                rows, rest = diff
                params = {**rest, **tabs}

                def micro(carry, inp):
                    mstate, xent_sum = carry
                    i, batch, inv_i, mask_i = inp
                    plan_i = {key: e._replace(inv=inv_i[key],
                                              mask=mask_i.get(key))
                              for key, e in plan.items()}
                    rng = jax.random.fold_in(base_rng, i)
                    logits, new_mstate = self.model.apply(
                        params, mstate, batch["feat_ids"],
                        batch["feat_vals"], train=True, rng=rng,
                        shard_axis=None, data_axis=None,
                        emb_rows=rows, emb_plan=plan_i)
                    labels = self._batch_labels(batch)
                    xent = jnp.mean(self._per_example_loss(logits, labels))
                    return (new_mstate, xent_sum + xent), None

                (new_mstate, xent_sum), _ = jax.lax.scan(
                    micro, (state.model_state, jnp.zeros((), jnp.float32)),
                    (jnp.arange(a), batches, inv_stack, mask_stack))
                xent = xent_sum / a
                l2 = self.model.l2_loss(params, emb_rows=rows, emb_plan=plan)
                return xent + l2, (xent, l2, new_mstate)

            (_, (xent, l2, new_mstate)), (g_rows, g_rest) = (
                jax.value_and_grad(loss_fn, has_aux=True)((rows0, rest0)))

        opt = state.opt_state
        upd_rest, new_base = self.tx.update(g_rest, opt["base"], rest0)
        new_rest = optax.apply_updates(rest0, upd_rest)
        count = opt["count"] + 1
        new_params = dict(new_rest)
        if fused:
            emb_params, new_embed, l2 = self._fused_apply(
                state, tabs, gext, count)
        else:
            emb_params, new_embed = self._sparse_apply(
                state, plan, rows0, g_rows, count)
        new_params.update(emb_params)
        new_opt = {"base": new_base, "embed": new_embed, "count": count}
        new_state = state.replace(
            step=state.step + a, params=new_params, opt_state=new_opt,
            model_state=new_mstate)
        return new_state, {"loss": xent + l2, "xent": xent}

    def _make_train_step(self) -> Callable:
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None
        data_axis = mi.data_axis

        def step(state: TrainState, batch):
            return self._step_impl(
                state, batch, data_axis=data_axis, shard_axis=shard_axis)

        donate = (0,) if self._donate_state else ()
        if mi.mesh is None:
            return jax.jit(step, donate_argnums=donate)
        specs = self._dummy_specs()
        return jax.jit(
            shard_map(
                step, mesh=mi.mesh,
                in_specs=(specs["state"], specs["batch"]),
                out_specs=(specs["state"], P()),
                # Grouped psums defeat static replication inference; the
                # hierarchical program opts out of the check.
                check_vma=self._hier_groups is None),
            donate_argnums=donate)

    def _make_train_multi_step(self) -> Callable:
        """K optimizer steps in ONE dispatch: lax.scan over a stacked batch
        [K, B, ...] (K comes from the batch's leading dim; jit specializes
        per shape). Bit-identical to K sequential train_step calls (same rng
        folding, same update order) but amortizes the per-step host dispatch
        and host->device transfer overhead — the dominant e2e cost on a
        single-core host (see README Performance).

        Under ``--grad_accum_steps a`` > 1 the K scanned microbatches
        regroup at trace time into K//a accumulated optimizer applies
        (``_accum_step_impl``) plus K%a single-microbatch FULL optimizer
        steps for a ragged tail group — a tail never stalls on a partial
        accumulation group. ``state.step`` still counts microbatches either
        way (resume bookkeeping equates steps with batches consumed)."""
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None
        data_axis = mi.data_axis
        a = self._accum

        def multi(state: TrainState, batches):
            def body(st, batch):
                new_st, m = self._step_impl(
                    st, batch, data_axis=data_axis, shard_axis=shard_axis)
                return new_st, jnp.stack((m["loss"], m["xent"]))

            if a > 1:
                k_steps = batches["label"].shape[0]
                n_macro, left = divmod(k_steps, a)
                ms = None
                if n_macro:
                    groups = jax.tree.map(
                        lambda x: x[:n_macro * a].reshape(
                            (n_macro, a) + x.shape[1:]), batches)

                    def macro_body(st, group):
                        new_st, m = self._accum_step_impl(
                            st, group, data_axis=data_axis,
                            shard_axis=shard_axis)
                        return new_st, jnp.stack((m["loss"], m["xent"]))

                    state, ms = jax.lax.scan(macro_body, state, groups)
                if left:
                    tail = jax.tree.map(lambda x: x[k_steps - left:], batches)
                    state, ms_tail = jax.lax.scan(body, state, tail)
                    ms = ms_tail if ms is None else jnp.concatenate(
                        [ms, ms_tail])
                return state, {"loss": ms[-1, 0], "xent": ms[-1, 1]}
            state2, ms = jax.lax.scan(body, state, batches)
            # Last-step metrics: matches what a sequential loop would report.
            return state2, {"loss": ms[-1, 0], "xent": ms[-1, 1]}

        # Donate only the state: scanned batch buffers are not reusable as
        # outputs (XLA reports them unusable and warns).
        donate = (0,) if self._donate_state else ()
        if mi.mesh is None:
            return jax.jit(multi, donate_argnums=donate)
        specs = self._dummy_specs()
        sb_specs = jax.tree.map(lambda s: P(None, *s), specs["batch"])
        return jax.jit(
            shard_map(
                multi, mesh=mi.mesh,
                in_specs=(specs["state"], sb_specs),
                out_specs=(specs["state"], P()),
                check_vma=self._hier_groups is None),
            donate_argnums=donate)

    @property
    def multi_step(self) -> Callable:
        if self._multi_step is None:
            self._multi_step = self._make_train_multi_step()
        return self._multi_step

    def put_superbatch(self, batches) -> Dict[str, jax.Array]:
        """Stack K host batches into [K, B, ...] arrays and transfer in one
        host->device move (batch dim sharded over 'data', K replicated)."""
        stacked = {
            key: np.stack([b[key] for b in batches]) for key in batches[0]}
        return self._put_stacked(stacked)

    def put_superbatch_rows(self, rows: Dict[str, np.ndarray], k: int
                            ) -> Dict[str, jax.Array]:
        """[k*B, ...] contiguous rows -> [k, B, ...] device arrays. The
        reshape is free (contiguous view), so a pipeline emitting pool
        slices (CtrPipeline.iter_superbatches) reaches the device with zero
        host-side stacking copies."""
        stacked = {key: v.reshape(k, v.shape[0] // k, *v.shape[1:])
                   for key, v in rows.items()}
        return self._put_stacked(stacked)

    def _put_stacked(self, stacked: Dict[str, np.ndarray]
                     ) -> Dict[str, jax.Array]:
        mi = self.mesh_info
        if mi.mesh is None:
            return jax.device_put(stacked)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                mi.sharding(
                    P(None, mesh_lib.DATA_AXIS, *([None] * (x.ndim - 2)))), x),
            stacked)

    def _eval_update(self, state: TrainState, batch, acc, *, data_axis,
                     shard_axis):
        """One weighted eval update (shared by the single-batch and scanned
        eval steps): ``batch['weight']`` ([B,1], 1=real row, 0=tail padding)
        flows into the AUC histograms and the loss sum, so every record
        counts exactly once regardless of how the tail was padded — and all
        ranks can run the same compiled shape on ragged shards."""
        auc_state, loss_state = acc
        logits, _ = self.model.apply(
            state.params, state.model_state, batch["feat_ids"],
            batch["feat_vals"], train=False, rng=None,
            shard_axis=shard_axis, data_axis=data_axis,
            **self._hist_kwargs(batch))
        if self._multitask:
            # Per-task dict accumulator: one psum-reducible histogram pair
            # per named task; the combined weighted loss mirrors training.
            labels_m = self._batch_labels(batch)
            w = batch["weight"].reshape(-1).astype(jnp.float32)
            per_ex = self._per_example_loss(logits, labels_m)
            probs = self.model.probs_from_logits(logits)
            deltas = {
                name: metrics_lib.auc_update(
                    metrics_lib.auc_init(self.cfg.auc_num_thresholds),
                    probs[:, t], labels_m[:, t], w)
                for t, name in enumerate(self._task_names)}
            loss_total = jnp.sum(per_ex * w)
            n = jnp.sum(w)
            if data_axis is not None:
                deltas = {name: metrics_lib.auc_psum(d, data_axis)
                          for name, d in deltas.items()}
                loss_total = jax.lax.psum(loss_total, data_axis)
                n = jax.lax.psum(n, data_axis)
            new_auc = {name: metrics_lib.auc_merge(auc_state[name], d)
                       for name, d in deltas.items()}
            new_loss = metrics_lib.MeanState(
                total=loss_state.total + loss_total,
                count=loss_state.count + n)
            return (new_auc, new_loss)
        labels = batch["label"].reshape(-1).astype(jnp.float32)
        w = batch["weight"].reshape(-1).astype(jnp.float32)
        per_ex = self._per_example_loss(logits, labels)
        probs = jax.nn.sigmoid(logits)
        delta = metrics_lib.auc_update(
            metrics_lib.auc_init(self.cfg.auc_num_thresholds), probs,
            labels, w)
        loss_total = jnp.sum(per_ex * w)
        n = jnp.sum(w)
        if data_axis is not None:
            delta = metrics_lib.auc_psum(delta, data_axis)
            loss_total = jax.lax.psum(loss_total, data_axis)
            n = jax.lax.psum(n, data_axis)
        new_auc = metrics_lib.auc_merge(auc_state, delta)
        new_loss = metrics_lib.MeanState(
            total=loss_state.total + loss_total, count=loss_state.count + n)
        return (new_auc, new_loss)

    def _make_eval_step(self) -> Callable:
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None
        data_axis = mi.data_axis

        def step(state: TrainState, batch, acc):
            return self._eval_update(state, batch, acc, data_axis=data_axis,
                                     shard_axis=shard_axis)

        if mi.mesh is None:
            return jax.jit(step)
        specs = self._dummy_specs()
        return jax.jit(shard_map(
            step, mesh=mi.mesh,
            in_specs=(specs["state"], specs["eval_batch"], P()),
            out_specs=P(),
            check_vma=True))

    def _make_eval_multi_step(self) -> Callable:
        """K weighted eval updates in ONE dispatch: lax.scan over stacked
        [K, B, ...] batches (the eval twin of ``multi_step``, VERDICT r3
        #2). The scan merges into the accumulator in batch order; on CPU
        that reproduces K sequential ``eval_step`` calls bit-for-bit (the
        property the tests pin), while on TPU the scanned program may fuse
        or reassociate float reductions differently, so expect agreement
        to rounding there, not bit-identity. Only the per-batch host
        dispatch + transfer overhead is amortized."""
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None
        data_axis = mi.data_axis

        def multi(state: TrainState, batches, acc):
            def body(a, batch):
                return self._eval_update(
                    state, batch, a, data_axis=data_axis,
                    shard_axis=shard_axis), None
            acc2, _ = jax.lax.scan(body, acc, batches)
            return acc2

        if mi.mesh is None:
            return jax.jit(multi)
        specs = self._dummy_specs()
        sb_specs = jax.tree.map(lambda s: P(None, *s), specs["eval_batch"])
        return jax.jit(shard_map(
            multi, mesh=mi.mesh,
            in_specs=(specs["state"], sb_specs, P()),
            out_specs=P(),
            check_vma=True))

    def _predict_logits(self, state: TrainState, batch, *, data_axis,
                        shard_axis):
        logits, _ = self.model.apply(
            state.params, state.model_state, batch["feat_ids"],
            batch["feat_vals"], train=False, rng=None,
            shard_axis=shard_axis, data_axis=data_axis,
            **self._hist_kwargs(batch))
        if self._multitask:
            return self.model.probs_from_logits(logits)  # [B, T]
        return jax.nn.sigmoid(logits)

    def _make_predict_step(self) -> Callable:
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None

        def step(state: TrainState, batch):
            return self._predict_logits(
                state, batch, data_axis=mi.data_axis, shard_axis=shard_axis)

        if mi.mesh is None:
            return jax.jit(step)
        specs = self._dummy_specs()
        return jax.jit(shard_map(
            step, mesh=mi.mesh,
            in_specs=(specs["state"], specs["batch"]),
            out_specs=P(mesh_lib.DATA_AXIS),
            check_vma=True))

    def _make_predict_multi_step(self) -> Callable:
        """K forward passes in ONE dispatch: scan over stacked [K, B, ...]
        batches returning [K, B] probabilities (the infer twin of
        ``multi_step``)."""
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None

        def multi(state: TrainState, batches):
            def body(carry, batch):
                return carry, self._predict_logits(
                    state, batch, data_axis=mi.data_axis,
                    shard_axis=shard_axis)
            _, probs = jax.lax.scan(body, 0, batches)
            return probs

        if mi.mesh is None:
            return jax.jit(multi)
        specs = self._dummy_specs()
        sb_specs = jax.tree.map(lambda s: P(None, *s), specs["batch"])
        return jax.jit(shard_map(
            multi, mesh=mi.mesh,
            in_specs=(specs["state"], sb_specs),
            out_specs=P(None, mesh_lib.DATA_AXIS),
            check_vma=True))

    def _dummy_specs(self) -> Dict[str, Any]:
        if self._specs is None:
            # Build spec trees from an abstract state (no device memory).
            abstract = jax.eval_shape(
                lambda: self._abstract_state_for_specs())
            state_specs = self._state_specs(abstract)
            batch = {
                "feat_ids": jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, self.cfg.field_size), jnp.int32),
                "feat_vals": jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, self.cfg.field_size), jnp.float32),
                "label": jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, 1), jnp.float32),
            }
            if self._multitask:
                batch["label2"] = jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, 1), jnp.float32)
            if (getattr(self.model, "uses_history", False)
                    and self.cfg.history_max_len > 0):
                # History runs (history_max_len > 0) carry the fixed-shape
                # pair in every batch (zero_batch emits all-masked fillers
                # for lockstep) — the shard_map in_specs tree must include
                # them or any DIN/BST mesh run dies on pytree structure
                # mismatch. At history_max_len == 0 the zoo feeds plain
                # batches and the models default to an empty history.
                hl = self.cfg.history_max_len
                batch["hist_ids"] = jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, hl), jnp.int32)
                batch["hist_mask"] = jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, hl), jnp.float32)
            eval_batch = dict(batch)
            eval_batch["weight"] = jax.ShapeDtypeStruct(
                (self.cfg.batch_size, 1), jnp.float32)
            self._specs = {
                "state": state_specs,
                "batch": mesh_lib.batch_pspecs(batch),
                "eval_batch": mesh_lib.batch_pspecs(eval_batch),
            }
        return self._specs

    def _abstract_state_for_specs(self) -> TrainState:
        rng = jax.random.PRNGKey(0)
        params, model_state = self.model.init(rng)
        opt_state = self._init_opt_state(params)
        return TrainState.create(params, opt_state, model_state, rng)

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------
    @property
    def train_step(self) -> Callable:
        if self._train_step is None:
            self._train_step = self._make_train_step()
        return self._train_step

    @property
    def eval_step(self) -> Callable:
        if self._eval_step is None:
            self._eval_step = self._make_eval_step()
        return self._eval_step

    @property
    def eval_multi_step(self) -> Callable:
        if self._eval_multi_step is None:
            self._eval_multi_step = self._make_eval_multi_step()
        return self._eval_multi_step

    @property
    def predict_step(self) -> Callable:
        if self._predict_step is None:
            self._predict_step = self._make_predict_step()
        return self._predict_step

    @property
    def predict_multi_step(self) -> Callable:
        if self._predict_multi_step is None:
            self._predict_multi_step = self._make_predict_multi_step()
        return self._predict_multi_step

    def _staged_put(self, put: Callable, *args) -> Any:
        """Route a staging-thread host->device transfer through the active
        fit's staging ring (slot fence + transfer/wait timing). Identity
        passthrough outside fit, so eval/predict transfers are untouched."""
        ring = self._ring
        if ring is None:
            return put(*args)
        return ring.put(lambda: put(*args), _staged_records(args))

    def _grad_payload_bytes(self) -> int:
        """Analytic per-device payload of ONE gradient reduce over 'data'
        (row-sharded embedding leaves count 1/model_size; see
        mesh.grad_payload_bytes). Computed once from abstract shapes."""
        if self._grad_bytes_cache is None:
            abstract = jax.eval_shape(
                lambda: self._abstract_state_for_specs())
            self._grad_bytes_cache = mesh_lib.grad_payload_bytes(
                abstract.params, self._embed_names,
                self.mesh_info.model_size,
                embedding_shard=("rows" if self.sparse_embed
                                 and self._shard_rows else "off"))
        return self._grad_bytes_cache

    def _stage(self, batches: Iterable[Dict[str, np.ndarray]], k: int,
               depth: int):
        """Group host batches into K-step superbatches and move them to device
        on a background thread, ``depth`` dispatch-groups ahead — overlapping
        the host->device transfer with step dispatch (the prefetch-to-device
        iterator analog of X3). Yields (device_batches, n_steps, n_local_ex).
        A tail group smaller than K is staged as single steps (no recompile
        for odd sizes).

        Fast path: a source exposing ``iter_superbatches`` (CtrPipeline)
        emits pre-grouped contiguous rows, skipping the np.stack copy."""
        sb_iter = getattr(batches, "iter_superbatches", None)

        def gen():
            if sb_iter is not None and k > 1:
                for rows, m, n_ex in sb_iter(k):
                    if m == 1:
                        yield self._staged_put(self.put_batch, rows), 1, n_ex
                    else:
                        yield self._staged_put(
                            self.put_superbatch_rows, rows, m), m, n_ex
                return
            group = []
            for b in batches:
                group.append(b)
                if len(group) == k:
                    n_ex = sum(g["label"].shape[0] for g in group)
                    if k == 1:
                        yield self._staged_put(
                            self.put_batch, group[0]), 1, n_ex
                    else:
                        yield self._staged_put(
                            self.put_superbatch, group), k, n_ex
                    group = []
            for b in group:
                yield (self._staged_put(self.put_batch, b), 1,
                       b["label"].shape[0])

        if depth <= 0:
            return gen()
        from ..data.pipeline import _prefetch  # noqa: PLC0415
        return _prefetch(gen(), depth)

    def _stage_tiered(self, batches: Iterable[Dict[str, np.ndarray]],
                      k: int, depth: int):
        """Tiered staging: same grouping contract as ``_stage``, but every
        group is routed through the hot/cold runtime on the staging thread
        — plan the cache transaction, PREFETCH missing cold rows (the fetch
        for dispatch t+1 overlaps the device computing dispatch t when
        ``depth`` > 0), and remap ``feat_ids`` to hot slot ids — before the
        host->device transfer. Plan order == yield order == dispatch order;
        the fit loop pops one plan per yielded group via
        ``_tier.apply_next``."""

        def stage_group(group):
            n_ex = sum(g["label"].shape[0] for g in group)
            remapped = self._tier.plan_group(group)
            if len(remapped) == 1:
                return self._staged_put(self.put_batch, remapped[0]), 1, n_ex
            return (self._staged_put(self.put_superbatch, remapped),
                    len(remapped), n_ex)

        def gen():
            group = []
            for b in batches:
                group.append(b)
                if len(group) == k:
                    yield stage_group(group)
                    group = []
            for b in group:
                yield stage_group([b])

        if depth <= 0:
            return gen()
        from ..data.pipeline import _prefetch  # noqa: PLC0415
        return _prefetch(gen(), depth)

    def _stage_rounds(self, batches: Iterable[Dict[str, np.ndarray]],
                      k: int, depth: int):
        """Background staging for the multi-process fit loop: pull k-batch
        rounds off the host pipeline and pre-transfer FULL rounds to device.

        Device placement (``put_superbatch`` -> ``make_array_from_process_
        local_data``) is process-local — each process only places its own
        shard on its own devices, no cross-host communication — so it is
        safe on a background thread. The collectives (the per-round count
        allgather and the step programs) are issued by the CALLER in
        deterministic order; this generator never touches them.

        Yields ``(staged, group)``: ``staged`` is the [k,B,...] device
        superbatch for full rounds (None for short ones), ``group`` the
        host batches — retained so a rank that turns out globally short
        can transfer the agreed prefix (a staged rank slices its device
        superbatch instead). One short round ends the
        stream (source exhausted). The np.stack in ``put_superbatch`` (vs
        the single-process zero-copy ``iter_superbatches`` feed) is the
        price of the lockstep protocol — the min-truncate exchange needs
        discrete batches, and ``iter_superbatches`` may emit short groups
        at pool boundaries, which would end the protocol early on one rank
        — but the copy runs on this staging thread, off the critical path."""
        import itertools  # noqa: PLC0415

        def gen():
            it = iter(batches)
            try:
                while True:
                    group = list(itertools.islice(it, k))
                    staged = None
                    if len(group) == k:
                        staged = (self._staged_put(self.put_superbatch, group)
                                  if k > 1
                                  else self._staged_put(
                                      self.put_batch, group[0]))
                    yield staged, group
                    if len(group) < k:
                        return
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()

        if depth <= 0:
            return gen()
        from ..data.pipeline import _prefetch  # noqa: PLC0415
        return _prefetch(gen(), depth)

    def _stage_multiprocess(self, batches: Iterable[Dict[str, np.ndarray]],
                            k: int, depth: int):
        """Multi-process staging with transfer/compute overlap (VERDICT r3
        #1): same yield contract as ``_stage`` and the same lockstep
        min-truncate protocol as rounds-of-k ragged-shard handling — every
        train dispatch is a global-mesh collective, so all ranks must run
        the same number of steps even when file-level shards hold different
        record counts. Each round, ranks exchange how many local batches
        they pulled; everyone dispatches the global minimum and stops at
        the first short round (longer ranks' leftovers are dropped — the
        cross-rank generalization of drop_remainder; the records return
        next epoch under the reshuffle).

        The host->device transfer of full rounds runs on a background
        thread ``depth`` rounds ahead (see ``_stage_rounds``); ALL
        collectives — count allgathers and step programs — are enqueued
        from the caller's thread, so their order is identical on every
        rank."""
        from jax.experimental import multihost_utils  # noqa: PLC0415

        rounds = self._stage_rounds(batches, k, depth)
        try:
            for staged, group in rounds:
                counts = np.asarray(multihost_utils.process_allgather(
                    np.asarray([len(group)])))
                m = int(counts.min())
                if m == k and staged is not None:
                    n_ex = sum(g["label"].shape[0] for g in group)
                    yield staged, k, n_ex
                elif m > 0:
                    # Globally-short final round. Every rank must dispatch
                    # the SAME program sequence (the step programs are
                    # global collectives), so all ranks emit ONE m-step
                    # group: ranks that already transferred a full [k,B]
                    # superbatch slice its prefix ON DEVICE (advisor r5 —
                    # previously the staged transfer was discarded and the
                    # prefix re-transferred batch-by-batch), short ranks
                    # transfer just their m batches. m == 1 lands on the
                    # single-step program every rank has already compiled;
                    # m > 1 costs one tail-of-training compile of the
                    # [m,B] scan. The slice is collective-free, so only
                    # staged ranks running it cannot desync the mesh.
                    n_ex = sum(g["label"].shape[0] for g in group[:m])
                    if staged is not None and k > 1:
                        if m == 1:
                            dev = jax.jit(
                                lambda d: {key: v[0] for key, v in d.items()}
                            )(staged)
                        else:
                            dev = jax.jit(
                                lambda d, _m=m: {key: v[:_m]
                                                 for key, v in d.items()}
                            )(staged)
                        yield dev, m, n_ex
                    elif m == 1:
                        yield self.put_batch(group[0]), 1, n_ex
                    else:
                        yield self.put_superbatch(group[:m]), m, n_ex
                if m < k:
                    if len(group) > m:
                        ulog.warning(
                            f"ragged shards: dropped >= {len(group) - m} "
                            f"local batches to keep ranks in lockstep (min "
                            f"of {counts.reshape(-1).tolist()} per round)")
                    return
        finally:
            # Early exit abandons the staging thread mid-stream on longer
            # ranks; close it so prefetch threads and file handles release.
            close = getattr(rounds, "close", None)
            if close is not None:
                close()

    def _guard_verdict(self, guard: "guard_lib.NonFiniteGuard",
                       state: TrainState, m: Dict[str, Any]) -> str:
        """Per-dispatch guard check for the skip/rollback policies: sync the
        dispatch's loss (the one extra device read those policies pay), run
        the on-device all-isfinite param reduce, classify. Shared by the
        staged and device-resident fit loops."""
        loss = float(m["loss"])
        params_bad = (guard.params_nonfinite(state)
                      if math.isfinite(loss) else False)
        return guard.observe(loss, int(state.step), params_bad=params_bad)

    def _make_watchdog(self, guard, data_health
                       ) -> Optional["guard_lib.StallWatchdog"]:
        if self.cfg.dispatch_timeout_s <= 0:
            return None
        return guard_lib.StallWatchdog(
            self.cfg.dispatch_timeout_s,
            health=guard.health if guard is not None else None,
            data_health=data_health, abort=self.watchdog_abort).start()

    def fit(
        self,
        state: TrainState,
        batches: Iterable[Dict[str, np.ndarray]],
        *,
        hooks: Optional[list] = None,
        max_steps: Optional[int] = None,
        on_log: Optional[Callable[[int, float, float], None]] = None,
        guard: Optional["guard_lib.NonFiniteGuard"] = None,
    ) -> Tuple[TrainState, Dict[str, float]]:
        """Run the train loop over an iterable of host batches.

        Dispatches ``cfg.steps_per_loop`` optimizer steps per host round trip
        (one stacked transfer + one lax.scan program); hooks fire once per
        dispatch with ``metrics["steps_done"]`` = number of steps taken.

        ``guard`` (a :class:`guard_lib.NonFiniteGuard`) enables the
        non-finite policy: under ``abort`` it piggybacks on the log-cadence
        loss sync; under ``skip``/``rollback`` every dispatch is checked
        before its update is accepted — a skip restores the pre-dispatch
        state and fires no hooks (the dropped dispatch never happened), a
        rollback raises :class:`guard_lib.RollbackSignal` for the task
        driver to restore the last checkpoint.
        """
        cfg = self.cfg
        k = max(cfg.steps_per_loop, 1)
        world = jax.process_count() if self.mesh_info.mesh is not None else 1
        src_health = getattr(batches, "health", None)
        if max_steps is not None:
            import itertools  # noqa: PLC0415
            batches = itertools.islice(iter(batches), max_steps)
        depth = cfg.transfer_ahead
        # Device staging ring: every staging-thread transfer below routes
        # through it (via _staged_put), fencing on slot reuse — 2 slots =
        # transfer/compute overlap, 1 slot = serialized A/B baseline.
        ring = _StagingRing(cfg.staging_buffers)
        self._ring = ring
        if self._tier is not None:
            # Hot/cold tiering: plan + prefetch + slot remap on the staging
            # thread (single-process single-device by construction).
            staged_iter = self._stage_tiered(batches, k, depth)
        elif world > 1:
            # Lockstep min-truncate protocol + background transfer: all
            # collectives (the count allgathers AND the step programs) are
            # enqueued on THIS thread in the same order on every rank; only
            # the process-local host->device transfers run ahead on the
            # staging thread (VERDICT r3 #1: previously depth was forced to
            # 0 here, serializing transfer with dispatch).
            staged_iter = self._stage_multiprocess(batches, k, depth)
        else:
            staged_iter = self._stage(batches, k, depth)
        guard_active = guard is not None and guard.per_dispatch
        watchdog = self._make_watchdog(guard, src_health)
        last_loss = float("nan")
        t0 = time.time()
        examples_since_log = 0
        n_steps = 0
        m: Dict[str, Any] = {}
        prev_state: Optional[TrainState] = None
        meter = prof_lib.ThroughputMeter()
        comm_applies = 0
        try:
            for dev_batch, steps_done, local_ex in staged_iter:
                if self._tier is not None:
                    # Install this dispatch's fetched cold rows BEFORE the
                    # guard's prev_state snapshot: a skipped dispatch then
                    # still retains its installs, keeping the directory and
                    # the device cache consistent.
                    state = self._tier.apply_next(state)
                if guard_active:
                    # Donation is off under skip (see __init__), so the
                    # pre-dispatch state stays valid for a dropped update.
                    prev_state, prev_m = state, m
                with trace_lib.span("train.dispatch", steps=steps_done,
                                    examples=local_ex):
                    if steps_done == 1:
                        state, m = self.train_step(state, dev_batch)
                    else:
                        state, m = self.multi_step(state, dev_batch)
                # Slot fence + comms accounting BEFORE the guard verdict: a
                # skipped dispatch still occupied its staging slot and its
                # collectives still crossed the fabric.
                ring.retire(m["loss"])
                comm_applies += (steps_done // self._accum
                                 + steps_done % self._accum)
                if guard_active:
                    verdict = self._guard_verdict(guard, state, m)
                    if verdict == "skip":
                        # The poisoned batch is consumed; its update is not.
                        # No hooks, no step count: the dispatch never
                        # happened as far as checkpoints/logs are concerned.
                        state, m = prev_state, prev_m
                        if watchdog is not None:
                            watchdog.beat(n_steps)
                        continue
                    if verdict == "rollback":
                        raise guard_lib.RollbackSignal(int(state.step))
                prev_steps = n_steps
                n_steps += steps_done
                examples_since_log += local_ex * world
                meter.update(local_ex * world, steps_done)
                if watchdog is not None:
                    watchdog.beat(n_steps)
                if cfg.log_steps and (n_steps // cfg.log_steps
                                      > prev_steps // cfg.log_steps):
                    loss = float(m["loss"])  # device sync, bounded by log cadence
                    gstep = int(state.step)
                    last_loss = loss
                    if guard is not None and not guard_active:
                        # abort policy: reuse the loss scalar this log line
                        # already synced — zero extra dispatch cost.
                        guard.observe(
                            loss, gstep,
                            params_bad=(guard.params_nonfinite(state)
                                        if math.isfinite(loss) else False))
                    dt = time.time() - t0
                    eps = examples_since_log / max(dt, 1e-9)
                    ulog.info(
                        f"step={gstep} loss={loss:.5f} examples/sec={eps:,.0f}")
                    health = getattr(batches, "health", None)
                    if health is not None and health.consume_dirty():
                        # Fault events (healed retries / skipped records) since
                        # the last log line — same cadence as the loss log.
                        ulog.info(f"data health: {health.summary()}")
                    if on_log is not None:
                        # Same cadence as the log line: loss/step were already
                        # synced above, so the callback adds no device reads.
                        on_log(gstep, loss, eps)
                    t0 = time.time()
                    examples_since_log = 0
                if hooks:
                    m = dict(m)
                    m["steps_done"] = steps_done
                    for hook in hooks:
                        hook(state, m)
        finally:
            if watchdog is not None:
                watchdog.stop()
            # Unblock a staging thread parked on a slot fence before closing
            # the generator (close joins the prefetch thread).
            ring.close()
            self._ring = None
            # A mid-loop exception (rollback, preemption, abort) abandons the
            # staging generator; close it so prefetch threads, input-service
            # workers and file handles release before any retry attempt.
            close = getattr(staged_iter, "close", None)
            if close is not None:
                close()
        if n_steps:
            # Fold the async-dispatch drain into the measurement window so
            # the meter reports completed-on-device throughput, not host
            # dispatch rate.
            jax.block_until_ready(m["loss"])
            meter.record_drain()
        if np.isnan(last_loss) and n_steps:
            last_loss = float(m["loss"])
        out = {"loss": last_loss, "steps": float(n_steps)}
        out.update({k_: v for k_, v in meter.summary().items() if k_ != "steps"})
        out["staging_overlap_fraction"] = ring.overlap_fraction()
        out["staging_transfer_s"] = ring.transfer_s
        out["staging_wait_s"] = ring.wait_s
        if self.mesh_info.data_size > 1 and comm_applies:
            # Analytic comms volume of the gradient sync (the bench's
            # comms-per-example column): applies x per-apply payload.
            out["collective_applies"] = float(comm_applies)
            out["collective_bytes"] = float(
                comm_applies * self._grad_payload_bytes())
            out["collective_strategy"] = (
                "hierarchical" if self._hier_groups is not None else "flat")
        return state, out

    # ------------------------------------------------------------------
    # Device-resident dataset mode
    # ------------------------------------------------------------------
    # The decoded epoch lives in device memory; each dispatch gathers its
    # batches by row index ON DEVICE, so per-dispatch host->device traffic
    # is one int32 scalar (the cursor) instead of k*B records. The epoch's
    # emission order is computed on host exactly as the staged pooled path
    # would emit it, so with mesh=None the trajectory is bit-identical to
    # ``fit`` over the same pipeline (the CPU parity test pins this).

    @staticmethod
    def _device_memory_bytes() -> int:
        """Per-device memory limit, or a 16 GiB assumption where the
        backend doesn't report one (CPU): the budget check then still
        exercises deterministically via device_dataset_hbm_fraction."""
        try:
            stats = jax.devices()[0].memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0))
            if limit > 0:
                return limit
        except Exception:
            pass
        return 16 << 30

    def device_dataset_ineligible(self, pipe) -> Optional[str]:
        """None when ``fit_device_resident`` can reproduce the staged run
        for this pipeline, else a human-readable disqualifier (the caller
        warns and falls back to the staged path)."""
        cfg = self.cfg
        if self._multitask:
            return "multi-task run (the decoded-cache column set carries a "\
                   "single label column)"
        if jax.process_count() > 1:
            return "multi-process run (device columns would need per-host "\
                   "record sharding)"
        if self.mesh_info.model_size > 1:
            return "model-parallel mesh (row-sharded embedding lookups use "\
                   "the shard_map step path)"
        if getattr(pipe, "decoded_cache", "off") == "off":
            return "pipeline has no decoded cache (device upload reads the "\
                   "cached columns)"
        if getattr(pipe, "skip_batches", 0):
            return "resume skip_batches offset pending (staged path owns "\
                   "the trained-prefix drop)"
        try:
            cols = pipe.decoded_epoch_columns()
        except Exception as exc:  # cache build failed: surface via staged path
            return f"decoded cache unavailable ({exc})"
        n = cols.num_records
        if n == 0:
            return "empty dataset"
        k = max(cfg.steps_per_loop, 1)
        if pipe.shuffle and n >= max(pipe.shuffle_buffer, k * pipe.batch_size):
            return (f"shuffle pool smaller than the epoch ({n} records): "
                    "pool drain order depends on chunk arrival and cannot "
                    "be reproduced as a device gather")
        per_device = (cols.nbytes() // max(self.mesh_info.data_size, 1)
                      + n * 4)  # columns (row-sharded) + replicated index
        budget = int(self._device_memory_bytes()
                     * cfg.device_dataset_hbm_fraction)
        if per_device > budget:
            return (f"decoded epoch needs ~{per_device / 2**20:.1f} MiB "
                    f"per device, over the {budget / 2**20:.1f} MiB budget "
                    f"(device_dataset_hbm_fraction="
                    f"{cfg.device_dataset_hbm_fraction})")
        return None

    def _dd_upload(self, pipe) -> Dict[str, jax.Array]:
        """Upload the cached columns once per fingerprint; later epochs
        (and later fit calls over the same data) reuse the device copy."""
        fp = pipe.decoded_cache_fingerprint()
        if self._dd_cols is not None and self._dd_cols[0] == fp:
            return self._dd_cols[1]
        cols = pipe.decoded_epoch_columns()
        host = {"label": np.ascontiguousarray(cols.labels, np.float32),
                "feat_ids": np.ascontiguousarray(cols.ids, np.int32),
                "feat_vals": np.ascontiguousarray(cols.vals, np.float32)}
        mi = self.mesh_info
        if mi.mesh is None:
            dev = jax.device_put(host)
        else:
            # Single-process data mesh: rows sharded over 'data' (padding
            # rows are never indexed — every gather index is < n).
            pad = (-cols.num_records) % mi.data_size
            if pad:
                host = {key: np.concatenate(
                    [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                    for key, v in host.items()}
            dev = {key: jax.device_put(v, mi.sharding(
                P(mesh_lib.DATA_AXIS, *([None] * (v.ndim - 1)))))
                for key, v in host.items()}
        self._dd_cols = (fp, dev)
        return dev

    def _dd_put_indices(self, idx: np.ndarray) -> jax.Array:
        mi = self.mesh_info
        if mi.mesh is None:
            return jax.device_put(idx)
        return jax.device_put(idx, mi.sharding(P(None)))

    def _dd_program(self, m_steps: int, bsz: int) -> Callable:
        """Compiled ``(state, cols, idx, start) -> (state, metrics)``: slice
        ``m_steps*bsz`` emission indices at the cursor, gather the rows on
        device, scan the train step over them (same rng folding and metric
        convention as ``multi_step``). ``start`` is a traced scalar, so one
        compile serves every cursor position of this shape."""
        key = (m_steps, bsz)
        prog = self._dd_programs.get(key)
        if prog is not None:
            return prog

        def run(state: TrainState, cols, idx, start):
            sel = jax.lax.dynamic_slice_in_dim(idx, start, m_steps * bsz)
            sel = sel.reshape(m_steps, bsz)

            def body(st, s):
                batch = {"label": cols["label"][s],
                         "feat_ids": cols["feat_ids"][s],
                         "feat_vals": cols["feat_vals"][s]}
                new_st, m = self._step_impl(
                    st, batch, data_axis=None, shard_axis=None)
                return new_st, jnp.stack((m["loss"], m["xent"]))

            state2, ms = jax.lax.scan(body, state, sel)
            return state2, {"loss": ms[-1, 0], "xent": ms[-1, 1]}

        # Plain jit even under a (pure-data) mesh: inputs carry their
        # shardings and GSPMD partitions the gather + step; the global-mean
        # gradient math is identical to the single-device formulation.
        prog = jax.jit(run, donate_argnums=(0,) if self._donate_state else ())
        self._dd_programs[key] = prog
        return prog

    def fit_device_resident(
        self,
        state: TrainState,
        pipe,
        *,
        hooks: Optional[list] = None,
        max_steps: Optional[int] = None,
        on_log: Optional[Callable[[int, float, float], None]] = None,
        guard: Optional["guard_lib.NonFiniteGuard"] = None,
    ) -> Tuple[TrainState, Dict[str, float]]:
        """Train with the whole decoded dataset resident on device.

        Callers must have cleared :meth:`device_dataset_ineligible` first.
        Mirrors ``fit``'s contract: same dispatch grouping as the staged
        pooled pipeline (k-step superbatches, then single batches, then the
        short remainder unless ``drop_remainder``), same hook/log/meter
        cadence, same guard semantics, same return dict.
        """
        cfg = self.cfg
        k = max(cfg.steps_per_loop, 1)
        bs = pipe.batch_size
        cols = pipe.decoded_epoch_columns()
        n = cols.num_records
        dev_cols = self._dd_upload(pipe)
        remaining = max_steps
        meter = prof_lib.ThroughputMeter()
        last_loss = float("nan")
        t0 = time.time()
        examples_since_log = 0
        n_steps = 0
        m: Dict[str, Any] = {}
        health = getattr(pipe, "health", None)
        guard_active = guard is not None and guard.per_dispatch
        watchdog = self._make_watchdog(guard, health)
        try:
            for e in range(pipe.num_epochs):
                if remaining is not None and remaining <= 0:
                    break
                epoch = e + getattr(pipe, "epoch_offset", 0)
                idx_dev = self._dd_put_indices(
                    pipe.device_epoch_indices(epoch, k))
                # The staged pool's emission plan for one epoch, as batch
                # sizes.
                n_batches = n // bs
                r = n - n_batches * bs
                sizes = [bs] * n_batches
                if r and not pipe.drop_remainder:
                    sizes.append(r)
                if remaining is not None:
                    sizes = sizes[:remaining]
                    remaining -= len(sizes)
                start = 0
                i = 0
                while i < len(sizes):
                    if (sizes[i] == bs and i + k <= len(sizes)
                            and sizes[i + k - 1] == bs):
                        mm, bsz = k, bs
                    else:
                        mm, bsz = 1, sizes[i]
                    prog = self._dd_program(mm, bsz)
                    if guard_active:
                        prev_state, prev_m = state, m
                    state, m = prog(state, dev_cols, idx_dev, np.int32(start))
                    # The dispatch's rows are consumed whether or not its
                    # update survives the guard.
                    start += mm * bsz
                    i += mm
                    if guard_active:
                        verdict = self._guard_verdict(guard, state, m)
                        if verdict == "skip":
                            state, m = prev_state, prev_m
                            if watchdog is not None:
                                watchdog.beat(n_steps)
                            continue
                        if verdict == "rollback":
                            raise guard_lib.RollbackSignal(int(state.step))
                    prev_steps = n_steps
                    n_steps += mm
                    examples_since_log += mm * bsz
                    meter.update(mm * bsz, mm)
                    if watchdog is not None:
                        watchdog.beat(n_steps)
                    if cfg.log_steps and (n_steps // cfg.log_steps
                                          > prev_steps // cfg.log_steps):
                        loss = float(m["loss"])
                        gstep = int(state.step)
                        last_loss = loss
                        if guard is not None and not guard_active:
                            guard.observe(
                                loss, gstep,
                                params_bad=(guard.params_nonfinite(state)
                                            if math.isfinite(loss) else False))
                        dt = time.time() - t0
                        eps = examples_since_log / max(dt, 1e-9)
                        ulog.info(f"step={gstep} loss={loss:.5f} "
                                  f"examples/sec={eps:,.0f}")
                        if health is not None and health.consume_dirty():
                            ulog.info(f"data health: {health.summary()}")
                        if on_log is not None:
                            on_log(gstep, loss, eps)
                        t0 = time.time()
                        examples_since_log = 0
                    if hooks:
                        m = dict(m)
                        m["steps_done"] = mm
                        for hook in hooks:
                            hook(state, m)
        finally:
            if watchdog is not None:
                watchdog.stop()
        if n_steps:
            jax.block_until_ready(m["loss"])
            meter.record_drain()
        if np.isnan(last_loss) and n_steps:
            last_loss = float(m["loss"])
        out = {"loss": last_loss, "steps": float(n_steps)}
        out.update({k_: v for k_, v in meter.summary().items() if k_ != "steps"})
        return state, out

    def lockstep_batches(
        self,
        batches: Iterable[Dict[str, np.ndarray]],
        make_dummy: Callable[[], Dict[str, np.ndarray]],
        *,
        rounds_of: Optional[int] = None,
    ) -> Iterator[Tuple[Dict[str, np.ndarray], bool]]:
        """Yield ``(batch, is_real)`` with IDENTICAL yield counts across
        ranks — the shared lockstep mechanism for collective step functions
        over ragged per-rank shards (used by ``evaluate`` and the infer
        task; ``fit`` uses min-truncation instead because dummy batches
        would corrupt optimizer state).

        Each round every rank pulls up to ``rounds_of`` local batches and
        allgathers its count once; ranks below the round maximum top up with
        ``make_dummy()`` batches (callers mask them via zero weight or by
        discarding the output). Terminates when every rank is exhausted.
        One cross-host exchange per round, not per batch; all collectives
        are issued from the caller's thread in deterministic order.
        """
        from jax.experimental import multihost_utils  # noqa: PLC0415
        import itertools  # noqa: PLC0415

        k = max(self.cfg.steps_per_loop, 1) if rounds_of is None else rounds_of
        it = iter(batches)
        try:
            while True:
                group = list(itertools.islice(it, k))
                counts = np.asarray(multihost_utils.process_allgather(
                    np.asarray([len(group)])))
                top = int(counts.max())
                if top == 0:
                    return  # every rank exhausted
                for b in group:
                    yield b, True
                for _ in range(top - len(group)):
                    yield make_dummy(), False
        finally:
            # A consumer exception mid-eval/infer abandons the source; close
            # it so prefetch threads and file handles release promptly.
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _dummy_eval_batch(self, local_bs: int) -> Dict[str, np.ndarray]:
        """All-zero-weight batch: contributes nothing to AUC/loss."""
        hist_len = (self.cfg.history_max_len
                    if getattr(self.model, "uses_history", False) else 0)
        return {**zero_batch(self.cfg.field_size, local_bs,
                             num_labels=len(self._task_names),
                             hist_len=hist_len),
                "weight": np.zeros((local_bs, 1), np.float32)}

    def evaluate(
        self,
        state: TrainState,
        batches: Iterable[Dict[str, np.ndarray]],
    ) -> Dict[str, float]:
        """Streaming eval: AUC (reference's sole metric, :249-251) + mean loss.

        Collective-safe on ragged shards: every batch is padded to the
        compiled shape with a zero-weight tail (so NO record is dropped and
        none double-counts), and under multi-process ``lockstep_batches``
        keeps the eval_step collectives aligned — a rank whose shard is
        exhausted feeds zero-weight dummy batches until every rank is done."""
        if self._tier is not None:
            # Offline eval runs the ordinary dense forward over the full
            # table (flushed hot rows + cold store).
            state = self._tier.densified(state)
        cfg = self.cfg
        world = jax.process_count() if self.mesh_info.mesh is not None else 1
        local_bs = cfg.batch_size // world
        if cfg.batch_size % world != 0:
            raise ValueError(
                f"global batch_size={cfg.batch_size} not divisible by "
                f"process_count={world}")
        if self._multitask:
            acc = ({name: metrics_lib.auc_init(cfg.auc_num_thresholds)
                    for name in self._task_names},
                   metrics_lib.mean_init())
        else:
            acc = (metrics_lib.auc_init(cfg.auc_num_thresholds),
                   metrics_lib.mean_init())
        acc = jax.device_put(acc)
        n = 0
        if world > 1:
            staged = ((b if not real else _with_weight(b, local_bs), real)
                      for b, real in self.lockstep_batches(
                          batches, lambda: self._dummy_eval_batch(local_bs)))
        else:
            staged = ((_with_weight(b, local_bs), True) for b in batches)
        # K batches per dispatch (one stacked transfer + one lax.scan
        # program, VERDICT r3 #2) with single-step fallback for the short
        # tail group and for non-uniform shapes (an oversize batch jit-
        # respecializes on the single-step path). Group boundaries are
        # rank-identical under multi-process: lockstep_batches dummy-fills
        # every round to the same count on every rank, so the k-grouping —
        # and therefore the dispatched program sequence — stays aligned.
        k = max(cfg.steps_per_loop, 1)
        dispatched = 0
        t_start = time.time()
        group: list = []

        def flush(acc, dispatched):
            if len(group) == k and k > 1 and len(
                    {g["label"].shape[0] for g in group}) == 1:
                acc = self.eval_multi_step(
                    state, self.put_superbatch(group), acc)
                dispatched += 1
            else:
                for g in group:
                    acc = self.eval_step(state, self.put_batch(g), acc)
                    dispatched += 1
            group.clear()
            return acc, dispatched

        t_first_done = None  # wall clock after the first dispatch returned
        n_first = 0          # real batches covered by that first dispatch
        for batch, real in staged:
            group.append(batch)
            n += int(real)  # real local batches only (dummies excluded)
            if len(group) == k:
                acc, dispatched = flush(acc, dispatched)
                if t_first_done is None:
                    t_first_done = time.time()
                    n_first = n
        if group:
            acc, dispatched = flush(acc, dispatched)
            if t_first_done is None:
                t_first_done = time.time()
                n_first = n
        if dispatched == 0:
            # Nothing ran anywhere (a rank that only fed dummies still has a
            # valid psum-merged global acc and must NOT zero it out).
            out = {"auc": 0.0, "loss": 0.0, "batches": 0.0,
                   "examples_per_sec": 0.0,
                   "examples_per_sec_steady": 0.0}
            if self._multitask:
                out.update({f"auc_{name}": 0.0 for name in self._task_names})
            return out
        auc_state, loss_state = acc
        if self._multitask:
            per_task_auc = {
                name: float(metrics_lib.auc_compute(auc_state[name]))
                for name in self._task_names}  # device sync
            auc = per_task_auc[self._task_names[0]]
        else:
            per_task_auc = None
            auc = float(metrics_lib.auc_compute(auc_state))  # device sync
        n_examples = float(loss_state.count)  # global weighted count
        # Wall includes the final device sync above, so the rate is
        # completed-on-device, not dispatch rate. First-call numbers include
        # compile; steady-state callers (e.g. per-epoch eval after epoch 1)
        # see the amortized scanned-dispatch rate (VERDICT r3 #2).
        elapsed = max(time.time() - t_start, 1e-9)
        raw_eps = n_examples / elapsed
        # Steady-state rate: exclude the first dispatch (whose return time
        # bounds the jit compile) from the window and its batches from the
        # numerator. On a single-dispatch eval there is no steady window —
        # report the raw rate so the key is always present and comparable.
        first_elapsed = (t_first_done - t_start) if t_first_done else 0.0
        if dispatched > 1 and n > n_first and elapsed - first_elapsed > 1e-9:
            steady_eps = (n_examples * (n - n_first) / n) / (
                elapsed - first_elapsed)
        else:
            steady_eps = raw_eps
        out = {
            "auc": auc,
            "loss": float(metrics_lib.mean_compute(loss_state)),
            "batches": float(n),
            "examples_per_sec": raw_eps,
            "examples_per_sec_steady": steady_eps,
        }
        if per_task_auc is not None:
            # Named per-task AUCs alongside the headline (= first task).
            out.update({f"auc_{name}": v for name, v in per_task_auc.items()})
        return out

    def _local_rows(self, arr: jax.Array) -> np.ndarray:
        """This process's rows of a data-sharded output. Fully-addressable
        arrays (single process) fetch whole; otherwise concatenate the
        addressable row-shards in index order, deduplicating replicas across
        the 'model' axis."""
        if arr.is_fully_addressable:
            return np.asarray(jax.device_get(arr))
        seen: Dict[int, np.ndarray] = {}
        for s in arr.addressable_shards:
            start = s.index[0].start or 0
            if start not in seen:
                seen[start] = np.asarray(s.data)
        return np.concatenate([seen[k] for k in sorted(seen)])

    def _local_rows_stacked(self, arr: jax.Array) -> np.ndarray:
        """This process's rows of a [K, B]-stacked data-sharded output as a
        [K, local_B] array (axis 1 carries the 'data' sharding; axis 0 is
        the scan/stack dimension, replicated)."""
        if arr.is_fully_addressable:
            return np.asarray(jax.device_get(arr))
        seen: Dict[int, np.ndarray] = {}
        for s in arr.addressable_shards:
            start = s.index[1].start or 0
            if start not in seen:
                seen[start] = np.asarray(s.data)
        return np.concatenate([seen[k] for k in sorted(seen)], axis=1)

    def predict(
        self,
        state: TrainState,
        batches: Iterable[Dict[str, np.ndarray]],
    ) -> Iterator[np.ndarray]:
        """Yield per-batch probability vectors for this process's rows
        (reference infer task :445-449).

        Uniform-shaped batches are grouped ``steps_per_loop`` at a time into
        ONE stacked transfer + one scanned program (``predict_multi_step``,
        VERDICT r3 #2); short or ragged groups fall back to per-batch
        dispatch. A caller feeding a constant-shape padded stream (the infer
        task) gets the amortized path automatically, and per-batch yield
        order is preserved either way."""
        if self._tier is not None:
            state = self._tier.densified(state)
        k = max(self.cfg.steps_per_loop, 1)
        group: list = []
        for batch in batches:
            group.append(batch)
            if len(group) == k:
                yield from self._predict_group(state, group)
                group = []
        if group:
            yield from self._predict_group(state, group)

    def _predict_group(self, state: TrainState, group: list
                       ) -> Iterator[np.ndarray]:
        if len(group) > 1 and len({g["label"].shape[0] for g in group}) == 1:
            probs = self.predict_multi_step(state, self.put_superbatch(group))
            rows = self._local_rows_stacked(probs)
            for i in range(rows.shape[0]):
                yield rows[i]
        else:
            for g in group:
                yield self._local_rows(
                    self.predict_step(state, self.put_batch(g)))
