"""Trainer: jitted/shard_mapped train-eval-predict step functions + fit loop.

TPU-native replacement for the reference's Estimator driver (L3):

  * One *synchronous SPMD* mechanism replaces both reference backends: the
    step function is ``shard_map``-ped over the ``('data','model')`` mesh —
    gradients are ``pmean``-ed over 'data' (vs Horovod's NCCL ring allreduce,
    X2) and embedding lookups are masked-gather + ``psum`` over 'model'
    row-shards (vs the gRPC parameter server, X1). On one device it's a plain
    ``jax.jit``.
  * Replicated initialization from one PRNG key == Horovod's
    ``BroadcastGlobalVariablesHook(0)`` (reference 2-hvd-gpu/...py:372).
  * Everything under jit is static-shaped; one compiled program per task.

The fit loop feeds host batches via ``jax.make_array_from_process_local_data``
(multi-host-correct) and logs loss/examples-per-sec every ``log_steps``
(reference flag :47).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ..config import Config
from ..models import get_model
from ..parallel import mesh as mesh_lib
from ..utils import logging as ulog
from ..utils import profiling as prof_lib
from . import metrics as metrics_lib
from . import optimizers as opt_lib
from .state import TrainState


class Trainer:
    """Builds and runs the compiled train/eval/predict step functions."""

    def __init__(self, cfg: Config, mesh_info: Optional[mesh_lib.MeshInfo] = None):
        self.cfg = cfg
        self.model = get_model(cfg)
        self.mesh_info = mesh_info if mesh_info is not None else mesh_lib.build_mesh(cfg)
        self.tx = opt_lib.build_optimizer(cfg, world_size=self.mesh_info.data_size)
        self._specs: Optional[Dict[str, Any]] = None
        self._train_step: Optional[Callable] = None
        self._eval_step: Optional[Callable] = None
        self._predict_step: Optional[Callable] = None

    # ------------------------------------------------------------------
    # State creation / placement
    # ------------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None) -> TrainState:
        """Replicated-by-construction init: every process derives identical
        params from the same seed (broadcast-hook analog)."""
        seed = self.cfg.seed if seed is None else seed
        rng = jax.random.PRNGKey(seed)
        k_init, k_state = jax.random.split(rng)
        params, model_state = self.model.init(k_init)
        opt_state = self.tx.init(params)
        state = TrainState.create(params, opt_state, model_state, k_state)
        return self._place(state)

    def _state_specs(self, state: TrainState) -> TrainState:
        param_specs = mesh_lib.param_pspecs(
            state.params, self.model.embedding_param_names(),
            self.mesh_info.model_size)
        opt_specs = mesh_lib.opt_state_pspecs(
            state.opt_state, state.params, param_specs)
        mstate_specs = jax.tree.map(lambda _: P(), state.model_state)
        return TrainState(
            step=P(), params=param_specs, opt_state=opt_specs,
            model_state=mstate_specs, rng=P())

    def _place(self, state: TrainState) -> TrainState:
        """Apply NamedShardings (row-sharded embeddings, replicated rest)."""
        mi = self.mesh_info
        if mi.mesh is None:
            return jax.device_put(state)
        specs = self._state_specs(state)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, mi.sharding(s)), state, specs)

    def put_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        """Host numpy batch -> device array sharded over the data axis.

        Under multi-process each process passes its local shard of the global
        batch; ``make_array_from_process_local_data`` assembles the global
        array (the pod-sharded tf.data->device-iterator analog, X3)."""
        mi = self.mesh_info
        if mi.mesh is None:
            return jax.device_put(batch)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                mi.sharding(P(mesh_lib.DATA_AXIS, *([None] * (x.ndim - 1)))), x),
            dict(batch))

    # ------------------------------------------------------------------
    # Step functions
    # ------------------------------------------------------------------
    def _loss_terms(self, params, model_state, batch, *, train, rng,
                    shard_axis, data_axis):
        logits, new_mstate = self.model.apply(
            params, model_state, batch["feat_ids"], batch["feat_vals"],
            train=train, rng=rng, shard_axis=shard_axis, data_axis=data_axis)
        labels = batch["label"].reshape(-1).astype(jnp.float32)
        if self.cfg.loss_type == "log_loss":
            xent = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))
        else:  # square_loss (reference flag loss_type)
            xent = jnp.mean(jnp.square(jax.nn.sigmoid(logits) - labels))
        return logits, xent, new_mstate

    def _make_train_step(self) -> Callable:
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None
        data_axis = mi.data_axis

        def step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
            rng = jax.random.fold_in(state.rng, state.step)
            if data_axis is not None:
                # Distinct dropout per data shard; identical across model
                # shards (keeps activations replicated over 'model').
                rng = jax.random.fold_in(rng, jax.lax.axis_index(data_axis))

            def loss_fn(params):
                _, xent, new_mstate = self._loss_terms(
                    params, state.model_state, batch, train=True, rng=rng,
                    shard_axis=shard_axis, data_axis=data_axis)
                if data_axis is not None:
                    # THE gradient sync point: the loss is made a *global*
                    # scalar (mean over the data axis); differentiating it
                    # under shard_map's replication-aware AD yields gradients
                    # with the cross-replica psum already inserted by XLA —
                    # this replaces hvd.DistributedOptimizer's NCCL allreduce
                    # (2-hvd-gpu/...py:262) and the PS push/pull (X1).
                    xent = jax.lax.pmean(xent, data_axis)
                l2 = self.model.l2_loss(params)
                if shard_axis is not None:
                    # l2 over the full row-sharded table (invariant scalar).
                    l2 = jax.lax.psum(l2, shard_axis)
                return xent + l2, (xent, l2, new_mstate)

            (_, (xent, l2, new_mstate)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1, params=new_params, opt_state=new_opt,
                model_state=new_mstate)
            return new_state, {"loss": xent + l2, "xent": xent}

        if mi.mesh is None:
            return jax.jit(step, donate_argnums=0)
        specs = self._dummy_specs()
        return jax.jit(
            shard_map(
                step, mesh=mi.mesh,
                in_specs=(specs["state"], specs["batch"]),
                out_specs=(specs["state"], P()),
                check_vma=True),
            donate_argnums=0)

    def _make_eval_step(self) -> Callable:
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None
        data_axis = mi.data_axis

        def step(state: TrainState, batch, acc):
            auc_state, loss_state = acc
            logits, xent, _ = self._loss_terms(
                state.params, state.model_state, batch, train=False, rng=None,
                shard_axis=shard_axis, data_axis=data_axis)
            probs = jax.nn.sigmoid(logits)
            labels = batch["label"].reshape(-1)
            delta = metrics_lib.auc_update(
                metrics_lib.auc_init(self.cfg.auc_num_thresholds), probs, labels)
            n = jnp.float32(probs.shape[0])
            loss_total = xent * n
            if data_axis is not None:
                delta = metrics_lib.auc_psum(delta, data_axis)
                loss_total = jax.lax.psum(loss_total, data_axis)
                n = jax.lax.psum(n, data_axis)
            new_auc = metrics_lib.auc_merge(auc_state, delta)
            new_loss = metrics_lib.MeanState(
                total=loss_state.total + loss_total, count=loss_state.count + n)
            return (new_auc, new_loss)

        if mi.mesh is None:
            return jax.jit(step)
        specs = self._dummy_specs()
        return jax.jit(shard_map(
            step, mesh=mi.mesh,
            in_specs=(specs["state"], specs["batch"], P()),
            out_specs=P(),
            check_vma=True))

    def _make_predict_step(self) -> Callable:
        mi = self.mesh_info
        shard_axis = mi.model_axis if mi.model_size > 1 else None

        def step(state: TrainState, batch):
            logits, _ = self.model.apply(
                state.params, state.model_state, batch["feat_ids"],
                batch["feat_vals"], train=False, rng=None,
                shard_axis=shard_axis, data_axis=mi.data_axis)
            return jax.nn.sigmoid(logits)

        if mi.mesh is None:
            return jax.jit(step)
        specs = self._dummy_specs()
        return jax.jit(shard_map(
            step, mesh=mi.mesh,
            in_specs=(specs["state"], specs["batch"]),
            out_specs=P(mesh_lib.DATA_AXIS),
            check_vma=True))

    def _dummy_specs(self) -> Dict[str, Any]:
        if self._specs is None:
            # Build spec trees from an abstract state (no device memory).
            abstract = jax.eval_shape(
                lambda: self._abstract_state_for_specs())
            state_specs = self._state_specs(abstract)
            batch = {
                "feat_ids": jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, self.cfg.field_size), jnp.int32),
                "feat_vals": jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, self.cfg.field_size), jnp.float32),
                "label": jax.ShapeDtypeStruct(
                    (self.cfg.batch_size, 1), jnp.float32),
            }
            self._specs = {
                "state": state_specs,
                "batch": mesh_lib.batch_pspecs(batch),
            }
        return self._specs

    def _abstract_state_for_specs(self) -> TrainState:
        rng = jax.random.PRNGKey(0)
        params, model_state = self.model.init(rng)
        opt_state = self.tx.init(params)
        return TrainState.create(params, opt_state, model_state, rng)

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------
    @property
    def train_step(self) -> Callable:
        if self._train_step is None:
            self._train_step = self._make_train_step()
        return self._train_step

    @property
    def eval_step(self) -> Callable:
        if self._eval_step is None:
            self._eval_step = self._make_eval_step()
        return self._eval_step

    @property
    def predict_step(self) -> Callable:
        if self._predict_step is None:
            self._predict_step = self._make_predict_step()
        return self._predict_step

    def fit(
        self,
        state: TrainState,
        batches: Iterable[Dict[str, np.ndarray]],
        *,
        hooks: Optional[list] = None,
        max_steps: Optional[int] = None,
    ) -> Tuple[TrainState, Dict[str, float]]:
        """Run the train loop over an iterable of host batches."""
        cfg = self.cfg
        step_fn = self.train_step
        last_loss = float("nan")
        t0 = time.time()
        examples_since_log = 0
        n_steps = 0
        meter = prof_lib.ThroughputMeter()
        for batch in batches:
            dev_batch = self.put_batch(batch)
            state, m = step_fn(state, dev_batch)
            n_steps += 1
            global_examples = batch["label"].shape[0] * (
                jax.process_count() if self.mesh_info.mesh is not None else 1)
            examples_since_log += global_examples
            meter.update(global_examples)
            step_now = n_steps
            if cfg.log_steps and step_now % cfg.log_steps == 0:
                loss = float(m["loss"])
                last_loss = loss
                dt = time.time() - t0
                eps = examples_since_log / max(dt, 1e-9)
                ulog.info(
                    f"step={int(state.step)} loss={loss:.5f} "
                    f"examples/sec={eps:,.0f}")
                t0 = time.time()
                examples_since_log = 0
            for hook in hooks or []:
                hook(state, m)
            if max_steps is not None and n_steps >= max_steps:
                break
        if np.isnan(last_loss) and n_steps:
            last_loss = float(m["loss"])
        out = {"loss": last_loss, "steps": float(n_steps)}
        out.update({k: v for k, v in meter.summary().items() if k != "steps"})
        return state, out

    def evaluate(
        self,
        state: TrainState,
        batches: Iterable[Dict[str, np.ndarray]],
    ) -> Dict[str, float]:
        """Streaming eval: AUC (reference's sole metric, :249-251) + mean loss."""
        acc = (metrics_lib.auc_init(self.cfg.auc_num_thresholds),
               metrics_lib.mean_init())
        acc = jax.device_put(acc)
        step_fn = self.eval_step
        n = 0
        for batch in batches:
            acc = step_fn(state, self.put_batch(batch), acc)
            n += 1
        if n == 0:
            return {"auc": 0.0, "loss": 0.0, "batches": 0.0}
        auc_state, loss_state = acc
        return {
            "auc": float(metrics_lib.auc_compute(auc_state)),
            "loss": float(metrics_lib.mean_compute(loss_state)),
            "batches": float(n),
        }

    def predict(
        self,
        state: TrainState,
        batches: Iterable[Dict[str, np.ndarray]],
    ) -> Iterator[np.ndarray]:
        """Yield per-batch probability vectors (reference infer task :445-449)."""
        step_fn = self.predict_step
        for batch in batches:
            probs = step_fn(state, self.put_batch(batch))
            yield np.asarray(jax.device_get(probs))
