"""Atomic hot model publishing for the online trainer.

The reference ships fresh models by notebook-driven redeploys; an online
trainer must instead publish servable artifacts *mid-training* without ever
exposing a half-written directory. :class:`Publisher` is a fit-loop hook:

  * **Cadence** — ``--publish_every_steps`` uses boundary-crossing
    arithmetic (like ``CheckpointManager.should_save``), so the publish
    *steps* are a deterministic function of the step sequence alone — a
    resumed run republishes the same versions an uninterrupted run would
    (the drill's bit-identity check depends on this). ``--publish_every_secs``
    adds a wall-clock cadence for workloads where steps/sec varies.
  * **Off the hot path** — the hook snapshots params to host (the one
    synchronous cost: a device_get, which must happen before the next
    dispatch donates the buffers away) and hands the I/O to the shared
    :class:`~deepfm_tpu.utils.checkpoint.AsyncSaveExecutor`. While a publish
    is in flight, due cadences are counted as skipped, not queued.
  * **Atomicity** — the artifact (delta params checkpoint + servable export,
    via ``export_serving``) is staged under a dot-prefixed temp dir in the
    publish dir, completed (marker written last), fsynced, then
    ``os.replace``d to its final ``<step>/`` name; only after that does the
    ``LATEST`` pointer move (atomic pointer write, and never backwards). A
    crash at ANY point leaves either the previous artifact set intact or a
    complete new artifact — never a partially-visible one.
  * **Longevity wiring** — :meth:`drain` lets the preemption path wait for
    an in-flight publish before exiting 42; :meth:`check_wedged` (called
    every dispatch) trips the watchdog abort (exit 43) when a publish has
    been in flight longer than ``--publish_timeout_s``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..data import fileio
from ..obs import metrics as metrics_lib
from ..obs import trace as trace_lib
from ..utils import export as export_lib
from ..utils import faults as faults_lib
from ..utils import logging as ulog
from ..utils import preempt as preempt_lib
from ..utils.checkpoint import AsyncSaveExecutor


def _default_abort(detail: str) -> None:  # pragma: no cover - kills process
    ulog.warning(f"wedged publish: {detail}; aborting (exit "
                 f"{preempt_lib.EXIT_WATCHDOG})")
    os._exit(preempt_lib.EXIT_WATCHDOG)


def _pct(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


class Publisher:
    """Fit-loop hook publishing servable artifacts on a step/time cadence."""

    def __init__(self, model, cfg, publish_dir: str, *,
                 every_steps: int = 0, every_secs: float = 0.0,
                 timeout_s: float = 600.0,
                 executor: Optional[AsyncSaveExecutor] = None,
                 clock: Callable[[], float] = time.monotonic,
                 abort: Optional[Callable[[str], None]] = None,
                 extra_export: Optional[Callable[[str], None]] = None,
                 health=None):
        self._model = model
        self._cfg = cfg
        self._dir = publish_dir
        # Ran against the staging dir BEFORE export_serving finishes it, so
        # the completion marker still certifies everything the hook wrote
        # (the cascade uses this to ship towers + candidate index alongside
        # every ranker version — rec/cascade.cascade_extra_export).
        self._extra_export = extra_export
        self.every_steps = int(every_steps)
        self.every_secs = float(every_secs)
        self.timeout_s = float(timeout_s)
        self._executor = executor if executor is not None else AsyncSaveExecutor(
            name="publisher")
        self._own_executor = executor is None
        self._clock = clock
        self._abort = abort if abort is not None else _default_abort
        self._health = health  # TrainHealth, for watchdog_aborts accounting
        fileio.makedirs(publish_dir)
        self._inflight = None          # Future of the running publish job
        self._inflight_step = -1
        self._inflight_since = 0.0
        self._last_crossed_step = 0    # step-cadence boundary bookkeeping
        self._last_pub_time = clock()  # time cadence anchors at start
        self._head_step = 0            # newest step seen (staleness metric)
        # Stats (host-side, cheap): consumed by bench + the task result.
        self.published: List[int] = []      # versions successfully published
        self.publish_failures = 0
        self.skipped_inflight = 0           # due cadences hit while busy
        self.latencies_s: List[float] = []  # submit -> artifact visible
        self.staleness_steps: List[int] = []  # head - version at completion
        # Unified registry (obs.metrics): stats() is the metric surface.
        metrics_lib.auto_register("publisher", self)

    # ------------------------------------------------------------- cadence

    def seed_cadence(self, step: int) -> None:
        """Anchor the step cadence at a restored checkpoint step, so a
        resumed run crosses exactly the boundaries a fresh run would from
        there (same seeding rule as ``CheckpointManager.should_save``)."""
        self._last_crossed_step = max(self._last_crossed_step, int(step))
        self._head_step = max(self._head_step, int(step))

    def _due(self, step: int) -> bool:
        due = False
        if self.every_steps > 0:
            if (step // self.every_steps
                    > self._last_crossed_step // self.every_steps):
                due = True
        if not due and self.every_secs > 0:
            if self._clock() - self._last_pub_time >= self.every_secs:
                due = True
        return due

    def maybe_publish(self, state, step: int) -> bool:
        """Per-dispatch hook: snapshot + submit when a cadence is due.
        Never blocks on I/O; returns True iff a publish was started."""
        step = int(step)
        self._head_step = max(self._head_step, step)
        self.check_wedged()
        if not self._due(step):
            return False
        if self._inflight is not None and not self._inflight.done():
            # Busy: drop this cadence rather than queueing a stale snapshot.
            self.skipped_inflight += 1
            self._last_crossed_step = step
            return False
        self._reap()
        self._last_crossed_step = step
        self._last_pub_time = self._clock()
        self.publish_now(state, step)
        return True

    def publish_now(self, state, step: int) -> None:
        """Snapshot ``state`` at ``step`` and publish asynchronously."""
        # Snapshot synchronously: the fit loop donates the state buffers to
        # the next dispatch, so the background job must never touch them.
        with trace_lib.span("publish.snapshot", version=int(step)):
            params = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), state.params)
            mstate = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), state.model_state)
        self._inflight_step = int(step)
        self._inflight_since = self._clock()
        self._inflight = self._executor.submit(
            self._do_publish, params, mstate, int(step))

    # ------------------------------------------------------- background job

    def _do_publish(self, params, mstate, step: int) -> Optional[str]:
        version = str(step)
        final_dir = fileio.join(self._dir, version)
        if fileio.exists(fileio.join(final_dir, export_lib.COMPLETE_MARKER)):
            # Idempotent republish (deterministic replay after a resume hits
            # the same cadence step): the bytes would be identical. Still
            # advance LATEST — a crash between the rename and the pointer
            # write heals here on the retry.
            self._advance_latest(version)
            return final_dir
        staging = fileio.join(self._dir, f".staging-{version}-{os.getpid()}")
        if fileio.isdir(staging):
            fileio.rmtree(staging)

        class _Snap:  # duck-typed TrainState view for export_serving
            pass
        snap = _Snap()
        snap.params, snap.model_state, snap.step = params, mstate, step

        # Spans run on the executor thread — complete ("X") events are
        # thread-local, so they land on the publisher's own trace row and
        # the drill's serve-vN-while-vN+1-stages overlap reads directly
        # off the merged timeline.
        with trace_lib.span("publish.stage", version=step):
            if self._extra_export is not None:
                self._extra_export(staging)
            export_lib.export_serving(self._model, snap, self._cfg, staging)
            fileio.fsync_dir(staging)
        faults_lib.check_publish_crash("before_rename")
        with trace_lib.span("publish.rename", version=step):
            fileio.replace(staging, final_dir)
            fileio.fsync_dir(self._dir)
        faults_lib.check_publish_crash("after_rename_before_latest")
        with trace_lib.span("publish.pointer", version=step):
            self._advance_latest(version)
        return final_dir

    def _advance_latest(self, version: str) -> None:
        """Move LATEST forward, never backwards: a resumed run republishing
        an old cadence step must not regress the serving pointer. Every
        actual move is recorded in the ``pointer_history.jsonl`` sidecar
        BEFORE the pointer write — a crash between the two heals on the
        retried publish because the append is tail-deduplicated."""
        current = export_lib.read_latest(self._dir)
        if current is not None:
            try:
                if int(os.path.basename(current)) >= int(version):
                    return
            except ValueError:
                pass  # non-numeric current pointer: overwrite it
        export_lib.append_pointer_event(self._dir, version, "publish")
        faults_lib.check_publish_crash("after_history_before_latest")
        export_lib.write_latest(self._dir, version)

    def history(self) -> List[Dict[str, Any]]:
        """The publish dir's pointer-history sidecar, oldest first."""
        return export_lib.pointer_history(self._dir)

    # ------------------------------------------------------------ lifecycle

    def _reap(self) -> None:
        """Collect the finished in-flight job's outcome into the stats."""
        fut, self._inflight = self._inflight, None
        if fut is None:
            return
        step, since = self._inflight_step, self._inflight_since
        self._inflight_step = -1
        try:
            result = fut.result(timeout=0)
        except Exception as e:
            self.publish_failures += 1
            ulog.warning(f"publish of step {step} failed ({e}); the previous "
                         "artifact stays live; retrying next cadence")
            return
        if result is not None:
            self.published.append(step)
            self.latencies_s.append(self._clock() - since)
            self.staleness_steps.append(max(0, self._head_step - step))

    def check_wedged(self) -> None:
        """Trip the watchdog when a publish exceeds ``timeout_s`` in flight."""
        if self._inflight is None:
            return
        if self._inflight.done():
            self._reap()
            return
        elapsed = self._clock() - self._inflight_since
        if self.timeout_s > 0 and elapsed > self.timeout_s:
            if self._health is not None:
                self._health.record_watchdog_abort()
            self._abort(
                f"publish of step {self._inflight_step} in flight for "
                f"{elapsed:.1f}s (publish_timeout_s={self.timeout_s})")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for the in-flight publish (preemption path / run end).
        True iff nothing was pending or it completed within ``timeout``."""
        fut = self._inflight
        if fut is None:
            return True
        try:
            fut.result(timeout=timeout)
        except Exception:
            pass  # failure accounting happens in _reap below
        if fut.done():
            self._reap()
            return True
        ulog.warning(f"publish of step {self._inflight_step} still in "
                     f"flight after {timeout}s drain")
        return False

    def close(self) -> None:
        self.drain(timeout=self.timeout_s if self.timeout_s > 0 else None)
        if self._own_executor:
            self._executor.close()

    def stats(self) -> Dict[str, Any]:
        return {
            "published_versions": list(self.published),
            "publish_count": len(self.published),
            "publish_failures": self.publish_failures,
            "publish_skipped_inflight": self.skipped_inflight,
            "publish_latency_p50_s": _pct(self.latencies_s, 50),
            "publish_latency_p99_s": _pct(self.latencies_s, 99),
            "publish_staleness_steps_max": (
                max(self.staleness_steps) if self.staleness_steps else None),
        }
