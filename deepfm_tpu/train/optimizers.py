"""Optimizer zoo matching the reference's four choices + world-size LR scaling.

Reference (``1-ps-cpu/...py:260-269``):
  Adam(lr, beta1=0.9, beta2=0.999, eps=1e-8)
  Adagrad(lr, initial_accumulator_value=1e-8)
  Momentum(lr, momentum=0.95)
  Ftrl(lr)  — TF defaults: lr_power=-0.5, initial_accumulator=0.1, l1=l2=0

Horovod variant scales lr by world size (``2-hvd-gpu/...py:149``); here that
is ``scale_lr_by_world`` x the data-axis size of the mesh.

FTRL has no optax built-in; ``ftrl()`` below is a custom
``GradientTransformation`` implementing FTRL-Proximal (McMahan et al. 2013),
the same update ``tf.train.FtrlOptimizer`` applies densely.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax

from ..config import Config


class FtrlState(NamedTuple):
    z: optax.Updates   # per-weight z accumulator
    n: optax.Updates   # per-weight squared-gradient accumulator


def ftrl(
    learning_rate: float,
    *,
    learning_rate_power: float = -0.5,
    initial_accumulator_value: float = 0.1,
    l1_regularization_strength: float = 0.0,
    l2_regularization_strength: float = 0.0,
    beta: float = 0.0,
) -> optax.GradientTransformation:
    """FTRL-Proximal as an optax GradientTransformation (requires params).

    w_new = 0                                  if |z| <= l1
          = -(z - sign(z)*l1) / ((beta + n_new^(-lr_power))/lr + 2*l2)  else
    with n_new = n + g^2 and z += g - (n_new^p - n^p)/lr * w, p = -lr_power.
    """
    if learning_rate_power > 0:
        raise ValueError("learning_rate_power must be <= 0")
    p = -learning_rate_power  # 0.5 for the default sqrt schedule

    def init_fn(params: optax.Params) -> FtrlState:
        return FtrlState(
            z=jax.tree.map(jnp.zeros_like, params),
            n=jax.tree.map(
                lambda x: jnp.full_like(x, initial_accumulator_value), params),
        )

    def update_fn(updates, state: FtrlState, params=None):
        if params is None:
            raise ValueError("ftrl requires params in update()")

        def leaf(g, z, n, w):
            g = g.astype(jnp.float32)
            n_new = n + jnp.square(g)
            sigma = (jnp.power(n_new, p) - jnp.power(n, p)) / learning_rate
            z_new = z + g - sigma * w
            denom = (beta + jnp.power(n_new, p)) / learning_rate \
                + 2.0 * l2_regularization_strength
            w_new = jnp.where(
                jnp.abs(z_new) <= l1_regularization_strength,
                jnp.zeros_like(w),
                -(z_new - jnp.sign(z_new) * l1_regularization_strength) / denom)
            return w_new - w, z_new, n_new

        flat = jax.tree.map(leaf, updates, state.z, state.n, params)
        deltas = jax.tree.map(lambda t: t[0], flat,
                              is_leaf=lambda t: isinstance(t, tuple))
        z_new = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        n_new = jax.tree.map(lambda t: t[2], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        return deltas, FtrlState(z=z_new, n=n_new)

    return optax.GradientTransformation(init_fn, update_fn)


def build_optimizer(cfg: Config, *, world_size: int = 1) -> optax.GradientTransformation:
    lr = cfg.learning_rate
    if cfg.scale_lr_by_world and world_size > 1:
        lr = lr * world_size
    name = cfg.optimizer.lower()
    if name == "adam":
        return optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8)
    if name == "adagrad":
        return optax.adagrad(lr, initial_accumulator_value=1e-8)
    if name in ("momentum", "sgd"):
        return optax.sgd(lr, momentum=0.95 if name == "momentum" else None)
    if name == "ftrl":
        return ftrl(lr)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
