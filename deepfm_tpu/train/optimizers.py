"""Optimizer zoo matching the reference's four choices + world-size LR scaling.

Reference (``1-ps-cpu/...py:260-269``):
  Adam(lr, beta1=0.9, beta2=0.999, eps=1e-8)
  Adagrad(lr, initial_accumulator_value=1e-8)
  Momentum(lr, momentum=0.95)
  Ftrl(lr)  — TF defaults: lr_power=-0.5, initial_accumulator=0.1, l1=l2=0

Horovod variant scales lr by world size (``2-hvd-gpu/...py:149``); here that
is ``scale_lr_by_world`` x the data-axis size of the mesh.

FTRL has no optax built-in; ``ftrl()`` below is a custom
``GradientTransformation`` implementing FTRL-Proximal (McMahan et al. 2013),
the same update ``tf.train.FtrlOptimizer`` applies densely.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax

from ..config import Config
from ..ops import embedding as emb_ops


class FtrlState(NamedTuple):
    z: optax.Updates   # per-weight z accumulator
    n: optax.Updates   # per-weight squared-gradient accumulator


def ftrl(
    learning_rate: float,
    *,
    learning_rate_power: float = -0.5,
    initial_accumulator_value: float = 0.1,
    l1_regularization_strength: float = 0.0,
    l2_regularization_strength: float = 0.0,
    beta: float = 0.0,
) -> optax.GradientTransformation:
    """FTRL-Proximal as an optax GradientTransformation (requires params).

    w_new = 0                                  if |z| <= l1
          = -(z - sign(z)*l1) / ((beta + n_new^(-lr_power))/lr + 2*l2)  else
    with n_new = n + g^2 and z += g - (n_new^p - n^p)/lr * w, p = -lr_power.
    """
    if learning_rate_power > 0:
        raise ValueError("learning_rate_power must be <= 0")
    p = -learning_rate_power  # 0.5 for the default sqrt schedule

    def init_fn(params: optax.Params) -> FtrlState:
        return FtrlState(
            z=jax.tree.map(jnp.zeros_like, params),
            n=jax.tree.map(
                lambda x: jnp.full_like(x, initial_accumulator_value), params),
        )

    def update_fn(updates, state: FtrlState, params=None):
        if params is None:
            raise ValueError("ftrl requires params in update()")

        def leaf(g, z, n, w):
            g = g.astype(jnp.float32)
            n_new = n + jnp.square(g)
            sigma = (jnp.power(n_new, p) - jnp.power(n, p)) / learning_rate
            z_new = z + g - sigma * w
            denom = (beta + jnp.power(n_new, p)) / learning_rate \
                + 2.0 * l2_regularization_strength
            w_new = jnp.where(
                jnp.abs(z_new) <= l1_regularization_strength,
                jnp.zeros_like(w),
                -(z_new - jnp.sign(z_new) * l1_regularization_strength) / denom)
            return w_new - w, z_new, n_new

        flat = jax.tree.map(leaf, updates, state.z, state.n, params)
        deltas = jax.tree.map(lambda t: t[0], flat,
                              is_leaf=lambda t: isinstance(t, tuple))
        z_new = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        n_new = jax.tree.map(lambda t: t[2], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        return deltas, FtrlState(z=z_new, n=n_new)

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# Sparse (touched-rows-only) Adam with lazy, timestamped moment correction
# ---------------------------------------------------------------------------
#
# Dense Adam updates EVERY table row every step: touched rows get the full
# update, idle rows still move by their decaying momentum tail
# (-lr * b1^k*m_hat / (sqrt(b2^k*v_hat)+eps)). Applying Adam only to the
# batch's touched rows therefore cannot be bit-exact — but the idle-row
# tail is bounded (a geometric series, ≲ lr*(sum_k b1^k/sqrt(b2^k)) ≈ 9*lr
# per idle stretch, far less in practice because v decays slower than m),
# so the trajectories agree within a pinned tolerance (tests).
#
# The lazy correction makes a touched row's update IDENTICAL to what dense
# Adam would compute for it: per row we store (m, v) and the step count
# ``tau`` at which the row was last touched. On a touch at global step
# ``count`` (1-based, optax convention):
#
#     m_t = b1^(count-tau) * m_stored + (1 - b1) * g       # k idle steps
#     v_t = b2^(count-tau) * v_stored + (1 - b2) * g^2     # decayed in O(1)
#     update = -lr * (m_t / (1-b1^count)) / (sqrt(v_t / (1-b2^count)) + eps)
#
# which is exactly optax.scale_by_adam's m/v for that row had the zero
# gradients been applied one step at a time — the decay factors simply
# telescope. Cost per step ∝ unique touched rows, never ∝ vocab.


class EmbedAdamEntry(NamedTuple):
    """Per-table lazy-Adam slots. ``tau`` is int32 [rows]: the global step
    count at which the row's (m, v) were last brought current."""
    m: jax.Array
    v: jax.Array
    tau: jax.Array


def embed_adam_init(table: jax.Array) -> EmbedAdamEntry:
    return EmbedAdamEntry(
        m=jnp.zeros_like(table, jnp.float32),
        v=jnp.zeros_like(table, jnp.float32),
        tau=jnp.zeros((table.shape[0],), jnp.int32),
    )


def sparse_adam_rows(
    rows0: jax.Array,      # f32 [U, ...] touched rows (pre-update values)
    g_rows: jax.Array,     # f32 [U, ...] summed per-row gradient
    m_rows: jax.Array,     # f32 [U, ...] stored first moment at uids
    v_rows: jax.Array,     # f32 [U, ...] stored second moment at uids
    tau_rows: jax.Array,   # int32 [U]    last-touch step count at uids
    count: jax.Array,      # int32 []     global step count AFTER this step
    *,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
):
    """One lazy-Adam step on gathered rows; returns (new_rows, new_m,
    new_v). Pure row-space math — the caller owns gather/scatter, so the
    tiered runtime can reuse this on hot-cache slots unchanged."""
    g = g_rows.astype(jnp.float32)
    cnt = count.astype(jnp.float32)
    idle = (count - tau_rows).astype(jnp.float32)  # [U] steps since touch
    idle = idle.reshape(idle.shape + (1,) * (g.ndim - 1))
    m = jnp.power(b1, idle) * m_rows + (1.0 - b1) * g
    v = jnp.power(b2, idle) * v_rows + (1.0 - b2) * jnp.square(g)
    m_hat = m / (1.0 - jnp.power(b1, cnt))
    v_hat = v / (1.0 - jnp.power(b2, cnt))
    new_rows = rows0.astype(jnp.float32) - lr * m_hat / (jnp.sqrt(v_hat) + eps)
    return new_rows.astype(rows0.dtype), m, v


def sparse_adam_masked(
    table: jax.Array,      # f32 [R, ...] full table (pre-update values)
    g_rows: jax.Array,     # f32 [R, ...] summed per-row gradient (junk on
                           #              untouched rows — masked out below)
    touched: jax.Array,    # bool [R]     rows present in this batch
    oe: EmbedAdamEntry,
    count: jax.Array,      # int32 []     global step count AFTER this step
    *,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    decay: Optional[tuple] = None,
):
    """Lazy Adam as a masked TABLE-SPACE sweep: per-row math identical to
    :func:`sparse_adam_rows`, applied under ``touched`` with untouched rows
    keeping their exact bits (a ``where``, not a blend). The sweep costs
    one elementwise pass over the table — the same shape of work a dense
    Adam step does — so it beats the gather/apply/scatter round-trip
    whenever the physical table is small enough to sweep (the monolithic
    CTR regime; ops.pallas_embedding.PLAN_COUNT_MAX_ROWS bounds it).

    Numerics contract: the MATH matches sparse_adam_rows exactly, but the
    compiled programs differ in shape ([rows] sweep vs [uids] gather), so
    XLA:CPU is free to fuse/contract the m_hat / (sqrt(v_hat)+eps) tail
    differently — in practice a 1–2 ULP divergence per apply from step 2
    on (step 1 is exact because m=v=0). The trainer's kill-switch parity
    test therefore pins this leg with a tight tolerance rather than bit
    equality; ``optimization_barrier`` placements were tried and do not
    close the gap (XLA duplicates barriered chains per consumer).

    ``decay``: optional precomputed ``(b1^idle, b2^idle)`` pair of [R]
    arrays. The pows are the sweep's hot spot — left inline, XLA fuses
    the [R]-shaped pow into the [R, D] elementwise loop and evaluates it
    D times per row — so the caller computes them ONCE behind an
    optimization_barrier and shares them across every table of the plane
    (tau is identical across tables: same touched set every step).
    Returns ``(new_table, new_EmbedAdamEntry)``."""
    g = g_rows.astype(jnp.float32)
    cnt = count.astype(jnp.float32)
    if decay is None:
        idle = (count - oe.tau).astype(jnp.float32)  # [R] steps since touch
        decay = jax.lax.optimization_barrier(
            (jnp.power(b1, idle), jnp.power(b2, idle)))
    pw1, pw2 = (d.reshape(d.shape + (1,) * (g.ndim - 1)) for d in decay)
    m = pw1 * oe.m + (1.0 - b1) * g
    v = pw2 * oe.v + (1.0 - b2) * jnp.square(g)
    m_hat = m / (1.0 - jnp.power(b1, cnt))
    v_hat = v / (1.0 - jnp.power(b2, cnt))
    new_rows = table.astype(jnp.float32) - lr * m_hat / (jnp.sqrt(v_hat) + eps)
    keep = touched.reshape(touched.shape + (1,) * (g.ndim - 1))
    new_table = jnp.where(keep, new_rows.astype(table.dtype), table)
    new_oe = EmbedAdamEntry(
        m=jnp.where(keep, m, oe.m),
        v=jnp.where(keep, v, oe.v),
        tau=jnp.where(touched, count, oe.tau))
    return new_table, new_oe


def sparse_apply_rows(
    rows0: jax.Array,            # f32 [U, ...] touched rows (pre-update)
    g_rows: jax.Array,           # f32 [U, ...] summed per-row gradient
    entry: emb_ops.PlanEntry,
    oe: EmbedAdamEntry,
    count: jax.Array,
    *,
    lr: float,
    table: jax.Array,
):
    """One table's full sparse-Adam transaction: gather the lazy slots at
    the plan's uids, run :func:`sparse_adam_rows`, and write the three
    updated row sets plus the ``tau`` touch stamps back. Returns
    ``(new_table, new_entry)``. Shared by both sparse step impls (per-batch
    and merged-accumulation) so the gather/apply/writeback sequence — and
    therefore the numerics — exists in exactly one place; the writebacks go
    through ``scatter_rows``/``set_rows_scalar``, which pick the
    select-over-ids formulation automatically on counting plans."""
    new_rows, new_m, new_v = sparse_adam_rows(
        rows0, g_rows,
        emb_ops.gather_rows(oe.m, entry),
        emb_ops.gather_rows(oe.v, entry),
        emb_ops.gather_rows(oe.tau, entry),
        count, lr=lr)
    new_table = emb_ops.scatter_rows(table, entry, new_rows)
    new_oe = EmbedAdamEntry(
        m=emb_ops.scatter_rows(oe.m, entry, new_m),
        v=emb_ops.scatter_rows(oe.v, entry, new_v),
        tau=emb_ops.set_rows_scalar(oe.tau, entry, count))
    return new_table, new_oe


def build_optimizer(cfg: Config, *, world_size: int = 1) -> optax.GradientTransformation:
    lr = cfg.learning_rate
    if cfg.scale_lr_by_world and world_size > 1:
        lr = lr * world_size
    name = cfg.optimizer.lower()
    if name == "adam":
        return optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8)
    if name == "adagrad":
        return optax.adagrad(lr, initial_accumulator_value=1e-8)
    if name in ("momentum", "sgd"):
        return optax.sgd(lr, momentum=0.95 if name == "momentum" else None)
    if name == "ftrl":
        return ftrl(lr)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
