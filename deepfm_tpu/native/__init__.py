from . import loader  # noqa: F401
