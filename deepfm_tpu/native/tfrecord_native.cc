// Native TFRecord frame splitter + tf.train.Example CTR decoder.
//
// TPU-native equivalent of the reference's two C++ data dependencies:
// the TFRecord/proto codec inside TensorFlow (X4) and the PipeModeDataset
// FIFO reader's parsing core (X3). The host CPU decode is the input
// pipeline's hot loop (reference decodes with vectorized tf.parse_example
// after .batch(), 1-ps-cpu/...py:119-128); this library does the same work —
// record framing, CRC32C integrity, protobuf wire parsing into fixed-shape
// arrays — in one pass at C speed, exposed to Python via ctypes (no pybind
// dependency).
//
// Build: g++ -O3 -march=native -shared -fPIC tfrecord_native.cc -o libtfrecord.so
//
// On-disk schema, matching the reference converter (tools/libsvm_to_tfrecord.py:25-33):
//   Example{ label: float_list[1], ids: int64_list[F], values: float_list[F] }
// The legacy aliases feat_ids/feat_vals (written by pre-r3 versions of this
// repo) are accepted on read.

#include <cstdint>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), slice-by-8 software implementation.
// ---------------------------------------------------------------------------

uint32_t g_crc_table[8][256];
bool g_crc_init = false;

void init_crc_tables() {
  if (g_crc_init) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    g_crc_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = g_crc_table[0][i];
    for (int k = 1; k < 8; ++k) {
      crc = g_crc_table[0][crc & 0xFF] ^ (crc >> 8);
      g_crc_table[k][i] = crc;
    }
  }
  g_crc_init = true;
}

uint32_t crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = 0xFFFFFFFFu;
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    word ^= crc;
    crc = g_crc_table[7][word & 0xFF] ^ g_crc_table[6][(word >> 8) & 0xFF] ^
          g_crc_table[5][(word >> 16) & 0xFF] ^ g_crc_table[4][(word >> 24) & 0xFF] ^
          g_crc_table[3][(word >> 32) & 0xFF] ^ g_crc_table[2][(word >> 40) & 0xFF] ^
          g_crc_table[1][(word >> 48) & 0xFF] ^ g_crc_table[0][(word >> 56) & 0xFF];
    data += 8;
    len -= 8;
  }
  while (len--) crc = g_crc_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

inline uint32_t masked_crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// Protobuf wire helpers.
// ---------------------------------------------------------------------------

// Reads a varint; returns false on overrun/malformed.
inline bool read_varint(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (p < end && shift <= 63) {
    uint8_t b = *p++;
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
  }
  // Loop exits after consuming the 10th byte (shift 0..63 inclusive = 10
  // iterations, covering 64-bit two's-complement varints) or on overrun.
  return false;
}

inline bool skip_field(const uint8_t*& p, const uint8_t* end, uint32_t wire) {
  uint64_t tmp;
  switch (wire) {
    case 0: return read_varint(p, end, &tmp);
    case 1: if (end - p < 8) return false; p += 8; return true;
    case 2:
      if (!read_varint(p, end, &tmp) || static_cast<uint64_t>(end - p) < tmp)
        return false;
      p += tmp;
      return true;
    case 5: if (end - p < 4) return false; p += 4; return true;
    default: return false;
  }
}

// Parse FloatList payload -> out[0..cap); returns count or -1.
long parse_float_list(const uint8_t* p, const uint8_t* end, float* out, long cap) {
  long n = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {  // packed
      uint64_t len;
      if (!read_varint(p, end, &len) || static_cast<uint64_t>(end - p) < len)
        return -1;
      long cnt = len / 4;
      if (n + cnt > cap) return -1;
      std::memcpy(out + n, p, cnt * 4);
      n += cnt;
      p += len;
    } else if (field == 1 && wire == 5) {  // unpacked
      if (end - p < 4 || n >= cap) return -1;
      std::memcpy(out + n, p, 4);
      ++n;
      p += 4;
    } else {
      if (!skip_field(p, end, wire)) return -1;
    }
  }
  return n;
}

// Parse Int64List payload -> out[0..cap) as int32 (CTR ids fit); returns count or -1.
long parse_int64_list(const uint8_t* p, const uint8_t* end, int32_t* out, long cap) {
  long n = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {  // packed
      uint64_t len;
      if (!read_varint(p, end, &len) || static_cast<uint64_t>(end - p) < len)
        return -1;
      const uint8_t* stop = p + len;
      while (p < stop) {
        uint64_t v;
        if (!read_varint(p, stop, &v) || n >= cap) return -1;
        out[n++] = static_cast<int32_t>(static_cast<int64_t>(v));
      }
    } else if (field == 1 && wire == 0) {
      uint64_t v;
      if (!read_varint(p, end, &v) || n >= cap) return -1;
      out[n++] = static_cast<int32_t>(static_cast<int64_t>(v));
    } else {
      if (!skip_field(p, end, wire)) return -1;
    }
  }
  return n;
}

// Truncating variants for ragged history lists: write at most cap entries
// but return the ACTUAL element count (which may exceed cap — the caller
// clamps). Only malformed wire is an error (-1); overflow is silent
// truncation, matching the fixed [max_len] history contract.
long parse_float_list_trunc(const uint8_t* p, const uint8_t* end, float* out,
                            long cap) {
  long n = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {  // packed
      uint64_t len;
      if (!read_varint(p, end, &len) || static_cast<uint64_t>(end - p) < len)
        return -1;
      long cnt = len / 4;
      long keep = (n < cap) ? ((cnt < cap - n) ? cnt : cap - n) : 0;
      if (keep > 0) std::memcpy(out + n, p, keep * 4);
      n += cnt;
      p += len;
    } else if (field == 1 && wire == 5) {  // unpacked
      if (end - p < 4) return -1;
      if (n < cap) std::memcpy(out + n, p, 4);
      ++n;
      p += 4;
    } else {
      if (!skip_field(p, end, wire)) return -1;
    }
  }
  return n;
}

long parse_int64_list_trunc(const uint8_t* p, const uint8_t* end, int32_t* out,
                            long cap) {
  long n = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {  // packed
      uint64_t len;
      if (!read_varint(p, end, &len) || static_cast<uint64_t>(end - p) < len)
        return -1;
      const uint8_t* stop = p + len;
      while (p < stop) {
        uint64_t v;
        if (!read_varint(p, stop, &v)) return -1;
        if (n < cap) out[n] = static_cast<int32_t>(static_cast<int64_t>(v));
        ++n;
      }
    } else if (field == 1 && wire == 0) {
      uint64_t v;
      if (!read_varint(p, end, &v)) return -1;
      if (n < cap) out[n] = static_cast<int32_t>(static_cast<int64_t>(v));
      ++n;
    } else {
      if (!skip_field(p, end, wire)) return -1;
    }
  }
  return n;
}

struct KeyRef { const uint8_t* p; uint64_t len; };

inline bool key_is(const KeyRef& k, const char* s) {
  size_t sl = std::strlen(s);
  return k.len == sl && std::memcmp(k.p, s, sl) == 0;
}

// Parse one serialized Example. Returns 0 ok, negative error. label2 (when
// non-null) receives the optional "label2" float key, defaulting to 0.0f
// when the key is absent — single-label files stay decodable as multi-task
// input; existing callers pass nullptr and are untouched. hist_ids/hist_vals
// (when non-null, sized [max_hist]) receive the optional ragged
// "hist_ids"/"hist_vals" pair zero-padded and silently truncated to
// max_hist, with *hist_len = min(actual, max_hist); both keys absent decodes
// as an empty history. One key without the other, or differing lengths, is a
// schema error (-27).
long parse_ctr_example(const uint8_t* p, const uint8_t* end, long field_size,
                       float* label, int32_t* ids, float* vals,
                       float* label2 = nullptr, long max_hist = 0,
                       int32_t* hist_ids = nullptr, float* hist_vals = nullptr,
                       int32_t* hist_len = nullptr) {
  bool got_label = false, got_ids = false, got_vals = false;
  if (label2) *label2 = 0.0f;
  long hist_ids_n = 0, hist_vals_n = 0;
  if (hist_ids) std::memset(hist_ids, 0, max_hist * sizeof(int32_t));
  if (hist_vals) std::memset(hist_vals, 0, max_hist * sizeof(float));
  if (hist_len) *hist_len = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(p, end, &tag)) return -10;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field != 1 || wire != 2) {  // not Example.features
      if (!skip_field(p, end, wire)) return -10;
      continue;
    }
    uint64_t flen;
    if (!read_varint(p, end, &flen) || static_cast<uint64_t>(end - p) < flen)
      return -10;
    const uint8_t* fp = p;
    const uint8_t* fend = p + flen;
    p = fend;
    while (fp < fend) {  // Features.feature map entries
      uint64_t ftag;
      if (!read_varint(fp, fend, &ftag)) return -11;
      if ((ftag >> 3) != 1 || (ftag & 7) != 2) {
        if (!skip_field(fp, fend, ftag & 7)) return -11;
        continue;
      }
      uint64_t elen;
      if (!read_varint(fp, fend, &elen) || static_cast<uint64_t>(fend - fp) < elen)
        return -11;
      const uint8_t* ep = fp;
      const uint8_t* eend = fp + elen;
      fp = eend;
      KeyRef key{nullptr, 0};
      const uint8_t* feat_p = nullptr;
      uint64_t feat_len = 0;
      while (ep < eend) {  // map entry: key=1, value=2
        uint64_t etag;
        if (!read_varint(ep, eend, &etag)) return -12;
        uint32_t ef = etag >> 3, ew = etag & 7;
        if (ew != 2) {
          if (!skip_field(ep, eend, ew)) return -12;
          continue;
        }
        uint64_t vlen;
        if (!read_varint(ep, eend, &vlen) || static_cast<uint64_t>(eend - ep) < vlen)
          return -12;
        if (ef == 1) { key.p = ep; key.len = vlen; }
        else if (ef == 2) { feat_p = ep; feat_len = vlen; }
        ep += vlen;
      }
      if (!key.p || !feat_p) continue;
      // Feature: one length-delimited sub-message (1:bytes 2:float 3:int64)
      const uint8_t* vp = feat_p;
      const uint8_t* vend = feat_p + feat_len;
      uint64_t vtag;
      if (!read_varint(vp, vend, &vtag)) return -13;
      uint32_t vfield = vtag >> 3;
      if ((vtag & 7) != 2) continue;
      uint64_t plen;
      if (!read_varint(vp, vend, &plen) || static_cast<uint64_t>(vend - vp) < plen)
        return -13;
      const uint8_t* payload = vp;
      const uint8_t* pend = vp + plen;
      if (key_is(key, "label") && vfield == 2) {
        if (parse_float_list(payload, pend, label, 1) != 1) return -20;
        got_label = true;
      } else if (label2 && key_is(key, "label2") && vfield == 2) {
        if (parse_float_list(payload, pend, label2, 1) != 1) return -24;
      } else if ((key_is(key, "ids") || key_is(key, "feat_ids")) &&
                 vfield == 3) {
        if (parse_int64_list(payload, pend, ids, field_size) != field_size)
          return -21;
        got_ids = true;
      } else if ((key_is(key, "values") || key_is(key, "feat_vals")) &&
                 vfield == 2) {
        if (parse_float_list(payload, pend, vals, field_size) != field_size)
          return -22;
        got_vals = true;
      } else if (hist_ids && key_is(key, "hist_ids") && vfield == 3) {
        hist_ids_n = parse_int64_list_trunc(payload, pend, hist_ids, max_hist);
        if (hist_ids_n < 0) return -25;
      } else if (hist_vals && key_is(key, "hist_vals") && vfield == 2) {
        hist_vals_n = parse_float_list_trunc(payload, pend, hist_vals, max_hist);
        if (hist_vals_n < 0) return -26;
      }
    }
  }
  if (hist_ids) {
    if (hist_ids_n != hist_vals_n) return -27;
    if (hist_len) {
      *hist_len = static_cast<int32_t>(
          hist_ids_n < max_hist ? hist_ids_n : max_hist);
    }
  }
  return (got_label && got_ids && got_vals) ? 0 : -23;
}

}  // namespace

extern "C" {

// Split TFRecord frames in buf[0..len). Fills offsets/lengths (payload only,
// excluding framing) up to max_records. verify_crc: 0 none, 1 both CRCs.
// allow_partial: 1 = stop cleanly at an incomplete trailing record (chunked
// streaming; *consumed tells the caller how many bytes were fully framed so
// it can carry the tail into the next chunk). 0 = truncation is an error.
// Returns record count, or negative: -1 truncated, -2 crc mismatch,
// -3 capacity exceeded (only when allow_partial=0).
long dfm_split_frames_ex(const uint8_t* buf, long len, long verify_crc,
                         long allow_partial, long max_records,
                         long* offsets, long* lengths, long* consumed) {
  init_crc_tables();
  long n = 0;
  long pos = 0;
  while (pos < len) {
    if (len - pos < 12) {
      if (allow_partial) break;
      return -1;
    }
    uint64_t rec_len;
    std::memcpy(&rec_len, buf + pos, 8);
    if (verify_crc) {
      uint32_t stored;
      std::memcpy(&stored, buf + pos + 8, 4);
      if (masked_crc32c(buf + pos, 8) != stored) return -2;
    }
    // avail/rec_len compared without addition: rec_len + 4 could wrap uint64
    // on a corrupt length field and defeat the bounds check.
    uint64_t avail = static_cast<uint64_t>(len - pos - 12);
    if (avail < 4 || rec_len > avail - 4) {
      if (allow_partial) break;  // record continues past this chunk
      return -1;
    }
    if (verify_crc) {
      uint32_t stored;
      std::memcpy(&stored, buf + pos + 12 + rec_len, 4);
      if (masked_crc32c(buf + pos + 12, rec_len) != stored) return -2;
    }
    if (n >= max_records) {
      if (allow_partial) break;
      return -3;
    }
    offsets[n] = pos + 12;
    lengths[n] = static_cast<long>(rec_len);
    ++n;
    pos += 12 + rec_len + 4;
  }
  if (consumed) *consumed = pos;
  return n;
}

// Back-compat whole-buffer splitter (strict framing).
long dfm_split_frames(const uint8_t* buf, long len, long verify_crc,
                      long max_records, long* offsets, long* lengths) {
  return dfm_split_frames_ex(buf, len, verify_crc, /*allow_partial=*/0,
                             max_records, offsets, lengths, nullptr);
}

// Decode n CTR Examples addressed by (offsets, lengths) into fixed-shape
// outputs: labels[n], ids[n*field_size], vals[n*field_size].
// Returns 0, or -(100+i) error at record i; *err_detail (if non-null) holds
// the parse_ctr_example code for that record: -10..-13 malformed wire,
// -20/-21/-22 label/ids/values length != expected, -23 required key missing.
long dfm_decode_ctr_ex(const uint8_t* buf, const long* offsets,
                       const long* lengths, long n, long field_size,
                       float* labels, int32_t* ids, float* vals,
                       long* err_detail) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* p = buf + offsets[i];
    long rc = parse_ctr_example(p, p + lengths[i], field_size, labels + i,
                                ids + i * field_size, vals + i * field_size);
    if (rc != 0) {
      if (err_detail) *err_detail = rc;
      return -(100 + i);
    }
  }
  return 0;
}

// Back-compat entry without the error-detail out-param.
long dfm_decode_ctr(const uint8_t* buf, const long* offsets, const long* lengths,
                    long n, long field_size, float* labels, int32_t* ids,
                    float* vals) {
  return dfm_decode_ctr_ex(buf, offsets, lengths, n, field_size, labels, ids,
                           vals, nullptr);
}

// Two-label decode for multi-task training (--tasks ctr,cvr): additionally
// fills labels2[n] from the optional "label2" float key, 0.0 when absent.
// Error contract matches dfm_decode_ctr_ex, plus detail -24 for a malformed
// 'label2' (present but not a single float).
long dfm_decode_ctr2_ex(const uint8_t* buf, const long* offsets,
                        const long* lengths, long n, long field_size,
                        float* labels, float* labels2, int32_t* ids,
                        float* vals, long* err_detail) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* p = buf + offsets[i];
    long rc = parse_ctr_example(p, p + lengths[i], field_size, labels + i,
                                ids + i * field_size, vals + i * field_size,
                                labels2 + i);
    if (rc != 0) {
      if (err_detail) *err_detail = rc;
      return -(100 + i);
    }
  }
  return 0;
}

// History decode for sequence models: additionally fills the optional ragged
// "hist_ids"/"hist_vals" pair into fixed [n, max_hist] outputs, zero-padded
// and silently truncated past max_hist, with hist_len[i] = min(actual,
// max_hist). Records without history keys decode with hist_len 0. Error
// contract matches dfm_decode_ctr_ex, plus details -25/-26 for malformed
// hist_ids/hist_vals wire and -27 for a length-mismatched (or half-present)
// pair.
long dfm_decode_ctr_hist(const uint8_t* buf, const long* offsets,
                         const long* lengths, long n, long field_size,
                         long max_hist, float* labels, int32_t* ids,
                         float* vals, int32_t* hist_ids, float* hist_vals,
                         int32_t* hist_len, long* err_detail) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* p = buf + offsets[i];
    long rc = parse_ctr_example(p, p + lengths[i], field_size, labels + i,
                                ids + i * field_size, vals + i * field_size,
                                nullptr, max_hist, hist_ids + i * max_hist,
                                hist_vals + i * max_hist, hist_len + i);
    if (rc != 0) {
      if (err_detail) *err_detail = rc;
      return -(100 + i);
    }
  }
  return 0;
}

// Fused decode + shuffle scatter: decode record i straight into row dest[i]
// of the output arrays (the shuffle pool). Each record's field bytes are
// written exactly once, at their permuted destination — replacing the
// decode-then-scatter sequence (two full passes over the pool: one
// sequential write + one random-access copy) with a single pass whose only
// random access is the final store. The caller owns destination bounds
// (every dest[i] < pool rows) and disjointness across concurrent calls.
long dfm_decode_ctr_scatter(const uint8_t* buf, const long* offsets,
                            const long* lengths, long n, long field_size,
                            const long* dest, float* labels, int32_t* ids,
                            float* vals, long* err_detail) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* p = buf + offsets[i];
    const long d = dest[i];
    long rc = parse_ctr_example(p, p + lengths[i], field_size, labels + d,
                                ids + d * field_size, vals + d * field_size);
    if (rc != 0) {
      if (err_detail) *err_detail = rc;
      return -(100 + i);
    }
  }
  return 0;
}

// Fused decode->assemble over MANY framed chunk spans in one call: the
// whole pool drain (every raw chunk held since the last drain) decodes
// straight into the permuted rows of the preallocated transfer-layout
// output buffers — labels[P] (the [P,1] column of the emitted batch dict
// is the same contiguous floats), ids[P*field_size], vals[P*field_size].
// dest is the concatenated destination-row vector across chunks (chunk c's
// records use dest[base_c .. base_c+counts[c])). One ctypes crossing and
// one GIL release per drain instead of one per chunk: on a contended
// 1-core host each C call's GIL reacquisition can stall up to a switch
// interval behind the consumer thread, so fewer crossings is a real win,
// not just call-overhead accounting.
// Returns 0, or -(100+i) with i the record index WITHIN the failing chunk;
// *err_chunk (if non-null) holds that chunk's index and *err_detail the
// parse_ctr_example code (same contract as dfm_decode_ctr_ex).
long dfm_decode_ctr_assemble(const uint8_t* const* bufs,
                             const long* const* offsets,
                             const long* const* lengths,
                             const long* counts, long n_chunks,
                             long field_size, const long* dest,
                             float* labels, int32_t* ids, float* vals,
                             long* err_chunk, long* err_detail) {
  long base = 0;
  for (long c = 0; c < n_chunks; ++c) {
    const uint8_t* buf = bufs[c];
    const long* off = offsets[c];
    const long* len = lengths[c];
    const long n = counts[c];
    for (long i = 0; i < n; ++i) {
      const uint8_t* p = buf + off[i];
      const long d = dest[base + i];
      long rc = parse_ctr_example(p, p + len[i], field_size, labels + d,
                                  ids + d * field_size,
                                  vals + d * field_size);
      if (rc != 0) {
        if (err_chunk) *err_chunk = c;
        if (err_detail) *err_detail = rc;
        return -(100 + i);
      }
    }
    base += n;
  }
  return 0;
}

// Standalone CRC32C for tests.
uint32_t dfm_crc32c(const uint8_t* data, long len) {
  init_crc_tables();
  return crc32c(data, len);
}

}  // extern "C"
