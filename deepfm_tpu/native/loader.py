"""ctypes loader for the native TFRecord decoder (builds on first use).

Compiles ``tfrecord_native.cc`` with g++ into a cached shared library and
exposes:
  * ``split_frames(buf, verify_crc)`` -> (offsets, lengths) int64 arrays
  * ``decode_batch(records, field_size)`` -> (labels, ids, vals) — drop-in
    replacement for ``pipeline.decode_batch_python``
  * ``decode_file_bytes(buf, field_size, verify_crc)`` — whole-buffer
    one-pass framing + CRC + proto decode (the true hot path)

Falls back gracefully: ``available()`` returns False if the toolchain or
build fails, and the pipeline uses the pure-Python codec.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tfrecord_native.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD_DIR, "libtfrecord.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        needs_build = (not os.path.exists(_SO)
                       or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if needs_build and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        # Buffer params are raw pointers (not c_char_p) so zero-copy views of
        # bytes AND mmap objects both work via np.frombuffer.
        lib.dfm_split_frames.restype = ctypes.c_long
        lib.dfm_split_frames.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long, ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        lib.dfm_split_frames_ex.restype = ctypes.c_long
        lib.dfm_split_frames_ex.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long, ctypes.c_long,
            ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long)]
        lib.dfm_decode_ctr.restype = ctypes.c_long
        lib.dfm_decode_ctr.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float)]
        lib.dfm_decode_ctr_ex.restype = ctypes.c_long
        lib.dfm_decode_ctr_ex.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_long)]
        lib.dfm_decode_ctr_scatter.restype = ctypes.c_long
        lib.dfm_decode_ctr_scatter.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_long)]
        lib.dfm_crc32c.restype = ctypes.c_uint32
        lib.dfm_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_long]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def crc32c(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.dfm_crc32c(data, len(data)))


def split_frames_partial(buf, *, verify_crc: bool = True
                         ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Like split_frames but tolerates an incomplete trailing record.

    Returns (offsets, lengths, consumed): ``consumed`` is the byte count of
    fully-framed records; the caller carries ``buf[consumed:]`` into the next
    chunk. This is the chunked-streaming primitive — constant memory on
    multi-GB shards, ordinary read() I/O (no mmap SIGBUS hazard on network
    filesystems)."""
    lib = _load()
    assert lib is not None
    cap = max(len(buf) // 16, 1)
    offsets = np.empty(cap, dtype=np.int64)
    lengths = np.empty(cap, dtype=np.int64)
    consumed = ctypes.c_long(0)
    n = lib.dfm_split_frames_ex(
        _as_ubyte_ptr(buf), len(buf), int(verify_crc), 1, cap,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        ctypes.byref(consumed))
    if n == -2:
        raise IOError("corrupt TFRecord: CRC mismatch")
    if n < 0:
        raise IOError(f"TFRecord split error {n}")
    return offsets[:n], lengths[:n], int(consumed.value)


def _as_ubyte_ptr(buf) -> "ctypes.POINTER(ctypes.c_ubyte)":
    """Zero-copy pointer to a bytes-like object (bytes, mmap, memoryview)."""
    arr = np.frombuffer(buf, dtype=np.uint8)
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))


def split_frames(buf, *, verify_crc: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Frame offsets/lengths of every record in a TFRecord byte buffer."""
    lib = _load()
    assert lib is not None
    # Upper bound: every record is >= 16 bytes on disk.
    cap = max(len(buf) // 16, 1)
    offsets = np.empty(cap, dtype=np.int64)
    lengths = np.empty(cap, dtype=np.int64)
    n = lib.dfm_split_frames(
        _as_ubyte_ptr(buf), len(buf), int(verify_crc), cap,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)))
    if n == -1:
        raise IOError("truncated TFRecord")
    if n == -2:
        raise IOError("corrupt TFRecord: CRC mismatch")
    if n < 0:
        raise IOError(f"TFRecord split error {n}")
    return offsets[:n], lengths[:n]


def decode_spans(buf, offsets: np.ndarray, lengths: np.ndarray,
                 field_size: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    lib = _load()
    assert lib is not None
    n = len(offsets)
    labels = np.empty(n, dtype=np.float32)
    ids = np.empty((n, field_size), dtype=np.int32)
    vals = np.empty((n, field_size), dtype=np.float32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr_ex(
        _as_ubyte_ptr(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, field_size,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(detail))
    if rc != 0:
        raise ValueError(f"native decode failed at record {-rc - 100}: "
                         f"{_decode_reason(detail.value, field_size)}")
    return labels, ids, vals


def _decode_reason(code: int, field_size: int) -> str:
    """Human-readable reason for a parse_ctr_example error code (shared by
    every decode entry point)."""
    reasons = {
        -20: "'label' is not a single float",
        -21: f"'ids' length != field_size={field_size}",
        -22: f"'values' length != field_size={field_size}",
        -23: ("required keys missing — need 'label' plus 'ids'/'values' "
              "(reference schema) or 'feat_ids'/'feat_vals' (legacy)"),
    }
    return reasons.get(code, f"malformed Example wire data (code {code})")


def decode_spans_scatter(buf, offsets: np.ndarray, lengths: np.ndarray,
                         field_size: int, dest: np.ndarray,
                         labels: np.ndarray, ids: np.ndarray,
                         vals: np.ndarray) -> None:
    """Fused decode + scatter: decode record i of ``buf`` into row
    ``dest[i]`` of the caller-provided pool arrays (``labels`` [P],
    ``ids`` [P, field_size] int32, ``vals`` [P, field_size] float32, all
    C-contiguous). One pass over the records instead of decode-then-scatter
    (see ``CtrPipeline._iter_pooled_raw``); the caller guarantees every
    ``dest[i]`` is in bounds and disjoint across concurrent calls (the GIL
    is released inside the C call, so threads may fill disjoint rows of the
    same pool in parallel)."""
    lib = _load()
    assert lib is not None
    n = len(offsets)
    assert labels.flags.c_contiguous and ids.flags.c_contiguous \
        and vals.flags.c_contiguous
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    dest = np.ascontiguousarray(dest, dtype=np.int64)
    # The C side scatters unchecked (labels[dest[i]] etc.) — a caller bug
    # here is silent out-of-bounds heap writes, so validate the index
    # vector before handing over the pointers (advisor r5).
    if len(dest) != n:
        raise ValueError(
            f"decode_spans_scatter: len(dest)={len(dest)} != "
            f"len(offsets)={n}")
    rows = min(len(labels), len(ids), len(vals))
    if n and (int(dest.min()) < 0 or int(dest.max()) >= rows):
        raise ValueError(
            f"decode_spans_scatter: dest range [{int(dest.min())}, "
            f"{int(dest.max())}] outside pool of {rows} rows")
    if n == 0:
        return
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr_scatter(
        _as_ubyte_ptr(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, field_size,
        dest.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(detail))
    if rc != 0:
        # Index is relative to THIS (possibly sub-span) call, not the chunk.
        raise ValueError(
            f"native scatter-decode failed at span-local record {-rc - 100}: "
            f"{_decode_reason(detail.value, field_size)}")


def decode_batch(records: Sequence[bytes], field_size: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized decode of a list of serialized Examples (pipeline hook)."""
    buf = b"".join(records)
    lengths = np.fromiter((len(r) for r in records), dtype=np.int64,
                          count=len(records))
    offsets = np.zeros(len(records), dtype=np.int64)
    if len(records) > 1:
        np.cumsum(lengths[:-1], out=offsets[1:])
    return decode_spans(buf, offsets, lengths, field_size)


def decode_file_bytes(buf: bytes, field_size: int, *, verify_crc: bool = True
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One-pass decode of a whole TFRecord file buffer."""
    offsets, lengths = split_frames(buf, verify_crc=verify_crc)
    return decode_spans(buf, offsets, lengths, field_size)
