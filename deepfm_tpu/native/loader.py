"""ctypes loader for the native TFRecord decoder (builds on first use).

Compiles ``tfrecord_native.cc`` with g++ into a cached shared library and
exposes:
  * ``split_frames(buf, verify_crc)`` -> (offsets, lengths) int64 arrays
  * ``decode_batch(records, field_size)`` -> (labels, ids, vals) — drop-in
    replacement for ``pipeline.decode_batch_python``
  * ``decode_file_bytes(buf, field_size, verify_crc)`` — whole-buffer
    one-pass framing + CRC + proto decode (the true hot path)

Falls back gracefully: ``available()`` returns False if the toolchain or
build fails, and the pipeline uses the pure-Python codec.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tfrecord_native.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD_DIR, "libtfrecord.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        needs_build = (not os.path.exists(_SO)
                       or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if needs_build and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        # Buffer params are raw pointers (not c_char_p) so zero-copy views of
        # bytes AND mmap objects both work via np.frombuffer.
        lib.dfm_split_frames.restype = ctypes.c_long
        lib.dfm_split_frames.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long, ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        lib.dfm_split_frames_ex.restype = ctypes.c_long
        lib.dfm_split_frames_ex.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long, ctypes.c_long,
            ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long)]
        lib.dfm_decode_ctr.restype = ctypes.c_long
        lib.dfm_decode_ctr.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float)]
        lib.dfm_decode_ctr_ex.restype = ctypes.c_long
        lib.dfm_decode_ctr_ex.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_long)]
        lib.dfm_decode_ctr_scatter.restype = ctypes.c_long
        lib.dfm_decode_ctr_scatter.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_long)]
        # Fused multi-chunk assemble entry: absent from pre-r6 cached .so
        # builds (the mtime check rebuilds when the source is newer, but a
        # clock-skewed checkout can leave a stale library) — probe instead
        # of assuming, and let callers key off has_assemble().
        try:
            lib.dfm_decode_ctr_assemble.restype = ctypes.c_long
            lib.dfm_decode_ctr_assemble.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        except AttributeError:
            pass
        # Two-label decode entry (multi-task input): same stale-.so probe
        # discipline as the assemble entry above; callers key off
        # has_labels2() and fall back to the Python codec mirror.
        try:
            lib.dfm_decode_ctr2_ex.restype = ctypes.c_long
            lib.dfm_decode_ctr2_ex.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_long)]
        except AttributeError:
            pass
        # History decode entry (sequence models): same stale-.so probe
        # discipline; callers key off has_hist() and fall back to the
        # Python codec mirror.
        try:
            lib.dfm_decode_ctr_hist.restype = ctypes.c_long
            lib.dfm_decode_ctr_hist.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_long)]
        except AttributeError:
            pass
        lib.dfm_crc32c.restype = ctypes.c_uint32
        lib.dfm_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_long]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def crc32c(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.dfm_crc32c(data, len(data)))


def split_frames_partial(buf, *, verify_crc: bool = True
                         ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Like split_frames but tolerates an incomplete trailing record.

    Returns (offsets, lengths, consumed): ``consumed`` is the byte count of
    fully-framed records; the caller carries ``buf[consumed:]`` into the next
    chunk. This is the chunked-streaming primitive — constant memory on
    multi-GB shards, ordinary read() I/O (no mmap SIGBUS hazard on network
    filesystems)."""
    lib = _load()
    assert lib is not None
    cap = max(len(buf) // 16, 1)
    offsets = np.empty(cap, dtype=np.int64)
    lengths = np.empty(cap, dtype=np.int64)
    consumed = ctypes.c_long(0)
    n = lib.dfm_split_frames_ex(
        _as_ubyte_ptr(buf), len(buf), int(verify_crc), 1, cap,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        ctypes.byref(consumed))
    if n == -2:
        raise IOError("corrupt TFRecord: CRC mismatch")
    if n < 0:
        raise IOError(f"TFRecord split error {n}")
    return offsets[:n], lengths[:n], int(consumed.value)


def _as_ubyte_ptr(buf) -> "ctypes.POINTER(ctypes.c_ubyte)":
    """Zero-copy pointer to a bytes-like object (bytes, mmap, memoryview)."""
    arr = np.frombuffer(buf, dtype=np.uint8)
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))


def split_frames(buf, *, verify_crc: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Frame offsets/lengths of every record in a TFRecord byte buffer."""
    lib = _load()
    assert lib is not None
    # Upper bound: every record is >= 16 bytes on disk.
    cap = max(len(buf) // 16, 1)
    offsets = np.empty(cap, dtype=np.int64)
    lengths = np.empty(cap, dtype=np.int64)
    n = lib.dfm_split_frames(
        _as_ubyte_ptr(buf), len(buf), int(verify_crc), cap,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)))
    if n == -1:
        raise IOError("truncated TFRecord")
    if n == -2:
        raise IOError("corrupt TFRecord: CRC mismatch")
    if n < 0:
        raise IOError(f"TFRecord split error {n}")
    return offsets[:n], lengths[:n]


def decode_spans(buf, offsets: np.ndarray, lengths: np.ndarray,
                 field_size: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    lib = _load()
    assert lib is not None
    n = len(offsets)
    labels = np.empty(n, dtype=np.float32)
    ids = np.empty((n, field_size), dtype=np.int32)
    vals = np.empty((n, field_size), dtype=np.float32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr_ex(
        _as_ubyte_ptr(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, field_size,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(detail))
    if rc != 0:
        raise ValueError(f"native decode failed at record {-rc - 100}: "
                         f"{_decode_reason(detail.value, field_size)}")
    return labels, ids, vals


def _decode_reason(code: int, field_size: int) -> str:
    """Human-readable reason for a parse_ctr_example error code (shared by
    every decode entry point)."""
    reasons = {
        -20: "'label' is not a single float",
        -21: f"'ids' length != field_size={field_size}",
        -22: f"'values' length != field_size={field_size}",
        -23: ("required keys missing — need 'label' plus 'ids'/'values' "
              "(reference schema) or 'feat_ids'/'feat_vals' (legacy)"),
        -24: "'label2' is not a single float",
        -25: "malformed 'hist_ids' int64 list",
        -26: "malformed 'hist_vals' float list",
        -27: "'hist_ids'/'hist_vals' lengths differ (or one key missing)",
    }
    return reasons.get(code, f"malformed Example wire data (code {code})")


def has_labels2() -> bool:
    """True when the built library exports the two-label decode entry
    (``dfm_decode_ctr2_ex``). False on a stale cached .so — callers fall
    back to the Python codec mirror, which emits identical values."""
    lib = _load()
    return lib is not None and hasattr(lib, "dfm_decode_ctr2_ex")


def decode_spans2(buf, offsets: np.ndarray, lengths: np.ndarray,
                  field_size: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Two-label variant of :func:`decode_spans` for multi-task input:
    returns ``(labels, labels2, ids, vals)`` with ``labels2[i]`` from the
    optional ``label2`` key (0.0 when absent). Falls back to the
    bit-identical Python codec mirror when the cached library predates the
    entry (same discipline as ``assemble_spans``)."""
    lib = _load()
    n = len(offsets)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    if lib is None or not hasattr(lib, "dfm_decode_ctr2_ex"):
        from ..data import example_codec  # noqa: PLC0415 (avoid module cycle)
        labels = np.empty(n, dtype=np.float32)
        labels2 = np.empty(n, dtype=np.float32)
        ids = np.empty((n, field_size), dtype=np.int32)
        vals = np.empty((n, field_size), dtype=np.float32)
        for i, (off, ln) in enumerate(zip(offsets.tolist(), lengths.tolist())):
            lab, lab2, rid, rval = example_codec.decode_ctr_example2(
                bytes(buf[off:off + ln]), field_size)
            labels[i] = lab
            labels2[i] = lab2
            ids[i] = rid.astype(np.int32)
            vals[i] = rval
        return labels, labels2, ids, vals
    labels = np.empty(n, dtype=np.float32)
    labels2 = np.empty(n, dtype=np.float32)
    ids = np.empty((n, field_size), dtype=np.int32)
    vals = np.empty((n, field_size), dtype=np.float32)
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr2_ex(
        _as_ubyte_ptr(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, field_size,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(detail))
    if rc != 0:
        raise ValueError(f"native 2-label decode failed at record "
                         f"{-rc - 100}: "
                         f"{_decode_reason(detail.value, field_size)}")
    return labels, labels2, ids, vals


def decode_batch2(records: Sequence[bytes], field_size: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Two-label sibling of :func:`decode_batch`."""
    buf = b"".join(records)
    lengths = np.fromiter((len(r) for r in records), dtype=np.int64,
                          count=len(records))
    offsets = np.zeros(len(records), dtype=np.int64)
    if len(records) > 1:
        np.cumsum(lengths[:-1], out=offsets[1:])
    return decode_spans2(buf, offsets, lengths, field_size)


def has_hist() -> bool:
    """True when the built library exports the history decode entry
    (``dfm_decode_ctr_hist``). False on a stale cached .so — callers fall
    back to the Python codec mirror, which emits identical values."""
    lib = _load()
    return lib is not None and hasattr(lib, "dfm_decode_ctr_hist")


def decode_spans_hist(
        buf, offsets: np.ndarray, lengths: np.ndarray, field_size: int,
        max_len: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray,
           np.ndarray]:
    """History variant of :func:`decode_spans` for sequence models:
    returns ``(labels, ids, vals, hist_ids [n, max_len] int32,
    hist_vals [n, max_len] float32, hist_len [n] int32)`` with the ragged
    ``hist_ids``/``hist_vals`` pair zero-padded and truncated to ``max_len``
    per record (absent pair -> empty history). Falls back to the
    bit-identical Python codec mirror when the cached library predates the
    entry (same discipline as ``decode_spans2``)."""
    lib = _load()
    n = len(offsets)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    labels = np.empty(n, dtype=np.float32)
    ids = np.empty((n, field_size), dtype=np.int32)
    vals = np.empty((n, field_size), dtype=np.float32)
    hist_ids = np.zeros((n, max_len), dtype=np.int32)
    hist_vals = np.zeros((n, max_len), dtype=np.float32)
    hist_len = np.zeros(n, dtype=np.int32)
    if lib is None or not hasattr(lib, "dfm_decode_ctr_hist"):
        from ..data import example_codec  # noqa: PLC0415 (avoid module cycle)
        for i, (off, ln) in enumerate(zip(offsets.tolist(), lengths.tolist())):
            lab, rid, rval, hid, hval, hn = example_codec.decode_ctr_example_hist(
                bytes(buf[off:off + ln]), field_size, max_len)
            labels[i] = lab
            ids[i] = rid.astype(np.int32)
            vals[i] = rval
            hist_ids[i] = hid
            hist_vals[i] = hval
            hist_len[i] = hn
        return labels, ids, vals, hist_ids, hist_vals, hist_len
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr_hist(
        _as_ubyte_ptr(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, field_size, max_len,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        hist_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        hist_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        hist_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(detail))
    if rc != 0:
        raise ValueError(f"native history decode failed at record "
                         f"{-rc - 100}: "
                         f"{_decode_reason(detail.value, field_size)}")
    return labels, ids, vals, hist_ids, hist_vals, hist_len


def decode_batch_hist(records: Sequence[bytes], field_size: int, max_len: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray, np.ndarray, np.ndarray]:
    """History sibling of :func:`decode_batch`."""
    buf = b"".join(records)
    lengths = np.fromiter((len(r) for r in records), dtype=np.int64,
                          count=len(records))
    offsets = np.zeros(len(records), dtype=np.int64)
    if len(records) > 1:
        np.cumsum(lengths[:-1], out=offsets[1:])
    return decode_spans_hist(buf, offsets, lengths, field_size, max_len)


def decode_spans_scatter(buf, offsets: np.ndarray, lengths: np.ndarray,
                         field_size: int, dest: np.ndarray,
                         labels: np.ndarray, ids: np.ndarray,
                         vals: np.ndarray) -> None:
    """Fused decode + scatter: decode record i of ``buf`` into row
    ``dest[i]`` of the caller-provided pool arrays (``labels`` [P],
    ``ids`` [P, field_size] int32, ``vals`` [P, field_size] float32, all
    C-contiguous). One pass over the records instead of decode-then-scatter
    (see ``CtrPipeline._iter_pooled_raw``); the caller guarantees every
    ``dest[i]`` is in bounds and disjoint across concurrent calls (the GIL
    is released inside the C call, so threads may fill disjoint rows of the
    same pool in parallel)."""
    lib = _load()
    assert lib is not None
    n = len(offsets)
    assert labels.flags.c_contiguous and ids.flags.c_contiguous \
        and vals.flags.c_contiguous
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    dest = np.ascontiguousarray(dest, dtype=np.int64)
    # The C side scatters unchecked (labels[dest[i]] etc.) — a caller bug
    # here is silent out-of-bounds heap writes, so validate the index
    # vector before handing over the pointers (advisor r5).
    if len(dest) != n:
        raise ValueError(
            f"decode_spans_scatter: len(dest)={len(dest)} != "
            f"len(offsets)={n}")
    rows = min(len(labels), len(ids), len(vals))
    if n and (int(dest.min()) < 0 or int(dest.max()) >= rows):
        raise ValueError(
            f"decode_spans_scatter: dest range [{int(dest.min())}, "
            f"{int(dest.max())}] outside pool of {rows} rows")
    if n == 0:
        return
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr_scatter(
        _as_ubyte_ptr(buf),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, field_size,
        dest.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(detail))
    if rc != 0:
        # Index is relative to THIS (possibly sub-span) call, not the chunk.
        raise ValueError(
            f"native scatter-decode failed at span-local record {-rc - 100}: "
            f"{_decode_reason(detail.value, field_size)}")


def has_assemble() -> bool:
    """True when the built library exports the fused multi-chunk
    decode->assemble entry (``dfm_decode_ctr_assemble``). False on a stale
    cached .so from an older source tree — callers fall back to the
    per-chunk ``decode_spans_scatter`` path, which emits identical bytes."""
    lib = _load()
    return lib is not None and hasattr(lib, "dfm_decode_ctr_assemble")


def _validate_assemble_jobs(jobs, labels, ids, vals):
    """Shared bounds check for the fused entry and its Python fallback: the
    C side scatters unchecked, so every destination row must be validated
    before the pointers are handed over (same contract as
    ``decode_spans_scatter``)."""
    assert labels.flags.c_contiguous and ids.flags.c_contiguous \
        and vals.flags.c_contiguous
    rows = min(labels.shape[0], ids.shape[0], vals.shape[0])
    for offsets, _, dest in ((j[1], j[2], j[3]) for j in jobs):
        if len(dest) != len(offsets):
            raise ValueError(
                f"assemble_spans: len(dest)={len(dest)} != "
                f"len(offsets)={len(offsets)}")
        if len(dest) and (int(dest.min()) < 0 or int(dest.max()) >= rows):
            raise ValueError(
                f"assemble_spans: dest range [{int(dest.min())}, "
                f"{int(dest.max())}] outside pool of {rows} rows")


def assemble_spans(jobs, field_size: int, labels: np.ndarray,
                   ids: np.ndarray, vals: np.ndarray) -> None:
    """Fused decode->assemble: decode EVERY framed chunk span straight into
    its permuted rows of the transfer-layout output buffers, in ONE
    GIL-released C call per drain.

    ``jobs`` is a sequence of ``(buf, offsets, lengths, dest)`` — chunk
    bytes plus int64 span/destination arrays; ``labels`` is the label
    column ([P] or [P, 1] float32 — same contiguous memory either way),
    ``ids``/``vals`` are [P, field_size]. The caller owns destination
    bounds and disjointness, exactly like ``decode_spans_scatter``; unlike
    it, the whole drain crosses ctypes once, so a contended host pays one
    GIL reacquisition per drain instead of one per chunk."""
    lib = _load()
    assert lib is not None
    if not jobs:
        return
    if not hasattr(lib, "dfm_decode_ctr_assemble"):
        # Stale .so without the entry: per-chunk scatter, identical bytes.
        for buf, offsets, lengths, dest in jobs:
            decode_spans_scatter(buf, offsets, lengths, field_size, dest,
                                 labels.reshape(-1), ids, vals)
        return
    n_chunks = len(jobs)
    norm = []
    for buf, offsets, lengths, dest in jobs:
        norm.append((buf,
                     np.ascontiguousarray(offsets, dtype=np.int64),
                     np.ascontiguousarray(lengths, dtype=np.int64),
                     np.ascontiguousarray(dest, dtype=np.int64)))
    _validate_assemble_jobs(norm, labels, ids, vals)
    # Per-chunk pointer tables + the concatenated dest vector. The np
    # arrays in ``norm`` (and the raw buffers) stay referenced until the
    # call returns, so every pointer below stays live.
    bufs_arr = (ctypes.c_void_p * n_chunks)(
        *(ctypes.cast(_as_ubyte_ptr(j[0]), ctypes.c_void_p) for j in norm))
    offs_arr = (ctypes.c_void_p * n_chunks)(
        *(j[1].ctypes.data for j in norm))
    lens_arr = (ctypes.c_void_p * n_chunks)(
        *(j[2].ctypes.data for j in norm))
    counts = np.fromiter((len(j[1]) for j in norm), dtype=np.int64,
                         count=n_chunks)
    dest_all = (norm[0][3] if n_chunks == 1
                else np.concatenate([j[3] for j in norm]))
    err_chunk = ctypes.c_long(-1)
    detail = ctypes.c_long(0)
    rc = lib.dfm_decode_ctr_assemble(
        bufs_arr, offs_arr, lens_arr,
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n_chunks, field_size,
        dest_all.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(err_chunk), ctypes.byref(detail))
    if rc != 0:
        raise ValueError(
            f"native assemble failed at record {-rc - 100} of chunk "
            f"{err_chunk.value}: {_decode_reason(detail.value, field_size)}")


def assemble_spans_python(jobs, field_size: int, labels: np.ndarray,
                          ids: np.ndarray, vals: np.ndarray) -> None:
    """Pure-Python mirror of ``assemble_spans`` (bit-identical emission):
    each record decodes with the Python Example codec straight into its
    destination row of the same transfer-layout buffers. The reference
    implementation the fused C entry is tested against, and the forced
    fallback when the toolchain is unavailable."""
    from ..data import example_codec  # noqa: PLC0415 (avoid module cycle)
    _validate_assemble_jobs(
        [(j[0], np.asarray(j[1]), np.asarray(j[2]), np.asarray(j[3]))
         for j in jobs],
        labels, ids, vals)
    lab_flat = labels.reshape(-1)
    for buf, offsets, lengths, dest in jobs:
        for off, ln, d in zip(np.asarray(offsets).tolist(),
                              np.asarray(lengths).tolist(),
                              np.asarray(dest).tolist()):
            lab, rid, rval = example_codec.decode_ctr_example(
                bytes(buf[off:off + ln]), field_size)
            lab_flat[d] = lab
            ids[d] = rid.astype(np.int32)
            vals[d] = rval


def decode_batch(records: Sequence[bytes], field_size: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized decode of a list of serialized Examples (pipeline hook)."""
    buf = b"".join(records)
    lengths = np.fromiter((len(r) for r in records), dtype=np.int64,
                          count=len(records))
    offsets = np.zeros(len(records), dtype=np.int64)
    if len(records) > 1:
        np.cumsum(lengths[:-1], out=offsets[1:])
    return decode_spans(buf, offsets, lengths, field_size)


def decode_file_bytes(buf: bytes, field_size: int, *, verify_crc: bool = True
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One-pass decode of a whole TFRecord file buffer."""
    offsets, lengths = split_frames(buf, verify_crc=verify_crc)
    return decode_spans(buf, offsets, lengths, field_size)
