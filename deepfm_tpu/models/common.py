"""Shared model building blocks: initializers, DNN tower with BN/dropout.

Behavioral parity notes (vs reference ``model_fn``, ``1-ps-cpu/...py:149-292``):
  * Hidden layers: dense -> ReLU -> [BatchNorm] -> [dropout] (BN applied
    *after* the activation, reference ``:219-221``).
  * ``dropout`` values are KEEP probabilities (``tf.nn.dropout(keep_prob=...)``
    reference ``:222``), applied in TRAIN mode only.
  * Final output layer: dense to 1 with identity activation (``:226``).
  * Weight init: glorot/Xavier (``glorot_normal_initializer`` for embeddings
    ``:167-168``; ``fully_connected`` default glorot_uniform for the tower).
  * Only FM_W / FM_V carry an effective l2 penalty — the tower's regularizer
    losses were never added to the loss in the reference (TF1 collection not
    collected), so the tower here has none.

TPU-first: tower matmuls run in ``compute_dtype`` (bfloat16 by default) with
float32 params and float32 loss; BN statistics are float32. Under data
parallelism (``data_axis`` set, inside shard_map) BatchNorm uses
*cross-replica* statistics via pmean — a deliberate improvement over the
reference's per-worker BN stats (deterministic w.r.t. world size).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]
State = Dict[str, Any]


def glorot_normal(rng: jax.Array, shape: Sequence[int],
                  dtype: jnp.dtype = jnp.float32) -> jax.Array:
    fan_in, fan_out = _fans(shape)
    std = (2.0 / (fan_in + fan_out)) ** 0.5
    return std * jax.random.normal(rng, shape, dtype)


def glorot_uniform(rng: jax.Array, shape: Sequence[int],
                   dtype: jnp.dtype = jnp.float32) -> jax.Array:
    fan_in, fan_out = _fans(shape)
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def _fans(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    recep = 1
    for s in shape[:-2]:
        recep *= s
    return shape[-2] * recep, shape[-1] * recep


# ---------------------------------------------------------------------------
# BatchNorm (running-stats state; reference batch_norm_layer :286-291)
# ---------------------------------------------------------------------------


def batch_norm(
    h32: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    bn_state: State,
    *,
    train: bool,
    decay: float,
    data_axis: Optional[str] = None,
    eps: float = 1e-3,
) -> Tuple[jnp.ndarray, State]:
    """Normalize h32 [B, D] (float32). Returns (normalized, new_bn_state)."""
    if train:
        mean = jnp.mean(h32, axis=0)
        mean_sq = jnp.mean(jnp.square(h32), axis=0)
        if data_axis is not None:
            mean = jax.lax.pmean(mean, data_axis)
            mean_sq = jax.lax.pmean(mean_sq, data_axis)
        var = mean_sq - jnp.square(mean)
        new_state = {
            "mean": decay * bn_state["mean"] + (1 - decay) * mean,
            "var": decay * bn_state["var"] + (1 - decay) * var,
        }
    else:
        mean, var = bn_state["mean"], bn_state["var"]
        new_state = bn_state
    out = (h32 - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    return out, new_state


# ---------------------------------------------------------------------------
# DNN tower
# ---------------------------------------------------------------------------


def init_hidden_stack(rng: jax.Array, in_dim: int, layer_sizes: Sequence[int],
                      use_bn: bool) -> Tuple[Params, State]:
    params: Params = {"layers": []}
    state: State = {"bn": []}
    dims = [in_dim] + list(layer_sizes)
    keys = jax.random.split(rng, max(len(layer_sizes), 1))
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        layer = {
            "w": glorot_uniform(keys[i], (d_in, d_out)),
            "b": jnp.zeros((d_out,), jnp.float32),
        }
        if use_bn:
            layer["bn_scale"] = jnp.ones((d_out,), jnp.float32)
            layer["bn_bias"] = jnp.zeros((d_out,), jnp.float32)
            state["bn"].append({
                "mean": jnp.zeros((d_out,), jnp.float32),
                "var": jnp.ones((d_out,), jnp.float32),
            })
        params["layers"].append(layer)
    return params, state


def apply_hidden_stack(
    params: Params,
    state: State,
    x: jnp.ndarray,
    *,
    train: bool,
    dropout_keep: Sequence[float],
    use_bn: bool,
    bn_decay: float,
    rng: Optional[jax.Array],
    compute_dtype: jnp.dtype = jnp.bfloat16,
    data_axis: Optional[str] = None,
) -> Tuple[jnp.ndarray, State]:
    """dense->relu->[BN]->[dropout] stack. x: [B, D_in] -> ([B, D_last], state)."""
    new_state: State = {"bn": []}
    h = x.astype(compute_dtype)
    n_layers = len(params["layers"])
    if train and rng is not None and n_layers:
        drop_keys = list(jax.random.split(rng, n_layers))
    else:
        drop_keys = [None] * n_layers
    for i, layer in enumerate(params["layers"]):
        h = h @ layer["w"].astype(compute_dtype) + layer["b"].astype(compute_dtype)
        h = jax.nn.relu(h)
        if use_bn:
            h32, bn_new = batch_norm(
                h.astype(jnp.float32), layer["bn_scale"], layer["bn_bias"],
                state["bn"][i], train=train, decay=bn_decay, data_axis=data_axis)
            new_state["bn"].append(bn_new)
            h = h32.astype(compute_dtype)
        keep = dropout_keep[i] if i < len(dropout_keep) else 1.0
        if train and keep < 1.0 and drop_keys[i] is not None:
            mask = jax.random.bernoulli(drop_keys[i], keep, h.shape)
            h = jnp.where(mask, h / keep, jnp.zeros((), h.dtype))
    return h, new_state


def init_tower(rng: jax.Array, in_dim: int, layer_sizes: Sequence[int],
               use_bn: bool) -> Tuple[Params, State]:
    """Hidden stack + final dense->1. Returns (params, bn_state)."""
    k_stack, k_out = jax.random.split(rng)
    params, state = init_hidden_stack(k_stack, in_dim, layer_sizes, use_bn)
    last = layer_sizes[-1] if layer_sizes else in_dim
    params["out"] = {
        "w": glorot_uniform(k_out, (last, 1)),
        "b": jnp.zeros((1,), jnp.float32),
    }
    return params, state


def apply_tower(
    params: Params,
    state: State,
    x: jnp.ndarray,
    *,
    train: bool,
    dropout_keep: Sequence[float],
    use_bn: bool,
    bn_decay: float,
    rng: Optional[jax.Array],
    compute_dtype: jnp.dtype = jnp.bfloat16,
    data_axis: Optional[str] = None,
) -> Tuple[jnp.ndarray, State]:
    """Run hidden stack + output head. x: [B, D] -> ([B], new_bn_state)."""
    h, new_state = apply_hidden_stack(
        params, state, x, train=train, dropout_keep=dropout_keep, use_bn=use_bn,
        bn_decay=bn_decay, rng=rng, compute_dtype=compute_dtype,
        data_axis=data_axis)
    out = h @ params["out"]["w"].astype(h.dtype) + params["out"]["b"].astype(h.dtype)
    return out.astype(jnp.float32)[:, 0], new_state


def l2_half_sum(x: jnp.ndarray) -> jnp.ndarray:
    """tf.nn.l2_loss semantics: 0.5 * sum(x^2) (reference loss ``:244-246``)."""
    return 0.5 * jnp.sum(jnp.square(x.astype(jnp.float32)))
