"""Shared model building blocks: initializers, DNN tower with BN/dropout.

Behavioral parity notes (vs reference ``model_fn``, ``1-ps-cpu/...py:149-292``):
  * Hidden layers: dense -> ReLU -> [BatchNorm] -> [dropout] (BN applied
    *after* the activation, reference ``:219-221``).
  * ``dropout`` values are KEEP probabilities (``tf.nn.dropout(keep_prob=...)``
    reference ``:222``), applied in TRAIN mode only.
  * Final output layer: dense to 1 with identity activation (``:226``).
  * Weight init: glorot/Xavier (``glorot_normal_initializer`` for embeddings
    ``:167-168``; ``fully_connected`` default glorot_uniform for the tower).
  * Only FM_W / FM_V carry an effective l2 penalty — the tower's regularizer
    losses were never added to the loss in the reference (TF1 collection not
    collected), so the tower here has none.

TPU-first: tower matmuls run in ``compute_dtype`` (bfloat16 by default) with
float32 params and float32 loss; BN statistics are float32. Under data
parallelism (``data_axis`` set, inside shard_map) BatchNorm uses
*cross-replica* statistics via pmean — a deliberate improvement over the
reference's per-worker BN stats (deterministic w.r.t. world size).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops import embedding as emb_ops
from ..ops import pallas_embedding as pemb

Params = Dict[str, Any]
State = Dict[str, Any]


def glorot_normal(rng: jax.Array, shape: Sequence[int],
                  dtype: jnp.dtype = jnp.float32) -> jax.Array:
    fan_in, fan_out = _fans(shape)
    std = (2.0 / (fan_in + fan_out)) ** 0.5
    return std * jax.random.normal(rng, shape, dtype)


def glorot_uniform(rng: jax.Array, shape: Sequence[int],
                   dtype: jnp.dtype = jnp.float32) -> jax.Array:
    fan_in, fan_out = _fans(shape)
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def _fans(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    recep = 1
    for s in shape[:-2]:
        recep *= s
    return shape[-2] * recep, shape[-1] * recep


# ---------------------------------------------------------------------------
# BatchNorm (running-stats state; reference batch_norm_layer :286-291)
# ---------------------------------------------------------------------------


def batch_norm(
    h32: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    bn_state: State,
    *,
    train: bool,
    decay: float,
    data_axis: Optional[str] = None,
    eps: float = 1e-3,
) -> Tuple[jnp.ndarray, State]:
    """Normalize h32 [B, D] (float32). Returns (normalized, new_bn_state)."""
    if train:
        mean = jnp.mean(h32, axis=0)
        mean_sq = jnp.mean(jnp.square(h32), axis=0)
        if data_axis is not None:
            mean = jax.lax.pmean(mean, data_axis)
            mean_sq = jax.lax.pmean(mean_sq, data_axis)
        var = mean_sq - jnp.square(mean)
        new_state = {
            "mean": decay * bn_state["mean"] + (1 - decay) * mean,
            "var": decay * bn_state["var"] + (1 - decay) * var,
        }
    else:
        mean, var = bn_state["mean"], bn_state["var"]
        new_state = bn_state
    out = (h32 - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    return out, new_state


# ---------------------------------------------------------------------------
# DNN tower
# ---------------------------------------------------------------------------


def init_hidden_stack(rng: jax.Array, in_dim: int, layer_sizes: Sequence[int],
                      use_bn: bool) -> Tuple[Params, State]:
    params: Params = {"layers": []}
    state: State = {"bn": []}
    dims = [in_dim] + list(layer_sizes)
    keys = jax.random.split(rng, max(len(layer_sizes), 1))
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        layer = {
            "w": glorot_uniform(keys[i], (d_in, d_out)),
            "b": jnp.zeros((d_out,), jnp.float32),
        }
        if use_bn:
            layer["bn_scale"] = jnp.ones((d_out,), jnp.float32)
            layer["bn_bias"] = jnp.zeros((d_out,), jnp.float32)
            state["bn"].append({
                "mean": jnp.zeros((d_out,), jnp.float32),
                "var": jnp.ones((d_out,), jnp.float32),
            })
        params["layers"].append(layer)
    return params, state


def apply_hidden_stack(
    params: Params,
    state: State,
    x: jnp.ndarray,
    *,
    train: bool,
    dropout_keep: Sequence[float],
    use_bn: bool,
    bn_decay: float,
    rng: Optional[jax.Array],
    compute_dtype: jnp.dtype = jnp.bfloat16,
    data_axis: Optional[str] = None,
) -> Tuple[jnp.ndarray, State]:
    """dense->relu->[BN]->[dropout] stack. x: [B, D_in] -> ([B, D_last], state)."""
    new_state: State = {"bn": []}
    h = x.astype(compute_dtype)
    n_layers = len(params["layers"])
    if train and rng is not None and n_layers:
        drop_keys = list(jax.random.split(rng, n_layers))
    else:
        drop_keys = [None] * n_layers
    for i, layer in enumerate(params["layers"]):
        h = h @ layer["w"].astype(compute_dtype) + layer["b"].astype(compute_dtype)
        h = jax.nn.relu(h)
        if use_bn:
            h32, bn_new = batch_norm(
                h.astype(jnp.float32), layer["bn_scale"], layer["bn_bias"],
                state["bn"][i], train=train, decay=bn_decay, data_axis=data_axis)
            new_state["bn"].append(bn_new)
            h = h32.astype(compute_dtype)
        keep = dropout_keep[i] if i < len(dropout_keep) else 1.0
        if train and keep < 1.0 and drop_keys[i] is not None:
            mask = jax.random.bernoulli(drop_keys[i], keep, h.shape)
            h = jnp.where(mask, h / keep, jnp.zeros((), h.dtype))
    return h, new_state


def init_tower(rng: jax.Array, in_dim: int, layer_sizes: Sequence[int],
               use_bn: bool) -> Tuple[Params, State]:
    """Hidden stack + final dense->1. Returns (params, bn_state)."""
    k_stack, k_out = jax.random.split(rng)
    params, state = init_hidden_stack(k_stack, in_dim, layer_sizes, use_bn)
    last = layer_sizes[-1] if layer_sizes else in_dim
    params["out"] = {
        "w": glorot_uniform(k_out, (last, 1)),
        "b": jnp.zeros((1,), jnp.float32),
    }
    return params, state


def apply_tower(
    params: Params,
    state: State,
    x: jnp.ndarray,
    *,
    train: bool,
    dropout_keep: Sequence[float],
    use_bn: bool,
    bn_decay: float,
    rng: Optional[jax.Array],
    compute_dtype: jnp.dtype = jnp.bfloat16,
    data_axis: Optional[str] = None,
) -> Tuple[jnp.ndarray, State]:
    """Run hidden stack + output head. x: [B, D] -> ([B], new_bn_state)."""
    h, new_state = apply_hidden_stack(
        params, state, x, train=train, dropout_keep=dropout_keep, use_bn=use_bn,
        bn_decay=bn_decay, rng=rng, compute_dtype=compute_dtype,
        data_axis=data_axis)
    out = h @ params["out"]["w"].astype(h.dtype) + params["out"]["b"].astype(h.dtype)
    return out.astype(jnp.float32)[:, 0], new_state


def l2_half_sum(x: jnp.ndarray) -> jnp.ndarray:
    """tf.nn.l2_loss semantics: 0.5 * sum(x^2) (reference loss ``:244-246``)."""
    return 0.5 * jnp.sum(jnp.square(x.astype(jnp.float32)))


# ---------------------------------------------------------------------------
# Embedding schema: monolithic vs hash-bucketed multi-table layout
# ---------------------------------------------------------------------------


class EmbeddingSchema:
    """Resolves cfg into the embedding-table layout and owns every operation
    the models and trainer perform on it.

    Two layouts behind one interface:

    * **monolithic** (``embedding_buckets`` empty): one ``[padded_vocab,...]``
      array per embedding param — the original layout, entry pytree and init
      numerics unchanged (checkpoints stay compatible).
    * **hashed** (``embedding_buckets`` set): a dict of N tables
      ``{"t0": [B0,...], ...}``; ids map to a table (by id-hash or by field)
      and to a per-table bucket via stateless uint32 mixing
      (ops.embedding.hash_bucket), so the *logical* ``feature_size`` can
      exceed any single physical allocation.

    The sparse-update path speaks :class:`ops.embedding.PlanEntry` per
    table: the trainer builds one plan per batch, gathers the touched rows
    as the gradient leaf, and the models consume the gathered view through
    ``lookup_rows`` — the cotangent scatter-add (the segment-sum) therefore
    sizes with the batch's unique ids, never with the vocab.
    """

    #: plan/rows dict key for the monolithic table
    MONO = "table"

    def __init__(self, cfg: Any):
        self.feature_size = int(cfg.feature_size)
        self.field_size = int(cfg.field_size)
        self.buckets: List[int] = list(cfg.embedding_bucket_sizes)
        self.hashed = bool(self.buckets)
        self.assign = cfg.embedding_assign
        self.lookup_strategy = cfg.embedding_lookup
        self.kernels = getattr(cfg, "embedding_kernels", "auto")
        self.padded_vocab = emb_ops.padded_vocab(
            cfg.feature_size, cfg.mesh_model)
        # Row-sharding metadata (--embedding_shard rows): num_shards is the
        # model-axis size the tables are partitioned over; 1 means every
        # device holds full tables (the replicated layout). Table SHAPES
        # never depend on this (padded_vocab is mesh-independent), only
        # the placement and the step program do.
        self.shard_rows = getattr(cfg, "embedding_shard", "off") == "rows"
        self.num_shards = max(int(cfg.mesh_model), 1) if self.shard_rows else 1

    def table_rows(self, key: str) -> int:
        """Global row count of one physical table."""
        if not self.hashed:
            return self.padded_vocab
        return self.buckets[int(key[1:])]

    def rows_local(self, key: str) -> int:
        """Rows per shard of one table (== table_rows when unsharded)."""
        return self.table_rows(key) // self.num_shards

    # -- layout ---------------------------------------------------------
    def table_keys(self) -> List[str]:
        if not self.hashed:
            return [self.MONO]
        return [f"t{i}" for i in range(len(self.buckets))]

    def num_physical_rows(self) -> int:
        """Rows actually allocated (vs the logical feature_size)."""
        return sum(self.buckets) if self.hashed else self.padded_vocab

    def init_entry(self, rng: jax.Array, trailing: Tuple[int, ...]) -> Any:
        """Glorot-normal tables (reference embedding init). Monolithic
        reproduces the original init bit-for-bit: glorot over the REAL
        vocab, zero pad rows concatenated after."""
        if not self.hashed:
            t = glorot_normal(rng, (self.feature_size, *trailing))
            if self.padded_vocab != self.feature_size:
                pad = self.padded_vocab - self.feature_size
                t = jnp.concatenate(
                    [t, jnp.zeros((pad, *trailing), t.dtype)])
            return t
        keys = jax.random.split(rng, len(self.buckets))
        return {f"t{i}": glorot_normal(keys[i], (b, *trailing))
                for i, b in enumerate(self.buckets)}

    # -- id -> (table, bucket) mapping ---------------------------------
    def _table_of(self, feat_ids: jnp.ndarray) -> jnp.ndarray:
        n = len(self.buckets)
        if self.assign == "field":
            f = jnp.arange(feat_ids.shape[-1], dtype=jnp.int32) % n
            return jnp.broadcast_to(f, feat_ids.shape)
        return emb_ops.hash_table_assign(feat_ids, n)

    # -- dense forward --------------------------------------------------
    def lookup(self, entry: Any, feat_ids: jnp.ndarray, *,
               axis_name: Optional[str] = None) -> jnp.ndarray:
        """[B,F,*trailing] gather for the dense path (and eval/predict)."""
        if not self.hashed:
            return emb_ops.lookup(entry, feat_ids, axis_name=axis_name,
                                  strategy=self.lookup_strategy)
        table_of = self._table_of(feat_ids)
        shard = (jax.lax.axis_index(axis_name)
                 if axis_name is not None else None)
        out = None
        for i, b in enumerate(self.buckets):
            bucket = emb_ops.hash_bucket(feat_ids, b, salt=i + 1)
            tab = entry[f"t{i}"]
            if shard is None:
                part = jnp.take(tab, bucket, axis=0)
            else:
                # Row-sharded bucket (--embedding_shard rows): local
                # masked take; ONE psum below reassembles every bucket's
                # shard contributions at once.
                local = bucket - shard * tab.shape[0]
                ok = (local >= 0) & (local < tab.shape[0])
                part = jnp.take(tab, jnp.clip(local, 0, tab.shape[0] - 1),
                                axis=0)
                okx = ok.reshape(ok.shape + (1,) * (part.ndim - ok.ndim))
                part = jnp.where(okx, part, jnp.zeros((), part.dtype))
            sel = (table_of == i).astype(part.dtype)
            sel = sel.reshape(sel.shape + (1,) * (part.ndim - sel.ndim))
            part = part * sel
            out = part if out is None else out + part
        if axis_name is not None:
            out = jax.lax.psum(out, axis_name)
        return out

    # -- sparse-update plan ---------------------------------------------
    def sparse_plan(self, feat_ids: jnp.ndarray,
                    num_rows: Optional[int] = None
                    ) -> Dict[str, emb_ops.PlanEntry]:
        """One batch's dedup plan per table. ``num_rows`` overrides the
        monolithic OOB fill id (the tiered runtime feeds SLOT ids, whose
        table is embedding_hot_rows tall — padded_vocab still works as the
        fill because slots < hot_rows < padded_vocab, but an explicit
        override keeps intent readable)."""
        if not self.hashed:
            rows = self.padded_vocab if num_rows is None else int(num_rows)
            return {self.MONO: pemb.plan_build(feat_ids, rows,
                                               mode=self.kernels)}
        table_of = self._table_of(feat_ids)
        plan = {}
        for i, b in enumerate(self.buckets):
            bucket = emb_ops.hash_bucket(feat_ids, b, salt=i + 1)
            sel = table_of == i
            per_table = jnp.where(sel, bucket, jnp.int32(b))  # OOB when not ours
            plan[f"t{i}"] = pemb.plan_build(
                per_table, b, mask=sel.astype(jnp.float32),
                mode=self.kernels)
        return plan

    def tables(self, entry: Any) -> Dict[str, jax.Array]:
        """Uniform dict view of an entry: {key: [rows, ...] table}."""
        return entry if self.hashed else {self.MONO: entry}

    def from_tables(self, tables: Dict[str, jax.Array]) -> Any:
        return tables if self.hashed else tables[self.MONO]

    def gather_rows(self, entry: Any, plan: Dict[str, emb_ops.PlanEntry]
                    ) -> Dict[str, jax.Array]:
        """Touched rows per table — the sparse path's gradient leaf."""
        tabs = self.tables(entry)
        return {k: emb_ops.gather_rows(tabs[k], plan[k]) for k in plan}

    def lookup_rows(self, rows: Dict[str, jax.Array],
                    plan: Optional[Dict[str, emb_ops.PlanEntry]]
                    ) -> jnp.ndarray:
        """[B,F,*trailing] forward view over pre-gathered rows. When
        ``plan`` is None the rows are already the [B,F,...] batch view
        (the fused-backward path remaps once for all params up front)."""
        if plan is None:
            assert len(rows) == 1
            return next(iter(rows.values()))
        out = None
        for k in plan:
            part = emb_ops.lookup_rows(rows[k], plan[k])
            out = part if out is None else out + part
        return out

    # -- regularization -------------------------------------------------
    def l2(self, entry: Any, *, axis_name: Optional[str] = None
           ) -> jnp.ndarray:
        """0.5*sum(x^2) over REAL rows only — padded_vocab pad rows are
        structurally excluded (they are zero, so the value is unchanged;
        the exclusion guarantees their gradient is exactly zero by
        construction, not by reachability argument)."""
        if self.hashed:
            return sum(l2_half_sum(t) for t in entry.values())
        keep = emb_ops.pad_row_mask(entry.shape[0], self.feature_size,
                                    axis_name)
        keep = keep.reshape((-1,) + (1,) * (entry.ndim - 1))
        sq = jnp.square(entry.astype(jnp.float32))
        return 0.5 * jnp.sum(jnp.where(keep, sq, jnp.zeros((), sq.dtype)))

    def l2_rows(self, rows: Dict[str, jax.Array],
                plan: Dict[str, emb_ops.PlanEntry]) -> jnp.ndarray:
        """Sparse-mode L2 over the batch's TOUCHED rows only (OOB fill
        slots excluded). Deliberate deviation from dense L2 — idle rows do
        not decay between touches; TUNING §2.11 quantifies the drift."""
        total = None
        for k, entry in plan.items():
            valid = emb_ops.valid_rows(entry).astype(jnp.float32)
            valid = valid.reshape((-1,) + (1,) * (rows[k].ndim - 1))
            sq = jnp.square(rows[k].astype(jnp.float32)) * valid
            s = 0.5 * jnp.sum(sq)
            total = s if total is None else total + s
        return total

    def mask_pad_grads(self, grad_entry: Any, *,
                       axis_name: Optional[str] = None) -> Any:
        """Zero pad-row gradients on the dense path (hashed tables have no
        pad rows — every bucket is reachable)."""
        if self.hashed:
            return grad_entry
        return emb_ops.mask_pad_rows(grad_entry, self.feature_size,
                                     axis_name)
