"""DCN-v2: cross network (explicit feature crosses) + DNN tower.

BASELINE.json config 5: "DCN-v2 on Avazu TFRecord (cross-network tower,
stresses dense-interaction kernel)". Same sparse-CTR input contract as
DeepFM. Cross layers follow DCN-v2 (Wang et al., 2021):

    x_{l+1} = x_0 * (W_l x_l + b_l) + x_l          (full-rank)
    x_{l+1} = x_0 * (U_l (V_l x_l) + b_l) + x_l    (low-rank, cross_rank > 0)

The [D, D] cross matmuls (D = F*K) are dense MXU work — this model is the
dense-interaction stress case of the benchmark suite. Output combines the
cross tower and the deep tower (stacked-parallel structure): logits =
b + dense(concat(cross_out, deep_out)).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import Config
from . import common
from .deepfm import DeepFM


class DCNv2(DeepFM):
    name = "dcnv2"

    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        params, bn_state = super().init(rng)
        d = cfg.field_size * cfg.embedding_size
        keys = jax.random.split(jax.random.fold_in(rng, 7), cfg.cross_layers)
        cross = []
        for i in range(cfg.cross_layers):
            if cfg.cross_rank > 0:
                cross.append({
                    "u": common.glorot_uniform(keys[i], (cfg.cross_rank, d)),
                    "v": common.glorot_uniform(
                        jax.random.fold_in(keys[i], 1), (d, cfg.cross_rank)),
                    "b": jnp.zeros((d,), jnp.float32),
                })
            else:
                cross.append({
                    "w": common.glorot_uniform(keys[i], (d, d)),
                    "b": jnp.zeros((d,), jnp.float32),
                })
        params["cross"] = cross
        # Combination head over concat(cross_out[D], deep_out_hidden).
        deep_out_dim = cfg.deep_layer_sizes[-1] if cfg.deep_layer_sizes else d
        params["head"] = {
            "w": common.glorot_uniform(
                jax.random.fold_in(rng, 11), (d + deep_out_dim, 1)),
            "b": jnp.zeros((1,), jnp.float32),
        }
        return params, bn_state

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,
        feat_vals: jnp.ndarray,
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        cdt = jnp.dtype(cfg.compute_dtype)
        feat_vals = feat_vals.astype(jnp.float32)

        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        xv = v * feat_vals[..., None]
        x0 = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)

        # Cross tower.
        x0c = x0.astype(cdt)
        x = x0c
        for layer in params["cross"]:
            if "u" in layer:
                inner = (x @ layer["v"].astype(cdt)) @ layer["u"].astype(cdt)
            else:
                inner = x @ layer["w"].astype(cdt)
            x = x0c * (inner + layer["b"].astype(cdt)) + x
        cross_out = x

        # Deep tower (hidden stack only; the head combines both towers).
        h, new_state = common.apply_hidden_stack(
            params["tower"], state, x0, train=train,
            dropout_keep=cfg.dropout_rates, use_bn=cfg.batch_norm,
            bn_decay=cfg.batch_norm_decay, rng=rng, compute_dtype=cdt,
            data_axis=data_axis)

        combined = jnp.concatenate([cross_out, h.astype(cdt)], axis=1)
        out = combined @ params["head"]["w"].astype(cdt) + params["head"]["b"].astype(cdt)
        logits = params["fm_b"][0] + out.astype(jnp.float32)[:, 0]
        return logits, new_state
