"""DCN-v2: cross network (explicit feature crosses) + DNN tower.

BASELINE.json config 5: "DCN-v2 on Avazu TFRecord (cross-network tower,
stresses dense-interaction kernel)". Same sparse-CTR input contract as
DeepFM. Cross layers follow DCN-v2 (Wang et al., 2021):

    x_{l+1} = x_0 * (W_l x_l + b_l) + x_l          (full-rank)
    x_{l+1} = x_0 * (U_l (V_l x_l) + b_l) + x_l    (low-rank, cross_rank > 0)

The [D, D] cross matmuls (D = F*K) are dense MXU work — this model is the
dense-interaction stress case of the benchmark suite.

The implementation lives in ``models.graph`` (cross_network block + hidden
stack + combination head); this class is a thin, bit-identical wrapper kept
for the public name.
"""

from __future__ import annotations

from .graph import GraphDCNv2


class DCNv2(GraphDCNv2):
    name = "dcnv2"
