"""Model zoo registry: single-task graphs + the multi-task head wrapper.

``get_model`` is the one dispatch point: a config with more than one task
(``--tasks ctr,cvr``) builds the multi-task model (``--multitask``
architecture over the shared graph bottom); otherwise ``cfg.model`` picks a
single-task graph from the registry.
"""

from typing import Union

from ..config import Config
from .dcnv2 import DCNv2  # noqa: F401
from .deepfm import DeepFM  # noqa: F401
from .graph import DLRM  # noqa: F401
from .multitask import MultiTaskModel  # noqa: F401
from .sequence import GraphBST, GraphDIN  # noqa: F401
from .widedeep import WideDeep  # noqa: F401

_REGISTRY = {
    "deepfm": DeepFM,
    "widedeep": WideDeep,
    "dcnv2": DCNv2,
    "dlrm": DLRM,
    "din": GraphDIN,
    "bst": GraphBST,
}

CtrModel = Union[DeepFM, WideDeep, DCNv2, DLRM, GraphDIN, GraphBST,
                 MultiTaskModel]


def registered_models():
    """Registered single-task model names (the ``--model`` whitelist)."""
    return sorted(_REGISTRY)


def get_model(cfg: Config) -> CtrModel:
    if cfg.num_tasks > 1:
        return MultiTaskModel(cfg)
    try:
        return _REGISTRY[cfg.model](cfg)
    except KeyError:
        raise ValueError(f"unknown model {cfg.model!r}; have {sorted(_REGISTRY)}")
