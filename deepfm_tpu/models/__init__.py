"""Model zoo registry: deepfm | widedeep | dcnv2 (BASELINE.json configs)."""

from typing import Union

from ..config import Config
from .dcnv2 import DCNv2  # noqa: F401
from .deepfm import DeepFM  # noqa: F401
from .widedeep import WideDeep  # noqa: F401

_REGISTRY = {
    "deepfm": DeepFM,
    "widedeep": WideDeep,
    "dcnv2": DCNv2,
}

CtrModel = Union[DeepFM, WideDeep, DCNv2]


def get_model(cfg: Config) -> CtrModel:
    try:
        return _REGISTRY[cfg.model](cfg)
    except KeyError:
        raise ValueError(f"unknown model {cfg.model!r}; have {sorted(_REGISTRY)}")
