"""Explicit feature→tower graph: the decomposition every ranking model rides.

Every model in the zoo factors into the same three stages:

  1. **Embedding lookup** — named `EmbeddingSchema` entries (``fm_w`` [V],
     ``fm_v`` [V,K]) gathered per batch; row-shardable over the ``model``
     mesh axis, or fed pre-gathered touched rows on the sparse-update path.
  2. **Shared interaction blocks** — pure functions over the embedded
     features: first-order sum, FM second-order, DCN-v2 cross network,
     DLRM dot-interaction, the DNN hidden stack (``models.common``), and
     the MMoE expert mixture (``models.multitask``).
  3. **Task heads** — each named task reduces the block outputs to one
     logit. Single-task graphs emit ``[B]``; multi-task graphs
     (``models.multitask``) emit ``[B, T]`` with per-task losses combined
     by configurable weights.

The legacy classes (``DeepFM``, ``WideDeep``, ``DCNv2``) are thin wrappers
over the graph classes here: identical RNG key derivation and identical op
order, so forward, loss, and training trajectories are bit-identical to the
pre-graph implementations (pinned by tests/test_multitask.py and the NumPy
oracles in tests/test_models.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config
from ..ops import fm as fm_ops
from ..ops import pallas_fm
from . import common


# ----------------------------------------------------------------------
# Interaction blocks: pure functions over embedded features.
# ----------------------------------------------------------------------

def first_order(w: jnp.ndarray, feat_vals: jnp.ndarray) -> jnp.ndarray:
    """Linear term sum_f W[ids]*vals — the "wide" part. [B,F] -> [B]."""
    return jnp.sum(w * feat_vals, axis=1)


def fm_block(cfg: Config, w: jnp.ndarray, feat_vals: jnp.ndarray,
             xv: jnp.ndarray) -> jnp.ndarray:
    """First-order + FM second-order in one block (fused on TPU).

    Matches DeepFM's reference graph: ``sum_f(W*vals) + FM(xv)``. Takes the
    Pallas fused kernel when supported — both reductions in one VMEM pass —
    else the factored identity from ``ops.fm``.
    """
    if cfg.use_pallas and pallas_fm.supported(cfg.field_size,
                                              cfg.embedding_size):
        # Fused Pallas path: both FM reductions in one VMEM pass over the
        # same xv the tower consumes; d(xv)->d(v),d(vals) via JAX's
        # product rule outside the kernel.
        return pallas_fm.fused_fm(w, feat_vals, xv)
    return jnp.sum(w * feat_vals, axis=1) + fm_ops.fm_interaction(xv)


def init_cross_layer(key: jax.Array, d: int, cross_rank: int
                     ) -> Dict[str, jnp.ndarray]:
    """One DCN-v2 cross layer: full-rank W [D,D] or low-rank U/V factors."""
    if cross_rank > 0:
        return {
            "u": common.glorot_uniform(key, (cross_rank, d)),
            "v": common.glorot_uniform(
                jax.random.fold_in(key, 1), (d, cross_rank)),
            "b": jnp.zeros((d,), jnp.float32),
        }
    return {
        "w": common.glorot_uniform(key, (d, d)),
        "b": jnp.zeros((d,), jnp.float32),
    }


def cross_network(cross_params, x0c: jnp.ndarray,
                  compute_dtype: jnp.dtype) -> jnp.ndarray:
    """DCN-v2 cross tower: x_{l+1} = x0 * (W_l x_l + b_l) + x_l.

    ``x0c`` must already be cast to ``compute_dtype``; per-layer weights are
    cast inside the loop (the MXU-friendly recipe the legacy class used).
    """
    cdt = compute_dtype
    x = x0c
    for layer in cross_params:
        if "u" in layer:
            inner = (x @ layer["v"].astype(cdt)) @ layer["u"].astype(cdt)
        else:
            inner = x @ layer["w"].astype(cdt)
        x = x0c * (inner + layer["b"].astype(cdt)) + x
    return x


def dot_interaction(xv: jnp.ndarray) -> jnp.ndarray:
    """DLRM-style pairwise dot-interaction (Naumov et al., 2019).

    All F·(F-1)/2 distinct pairwise dots of the per-field embedding vectors:
    [B,F,K] -> [B, F*(F-1)/2]. The Gram matmul is MXU work; the triangular
    gather indices are static.
    """
    f = xv.shape[1]
    iu, ju = np.triu_indices(f, k=1)
    gram = jnp.matmul(xv, jnp.swapaxes(xv, 1, 2))  # [B,F,F]
    return gram[:, iu, ju]


# ----------------------------------------------------------------------
# Graph model skeleton: embedding stage + generic regularization.
# ----------------------------------------------------------------------

class GraphModel:
    """Shared skeleton of every feature→tower graph.

    Owns the embedding stage (schema, dense/sparse lookup, pad-aware L2)
    so concrete graphs only wire interaction blocks and heads. Subclasses
    define ``init`` and ``apply``; ``task_names``/``num_tasks`` default to
    the single-task contract (logits ``[B]``).
    """

    name = "graph"
    task_names: Tuple[str, ...] = ("ctr",)

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.emb = common.EmbeddingSchema(cfg)
        self.padded_vocab = self.emb.padded_vocab

    @property
    def num_tasks(self) -> int:
        return len(self.task_names)

    def _emb_lookup(self, params: common.Params, name: str,
                    feat_ids: jnp.ndarray, shard_axis: Optional[str],
                    emb_rows: Optional[Dict[str, Any]],
                    emb_plan: Optional[Dict[str, Any]]) -> jnp.ndarray:
        """Dense gather from the full table, or (sparse-update path) the
        batch's pre-gathered touched rows — ``emb_rows[name]`` is the
        gradient leaf there, so AD of this inverse-index gather lowers to
        the batch-sized segment-sum scatter instead of a full-table one."""
        if emb_rows is not None:
            return self.emb.lookup_rows(emb_rows[name], emb_plan)
        return self.emb.lookup(params[name], feat_ids, axis_name=shard_axis)

    def l2_loss(self, params: common.Params, *,
                shard_axis: Optional[str] = None,
                emb_rows: Optional[Dict[str, Any]] = None,
                emb_plan: Optional[Dict[str, Any]] = None) -> jnp.ndarray:
        """l2_reg * sum of pad-aware L2 over every embedding entry
        (reference :244-246). The sparse path penalizes only the batch's
        touched rows (TUNING §2.11)."""
        names = self.embedding_param_names()
        if emb_rows is not None:
            total = self.emb.l2_rows(emb_rows[names[0]], emb_plan)
            for n in names[1:]:
                total = total + self.emb.l2_rows(emb_rows[n], emb_plan)
        else:
            total = self.emb.l2(params[names[0]], axis_name=shard_axis)
            for n in names[1:]:
                total = total + self.emb.l2(params[n], axis_name=shard_axis)
        return self.cfg.l2_reg * total

    def embedding_param_names(self) -> Tuple[str, ...]:
        """Top-level param keys that are row-sharded over the model axis."""
        return ("fm_w", "fm_v")


class GraphDeepFM(GraphModel):
    """DeepFM as a graph: (fm_w, fm_v) → [fm_block, tower] → ctr head."""

    name = "deepfm"

    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        k_w, k_v, k_mlp = jax.random.split(rng, 3)
        fm_w = self.emb.init_entry(k_w, ())
        fm_v = self.emb.init_entry(k_v, (cfg.embedding_size,))
        tower, bn_state = common.init_tower(
            k_mlp, cfg.field_size * cfg.embedding_size, cfg.deep_layer_sizes,
            cfg.batch_norm)
        params = {"fm_b": jnp.zeros((1,), jnp.float32),
                  "fm_w": fm_w, "fm_v": fm_v, "tower": tower}
        return params, bn_state

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,   # int32 [B, F]
        feat_vals: jnp.ndarray,  # f32 [B, F]
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)

        # Embedding stage (reference :177-187).
        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F]
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F,K]
        xv = v * feat_vals[..., None]

        # Interaction blocks: fused first+second order FM, deep tower over
        # flattened xv (reference :203-226).
        y_wv = fm_block(cfg, w, feat_vals, xv)
        deep_in = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)
        tower_fn = lambda p, x: common.apply_tower(
            p, state, x, train=train, dropout_keep=cfg.dropout_rates,
            use_bn=cfg.batch_norm, bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)
        if cfg.remat:
            y_d, new_state = jax.checkpoint(tower_fn)(params["tower"], deep_in)
        else:
            y_d, new_state = tower_fn(params["tower"], deep_in)

        logits = params["fm_b"][0] + y_wv + y_d  # [B] (reference :229-231)
        return logits, new_state


class GraphWideDeep(GraphDeepFM):
    """Wide&Deep as a graph: first_order block + tower, no FM term."""

    name = "widedeep"

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,
        feat_vals: jnp.ndarray,
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)

        # Wide: linear over sparse features (first-order block).
        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        y_wide = first_order(w, feat_vals)

        # Deep: tower over embedded features.
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        xv = v * feat_vals[..., None]
        deep_in = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)
        y_d, new_state = common.apply_tower(
            params["tower"], state, deep_in, train=train,
            dropout_keep=cfg.dropout_rates, use_bn=cfg.batch_norm,
            bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)

        logits = params["fm_b"][0] + y_wide + y_d
        return logits, new_state


class GraphDCNv2(GraphDeepFM):
    """DCN-v2 as a graph: cross_network + hidden stack → combination head."""

    name = "dcnv2"

    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        params, bn_state = super().init(rng)
        d = cfg.field_size * cfg.embedding_size
        keys = jax.random.split(jax.random.fold_in(rng, 7), cfg.cross_layers)
        cross = []
        for i in range(cfg.cross_layers):
            cross.append(init_cross_layer(keys[i], d, cfg.cross_rank))
        params["cross"] = cross
        # Combination head over concat(cross_out[D], deep_out_hidden).
        deep_out_dim = cfg.deep_layer_sizes[-1] if cfg.deep_layer_sizes else d
        params["head"] = {
            "w": common.glorot_uniform(
                jax.random.fold_in(rng, 11), (d + deep_out_dim, 1)),
            "b": jnp.zeros((1,), jnp.float32),
        }
        return params, bn_state

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,
        feat_vals: jnp.ndarray,
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        cdt = jnp.dtype(cfg.compute_dtype)
        feat_vals = feat_vals.astype(jnp.float32)

        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        xv = v * feat_vals[..., None]
        x0 = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)

        # Cross tower.
        x0c = x0.astype(cdt)
        cross_out = cross_network(params["cross"], x0c, cdt)

        # Deep tower (hidden stack only; the head combines both towers).
        h, new_state = common.apply_hidden_stack(
            params["tower"], state, x0, train=train,
            dropout_keep=cfg.dropout_rates, use_bn=cfg.batch_norm,
            bn_decay=cfg.batch_norm_decay, rng=rng, compute_dtype=cdt,
            data_axis=data_axis)

        combined = jnp.concatenate([cross_out, h.astype(cdt)], axis=1)
        out = combined @ params["head"]["w"].astype(cdt) + params["head"]["b"].astype(cdt)
        logits = params["fm_b"][0] + out.astype(jnp.float32)[:, 0]
        return logits, new_state


class DLRM(GraphDeepFM):
    """DLRM-style model: first-order + tower over [xv, pairwise dots].

    Naumov et al. (2019): the dense tower consumes the flattened embeddings
    concatenated with all pairwise dot products of the per-field embedding
    vectors — explicit second-order crosses without the FM rank-1 collapse.
    Same input contract and embedding tables as DeepFM.
    """

    name = "dlrm"

    def top_input_dim(self) -> int:
        cfg = self.cfg
        return (cfg.field_size * cfg.embedding_size
                + cfg.field_size * (cfg.field_size - 1) // 2)

    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        k_w, k_v, k_mlp = jax.random.split(rng, 3)
        fm_w = self.emb.init_entry(k_w, ())
        fm_v = self.emb.init_entry(k_v, (cfg.embedding_size,))
        tower, bn_state = common.init_tower(
            k_mlp, self.top_input_dim(), cfg.deep_layer_sizes, cfg.batch_norm)
        params = {"fm_b": jnp.zeros((1,), jnp.float32),
                  "fm_w": fm_w, "fm_v": fm_v, "tower": tower}
        return params, bn_state

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,
        feat_vals: jnp.ndarray,
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)

        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        xv = v * feat_vals[..., None]

        y_first = first_order(w, feat_vals)
        flat = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)
        top_in = jnp.concatenate([flat, dot_interaction(xv)], axis=1)
        tower_fn = lambda p, x: common.apply_tower(
            p, state, x, train=train, dropout_keep=cfg.dropout_rates,
            use_bn=cfg.batch_norm, bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)
        if cfg.remat:
            y_d, new_state = jax.checkpoint(tower_fn)(params["tower"], top_in)
        else:
            y_d, new_state = tower_fn(params["tower"], top_in)

        logits = params["fm_b"][0] + y_first + y_d
        return logits, new_state
