"""Sequence models over user-history features: DIN/BST target attention.

Opens the variable-length scenario class on top of the PR-9 graph refactor:
batches may carry ``hist_ids`` int32 [B, L] / ``hist_mask`` f32 [B, L]
columns (the pipeline's fixed-shape padding of the ragged on-disk
``hist_ids``/``hist_vals`` pair), and the graphs here attend over that
history with the CANDIDATE as the query:

  * ``GraphDIN`` — Deep Interest Network (Zhou et al., KDD'18) target
    attention: additive-MLP relevance scores between the candidate
    embedding and each history embedding, mask-aware softmax
    (``ops.fm.masked_softmax`` — exact zeros, never NaN, on empty
    histories), attention-weighted history sum appended to the DeepFM
    tower input.
  * ``GraphBST`` — Behavior Sequence Transformer (Chen et al., 2019):
    ONE transformer block with learned positions over
    ``[history..., target]``, the target slot's output appended to the
    tower input.

Both keep the full DeepFM interaction path (fm_w/fm_v first+second order),
so they are drop-in members of the zoo: same ``apply`` contract, same
``embedding_param_names`` — history lookups route through the SAME
``EmbeddingSchema`` entry ``fm_v`` (hash bucketing and row sharding compose
for free). Called without history kwargs they see an empty history (the
attention contributes exact zeros), which is what the parametrized
zoo/checkpoint/forward tests exercise.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import fm as fm_ops
from . import common
from .graph import GraphDeepFM, fm_block


def init_target_attention(key: jax.Array, k_dim: int, att_dim: int
                          ) -> Dict[str, jnp.ndarray]:
    """DIN attention unit params: additive MLP over
    [query, key, query-key, query*key] -> score."""
    return {
        "w1": common.glorot_uniform(key, (4 * k_dim, att_dim)),
        "b1": jnp.zeros((att_dim,), jnp.float32),
        "w2": common.glorot_uniform(jax.random.fold_in(key, 1), (att_dim, 1)),
        "b2": jnp.zeros((1,), jnp.float32),
    }


def target_attention(att: Dict[str, jnp.ndarray], query: jnp.ndarray,
                     keys: jnp.ndarray, mask: jnp.ndarray,
                     compute_dtype: jnp.dtype = jnp.float32) -> jnp.ndarray:
    """DIN-style target attention block.

    query [B, K] (candidate embedding), keys [B, L, K] (history
    embeddings), mask [B, L] (>0 = real history position). Returns the
    attention-weighted history sum [B, K]; an all-masked (empty) history
    row returns exact zeros via ``masked_softmax``.
    """
    cdt = compute_dtype
    q = jnp.broadcast_to(query[:, None, :], keys.shape).astype(cdt)
    k = keys.astype(cdt)
    feats = jnp.concatenate([q, k, q - k, q * k], axis=-1)  # [B, L, 4K]
    h = jax.nn.relu(feats @ att["w1"].astype(cdt) + att["b1"].astype(cdt))
    scores = (h @ att["w2"].astype(cdt) + att["b2"].astype(cdt))[..., 0]
    weights = fm_ops.masked_softmax(scores.astype(jnp.float32),
                                    mask.astype(jnp.float32))  # [B, L]
    return jnp.sum(weights[..., None] * keys.astype(jnp.float32), axis=1)


def _empty_history(batch: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Static-shape stand-in when a caller passes no history: one all-masked
    position, so the attention output is exactly zero."""
    return (jnp.zeros((batch, 1), jnp.int32),
            jnp.zeros((batch, 1), jnp.float32))


class GraphDIN(GraphDeepFM):
    """DeepFM + DIN target attention over the user history.

    Tower input grows by one K-vector (the attended history); everything
    else — embedding entries, fm_block, head — is the DeepFM graph, so
    ``fm_v`` keeps a nonzero gradient even with an empty history.
    """

    name = "din"
    #: trainer forwards hist_ids/hist_mask batch columns when present
    uses_history = True

    def _att_dim(self) -> int:
        return max(8, 2 * self.cfg.embedding_size)

    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        k_w, k_v, k_mlp = jax.random.split(rng, 3)
        fm_w = self.emb.init_entry(k_w, ())
        fm_v = self.emb.init_entry(k_v, (cfg.embedding_size,))
        tower, bn_state = common.init_tower(
            k_mlp, cfg.field_size * cfg.embedding_size + cfg.embedding_size,
            cfg.deep_layer_sizes, cfg.batch_norm)
        params = {"fm_b": jnp.zeros((1,), jnp.float32),
                  "fm_w": fm_w, "fm_v": fm_v, "tower": tower,
                  "att": init_target_attention(
                      jax.random.fold_in(rng, 13), cfg.embedding_size,
                      self._att_dim())}
        return params, bn_state

    def _history_summary(self, params: common.Params, query: jnp.ndarray,
                         hist_ids: jnp.ndarray, hist_mask: jnp.ndarray,
                         shard_axis: Optional[str]) -> jnp.ndarray:
        """[B, K] attended history. Dense schema lookup always — the sparse
        plan covers feat_ids only (Config.validate gates sparse+history)."""
        keys = self.emb.lookup(params["fm_v"], hist_ids,
                               axis_name=shard_axis)  # [B, L, K]
        return target_attention(
            params["att"], query, keys, hist_mask,
            compute_dtype=jnp.dtype(self.cfg.compute_dtype))

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,   # int32 [B, F]
        feat_vals: jnp.ndarray,  # f32 [B, F]
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
        hist_ids: Optional[jnp.ndarray] = None,   # int32 [B, L]
        hist_mask: Optional[jnp.ndarray] = None,  # f32 [B, L]
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)
        if hist_ids is None:
            hist_ids, hist_mask = _empty_history(feat_ids.shape[0])

        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F]
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F,K]
        xv = v * feat_vals[..., None]

        # Candidate query: the value-weighted sum of the example's field
        # embeddings — the "target item" representation the attention
        # scores every history position against.
        query = jnp.sum(xv, axis=1)  # [B, K]
        hist = self._history_summary(params, query, hist_ids,
                                     hist_mask, shard_axis)  # [B, K]

        y_wv = fm_block(cfg, w, feat_vals, xv)
        deep_in = jnp.concatenate(
            [xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size),
             hist], axis=1)
        tower_fn = lambda p, x: common.apply_tower(
            p, state, x, train=train, dropout_keep=cfg.dropout_rates,
            use_bn=cfg.batch_norm, bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)
        if cfg.remat:
            y_d, new_state = jax.checkpoint(tower_fn)(params["tower"], deep_in)
        else:
            y_d, new_state = tower_fn(params["tower"], deep_in)

        logits = params["fm_b"][0] + y_wv + y_d
        return logits, new_state


class GraphBST(GraphDIN):
    """DeepFM + one transformer block over [history..., target].

    Behavior Sequence Transformer (Chen et al., 2019), minimal form: the
    history embeddings plus LEARNED position embeddings and the candidate
    (with its own learned position) form a [B, L+1, K] sequence; one
    single-head self-attention block (masked softmax over real positions +
    residual) runs over it, and the target slot's output is the history
    summary fed to the tower. Position table rows are sized by
    ``cfg.history_max_len`` (min 1), so serving and training must agree on
    the history length — MIGRATION documents the flag.
    """

    name = "bst"

    def _pos_rows(self) -> int:
        return max(1, int(getattr(self.cfg, "history_max_len", 0) or 0))

    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        params, bn_state = super().init(rng)
        cfg = self.cfg
        k = jax.random.fold_in(rng, 17)
        kk = jax.random.split(k, 5)
        kdim = cfg.embedding_size
        params["att"] = {
            "pos": 0.01 * jax.random.normal(kk[0], (self._pos_rows(), kdim)),
            "target_pos": 0.01 * jax.random.normal(kk[1], (kdim,)),
            "wq": common.glorot_uniform(kk[2], (kdim, kdim)),
            "wk": common.glorot_uniform(kk[3], (kdim, kdim)),
            "wv": common.glorot_uniform(kk[4], (kdim, kdim)),
        }
        return params, bn_state

    def _history_summary(self, params: common.Params, query: jnp.ndarray,
                         hist_ids: jnp.ndarray, hist_mask: jnp.ndarray,
                         shard_axis: Optional[str]) -> jnp.ndarray:
        att = params["att"]
        ln = hist_ids.shape[1]
        if ln > att["pos"].shape[0]:
            raise ValueError(
                f"history length {ln} exceeds the learned position table "
                f"({att['pos'].shape[0]} rows) — train and serve with the "
                "same --history_max_len")
        keys = self.emb.lookup(params["fm_v"], hist_ids,
                               axis_name=shard_axis)  # [B, L, K]
        seq = jnp.concatenate(
            [keys + att["pos"][None, :ln, :],
             (query + att["target_pos"])[:, None, :]], axis=1)  # [B, L+1, K]
        mask = jnp.concatenate(
            [(hist_mask > 0).astype(jnp.float32),
             jnp.ones((hist_ids.shape[0], 1), jnp.float32)], axis=1)
        q = seq @ att["wq"]
        k = seq @ att["wk"]
        v = seq @ att["wv"]
        scores = jnp.einsum("blk,bmk->blm", q, k) / jnp.sqrt(
            jnp.asarray(seq.shape[-1], jnp.float32))
        weights = fm_ops.masked_softmax(scores, mask[:, None, :])
        out = jnp.einsum("blm,bmk->blk", weights, v) + seq  # residual
        return out[:, -1, :]  # the target slot's contextualized output
