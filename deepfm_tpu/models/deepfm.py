"""DeepFM: bias + first-order + FM second-order + DNN tower.

TPU-native reimplementation of the reference ``model_fn`` graph
(``1-ps-cpu/DeepFM-dist-ps-for-multipleCPU-multiInstance.py:149-292``):

    y = FM_B + sum_f(W[ids]*vals) + FM(xv) + DNN(flatten(xv)),  pred = sigmoid(y)

with FM_W: [V], FM_V: [V, K] glorot-normal (reference ``:166-168``), the FM
identity from ``ops.fm``, and the tower from ``models.common``.

The implementation lives in ``models.graph`` — DeepFM is the graph
``(fm_w, fm_v) → [fm_block, tower] → ctr head`` (see graph.GraphDeepFM);
this class is a thin wrapper kept for the public name. Identical key
derivation and op order make it bit-identical to the pre-graph class
(pinned by tests/test_models.py's NumPy oracle and tests/test_multitask.py).
"""

from __future__ import annotations

from .graph import GraphDeepFM


class DeepFM(GraphDeepFM):
    name = "deepfm"
