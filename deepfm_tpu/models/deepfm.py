"""DeepFM: bias + first-order + FM second-order + DNN tower.

TPU-native reimplementation of the reference ``model_fn`` graph
(``1-ps-cpu/DeepFM-dist-ps-for-multipleCPU-multiInstance.py:149-292``):

    y = FM_B + sum_f(W[ids]*vals) + FM(xv) + DNN(flatten(xv)),  pred = sigmoid(y)

with FM_W: [V], FM_V: [V, K] glorot-normal (reference ``:166-168``), the FM
identity from ``ops.fm``, and the tower from ``models.common``. The embedding
tables may be row-sharded over the ``model`` mesh axis (``shard_axis``);
lookups then run as dense masked-gather + psum (``ops.embedding``), replacing
the reference's PS-hosted table (X1) with an ICI collective.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import Config
from ..ops import embedding as emb_ops
from ..ops import fm as fm_ops
from ..ops import pallas_fm
from . import common


class DeepFM:
    name = "deepfm"

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.padded_vocab = emb_ops.padded_vocab(cfg.feature_size, cfg.mesh_model)

    # -- parameters ----------------------------------------------------
    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        k_w, k_v, k_mlp = jax.random.split(rng, 3)
        fm_w = common.glorot_normal(k_w, (cfg.feature_size,))
        fm_v = common.glorot_normal(k_v, (cfg.feature_size, cfg.embedding_size))
        if self.padded_vocab != cfg.feature_size:
            pad = self.padded_vocab - cfg.feature_size
            fm_w = jnp.concatenate([fm_w, jnp.zeros((pad,), fm_w.dtype)])
            fm_v = jnp.concatenate(
                [fm_v, jnp.zeros((pad, cfg.embedding_size), fm_v.dtype)])
        tower, bn_state = common.init_tower(
            k_mlp, cfg.field_size * cfg.embedding_size, cfg.deep_layer_sizes,
            cfg.batch_norm)
        params = {"fm_b": jnp.zeros((1,), jnp.float32),
                  "fm_w": fm_w, "fm_v": fm_v, "tower": tower}
        return params, bn_state

    # -- forward -------------------------------------------------------
    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,   # int32 [B, F]
        feat_vals: jnp.ndarray,  # f32 [B, F]
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)

        # First-order: sum_f W[ids]*vals   (reference :177-179)
        w = emb_ops.lookup(params["fm_w"], feat_ids, axis_name=shard_axis,
                           strategy=cfg.embedding_lookup)  # [B,F]
        # Second-order FM over xv = V[ids]*vals   (reference :181-187)
        v = emb_ops.lookup(params["fm_v"], feat_ids, axis_name=shard_axis,
                           strategy=cfg.embedding_lookup)  # [B,F,K]
        xv = v * feat_vals[..., None]
        if cfg.use_pallas and pallas_fm.supported(cfg.field_size,
                                                 cfg.embedding_size):
            # Fused Pallas path: both FM reductions in one VMEM pass over the
            # same xv the tower consumes; d(xv)->d(v),d(vals) via JAX's
            # product rule outside the kernel.
            y_wv = pallas_fm.fused_fm(w, feat_vals, xv)
        else:
            y_wv = jnp.sum(w * feat_vals, axis=1) + fm_ops.fm_interaction(xv)

        # Deep tower over flattened xv   (reference :203-226)
        deep_in = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)
        tower_fn = lambda p, x: common.apply_tower(
            p, state, x, train=train, dropout_keep=cfg.dropout_rates,
            use_bn=cfg.batch_norm, bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)
        if cfg.remat:
            y_d, new_state = jax.checkpoint(tower_fn)(params["tower"], deep_in)
        else:
            y_d, new_state = tower_fn(params["tower"], deep_in)

        logits = params["fm_b"][0] + y_wv + y_d  # [B] (reference :229-231)
        return logits, new_state

    # -- regularization -------------------------------------------------
    def l2_loss(self, params: common.Params) -> jnp.ndarray:
        """l2_reg * (l2_loss(FM_W) + l2_loss(FM_V)) — reference :244-246."""
        return self.cfg.l2_reg * (
            common.l2_half_sum(params["fm_w"]) + common.l2_half_sum(params["fm_v"]))

    def embedding_param_names(self) -> Tuple[str, ...]:
        """Top-level param keys that are row-sharded over the model axis."""
        return ("fm_w", "fm_v")
