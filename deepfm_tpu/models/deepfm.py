"""DeepFM: bias + first-order + FM second-order + DNN tower.

TPU-native reimplementation of the reference ``model_fn`` graph
(``1-ps-cpu/DeepFM-dist-ps-for-multipleCPU-multiInstance.py:149-292``):

    y = FM_B + sum_f(W[ids]*vals) + FM(xv) + DNN(flatten(xv)),  pred = sigmoid(y)

with FM_W: [V], FM_V: [V, K] glorot-normal (reference ``:166-168``), the FM
identity from ``ops.fm``, and the tower from ``models.common``. The embedding
tables may be row-sharded over the ``model`` mesh axis (``shard_axis``);
lookups then run as dense masked-gather + psum (``ops.embedding``), replacing
the reference's PS-hosted table (X1) with an ICI collective.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import Config
from ..ops import fm as fm_ops
from ..ops import pallas_fm
from . import common


class DeepFM:
    name = "deepfm"

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.emb = common.EmbeddingSchema(cfg)
        self.padded_vocab = self.emb.padded_vocab

    # -- parameters ----------------------------------------------------
    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        k_w, k_v, k_mlp = jax.random.split(rng, 3)
        fm_w = self.emb.init_entry(k_w, ())
        fm_v = self.emb.init_entry(k_v, (cfg.embedding_size,))
        tower, bn_state = common.init_tower(
            k_mlp, cfg.field_size * cfg.embedding_size, cfg.deep_layer_sizes,
            cfg.batch_norm)
        params = {"fm_b": jnp.zeros((1,), jnp.float32),
                  "fm_w": fm_w, "fm_v": fm_v, "tower": tower}
        return params, bn_state

    # -- forward -------------------------------------------------------
    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,   # int32 [B, F]
        feat_vals: jnp.ndarray,  # f32 [B, F]
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)

        # First-order: sum_f W[ids]*vals   (reference :177-179)
        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F]
        # Second-order FM over xv = V[ids]*vals   (reference :181-187)
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F,K]
        xv = v * feat_vals[..., None]
        if cfg.use_pallas and pallas_fm.supported(cfg.field_size,
                                                 cfg.embedding_size):
            # Fused Pallas path: both FM reductions in one VMEM pass over the
            # same xv the tower consumes; d(xv)->d(v),d(vals) via JAX's
            # product rule outside the kernel.
            y_wv = pallas_fm.fused_fm(w, feat_vals, xv)
        else:
            y_wv = jnp.sum(w * feat_vals, axis=1) + fm_ops.fm_interaction(xv)

        # Deep tower over flattened xv   (reference :203-226)
        deep_in = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)
        tower_fn = lambda p, x: common.apply_tower(
            p, state, x, train=train, dropout_keep=cfg.dropout_rates,
            use_bn=cfg.batch_norm, bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)
        if cfg.remat:
            y_d, new_state = jax.checkpoint(tower_fn)(params["tower"], deep_in)
        else:
            y_d, new_state = tower_fn(params["tower"], deep_in)

        logits = params["fm_b"][0] + y_wv + y_d  # [B] (reference :229-231)
        return logits, new_state

    def _emb_lookup(self, params: common.Params, name: str,
                    feat_ids: jnp.ndarray, shard_axis: Optional[str],
                    emb_rows: Optional[Dict[str, Any]],
                    emb_plan: Optional[Dict[str, Any]]) -> jnp.ndarray:
        """Dense gather from the full table, or (sparse-update path) the
        batch's pre-gathered touched rows — ``emb_rows[name]`` is the
        gradient leaf there, so AD of this inverse-index gather lowers to
        the batch-sized segment-sum scatter instead of a full-table one."""
        if emb_rows is not None:
            return self.emb.lookup_rows(emb_rows[name], emb_plan)
        return self.emb.lookup(params[name], feat_ids, axis_name=shard_axis)

    # -- regularization -------------------------------------------------
    def l2_loss(self, params: common.Params, *,
                shard_axis: Optional[str] = None,
                emb_rows: Optional[Dict[str, Any]] = None,
                emb_plan: Optional[Dict[str, Any]] = None) -> jnp.ndarray:
        """l2_reg * (l2_loss(FM_W) + l2_loss(FM_V)) — reference :244-246.
        Pad rows are structurally excluded; the sparse path penalizes only
        the batch's touched rows (TUNING §2.11)."""
        if emb_rows is not None:
            return self.cfg.l2_reg * (
                self.emb.l2_rows(emb_rows["fm_w"], emb_plan)
                + self.emb.l2_rows(emb_rows["fm_v"], emb_plan))
        return self.cfg.l2_reg * (
            self.emb.l2(params["fm_w"], axis_name=shard_axis)
            + self.emb.l2(params["fm_v"], axis_name=shard_axis))

    def embedding_param_names(self) -> Tuple[str, ...]:
        """Top-level param keys that are row-sharded over the model axis."""
        return ("fm_w", "fm_v")
