"""Twin-tower (dual-encoder) retrieval model: in-batch-softmax training.

The retrieval stage of the cascade (README "Retrieval→ranking cascade").
NOT a ``--model`` zoo member — the zoo ranks one candidate per example;
this model embeds USERS (their click history) and ITEMS (candidate ids)
into one space so a :class:`~deepfm_tpu.rec.index.CandidateIndex` over all
item vectors can answer "top-N items for this user" without scoring the
whole corpus through the ranker.

Training follows the sampled-softmax dual-encoder recipe (Covington et
al., RecSys'16; Yi et al., RecSys'19): each batch's (user, clicked-item)
pairs score against each other, every OTHER row's item serving as an
in-batch negative — logits ``U @ I.T / temperature``, labels the diagonal.
Rows without a click or without history carry zero weight (an empty
history embeds every user identically — nothing to learn there).

Item ids share the :class:`~deepfm_tpu.models.common.EmbeddingSchema`
id space with the ranker (same hash bucketing), so an item id means the
same row in both stages of the cascade.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from . import common

Params = Dict[str, object]


def _mlp_init(key: jax.Array, dims: List[int]) -> List[Dict[str, jnp.ndarray]]:
    layers = []
    for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
        layers.append({
            "w": common.glorot_uniform(jax.random.fold_in(key, i), (a, b)),
            "b": jnp.zeros((b,), jnp.float32),
        })
    return layers


def _mlp_apply(layers, x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1:
            x = jax.nn.relu(x)
    return x


def _l2_normalize(x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    # sqrt(sum + eps), NOT max(norm, eps): norm's gradient at x == 0 is
    # NaN (0/0), and even a zero-weighted row's NaN poisons the whole
    # in-batch logit matrix. The smoothed form is finite everywhere.
    return x * jax.lax.rsqrt(jnp.sum(x * x, axis=-1, keepdims=True) + eps)


class TwinTower:
    """User tower over the history, item tower over candidate ids.

    Both towers project into a shared ``embedding_size``-dim unit sphere;
    retrieval scores are dot products (= cosine), so the candidate index
    needs nothing but the item matrix.
    """

    #: in-batch softmax temperature (fixed; unit-norm embeddings)
    TEMPERATURE = 0.1

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.emb = common.EmbeddingSchema(cfg)
        self.dim = cfg.embedding_size
        self.padded_vocab = self.emb.padded_vocab

    def init(self, rng: jax.Array) -> Params:
        k_e, k_u, k_i = jax.random.split(rng, 3)
        k = self.dim
        return {
            "emb": self.emb.init_entry(k_e, (k,)),
            "user": _mlp_init(k_u, [k, 2 * k, k]),
            "item": _mlp_init(k_i, [k, 2 * k, k]),
        }

    # ------------------------------------------------------------ encoders
    def user_embed(self, params: Params, hist_ids: jnp.ndarray,
                   hist_mask: jnp.ndarray) -> jnp.ndarray:
        """[B, L] history -> [B, D] unit vectors. Mask-weighted mean pool;
        an empty history pools to zeros and normalizes to zeros/eps —
        finite, and weighted out of the loss."""
        emb = self.emb.lookup(params["emb"], hist_ids)  # [B, L, K]
        m = (hist_mask > 0).astype(jnp.float32)[..., None]
        pooled = jnp.sum(emb * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0)
        return _l2_normalize(_mlp_apply(params["user"], pooled))

    def item_embed(self, params: Params, item_ids: jnp.ndarray) -> jnp.ndarray:
        """[B] item ids -> [B, D] unit vectors."""
        emb = self.emb.lookup(params["emb"], item_ids)  # [B, K]
        return _l2_normalize(_mlp_apply(params["item"], emb))

    def all_item_embeddings(self, params: Params,
                            num_items: int,
                            batch: int = 4096) -> np.ndarray:
        """[num_items, D] matrix for the candidate index, computed in
        batches so a big vocab never materializes one giant activation."""
        fn = jax.jit(lambda p, ids: self.item_embed(p, ids))
        out = np.empty((num_items, self.dim), np.float32)
        for lo in range(0, num_items, batch):
            hi = min(lo + batch, num_items)
            out[lo:hi] = np.asarray(
                fn(params, jnp.arange(lo, hi, dtype=jnp.int32)))
        return out

    # ---------------------------------------------------------------- loss
    def loss(self, params: Params, hist_ids: jnp.ndarray,
             hist_mask: jnp.ndarray, item_ids: jnp.ndarray,
             weights: jnp.ndarray) -> jnp.ndarray:
        """Weighted in-batch softmax: row b's positive is item b, the other
        B-1 items are its negatives. ``weights`` zeroes non-click /
        empty-history rows (their columns still serve as negatives)."""
        u = self.user_embed(params, hist_ids, hist_mask)    # [B, D]
        v = self.item_embed(params, item_ids)               # [B, D]
        logits = (u @ v.T) / self.TEMPERATURE               # [B, B]
        logp = jax.nn.log_softmax(logits, axis=1)
        nll = -jnp.diagonal(logp)                           # [B]
        denom = jnp.maximum(jnp.sum(weights), 1.0)
        return jnp.sum(nll * weights) / denom


def train_twin_tower(
    cfg: Config,
    batches: Iterable[Dict[str, np.ndarray]],
    *,
    item_slot: int = 0,
    learning_rate: Optional[float] = None,
    seed: Optional[int] = None,
) -> Tuple[TwinTower, Params, Dict[str, float]]:
    """Fit a :class:`TwinTower` over history batches; returns
    ``(model, params, stats)``.

    ``batches`` is any iterable of pipeline batches carrying ``hist_ids`` /
    ``hist_mask`` (``CtrPipeline(history=True)`` output). The positive item
    of each example is its id in field ``item_slot`` — the cascade's
    convention for "which field is the candidate item". Rows with no click
    or no history get zero loss weight.
    """
    import optax  # noqa: PLC0415 (jax-heavy, keep module import light)

    model = TwinTower(cfg)
    params = model.init(jax.random.PRNGKey(
        cfg.seed if seed is None else seed))
    tx = optax.adam(cfg.learning_rate if learning_rate is None
                    else learning_rate)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, hist_ids, hist_mask, item_ids, weights):
        loss, grads = jax.value_and_grad(model.loss)(
            params, hist_ids, hist_mask, item_ids, weights)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    steps, last_loss, used_rows = 0, float("nan"), 0
    for batch in batches:
        if "hist_ids" not in batch:
            raise ValueError(
                "train_twin_tower needs history batches "
                "(CtrPipeline(history=True)); got keys "
                f"{sorted(batch)}")
        hist_ids = jnp.asarray(batch["hist_ids"])
        hist_mask = jnp.asarray(batch["hist_mask"])
        item_ids = jnp.asarray(batch["feat_ids"][:, item_slot])
        w = (batch["label"].reshape(-1) > 0) \
            & (np.asarray(batch["hist_mask"]).sum(axis=1) > 0)
        weights = jnp.asarray(w.astype(np.float32))
        params, opt_state, loss = step(
            params, opt_state, hist_ids, hist_mask, item_ids, weights)
        steps += 1
        used_rows += int(w.sum())
        last_loss = float(loss)
    return model, params, {"steps": float(steps), "loss": last_loss,
                           "positive_rows": float(used_rows)}
