"""Wide&Deep: linear wide part + DNN tower (no FM interaction term).

BASELINE.json config 4: "swap FM tower for linear wide part, same TFRecord
input". Same input contract and embedding tables as DeepFM; the model drops
the second-order FM term, keeping y = b + wide(ids, vals) + DNN(xv).

The implementation lives in ``models.graph`` (first_order block + tower);
this class is a thin, bit-identical wrapper kept for the public name.
"""

from __future__ import annotations

from .graph import GraphWideDeep


class WideDeep(GraphWideDeep):
    name = "widedeep"
