"""Wide&Deep: linear wide part + DNN tower (no FM interaction term).

BASELINE.json config 4: "swap FM tower for linear wide part, same TFRecord
input". Same input contract and embedding tables as DeepFM; the model drops
the second-order FM term, keeping y = b + wide(ids, vals) + DNN(xv).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import Config
from . import common
from .deepfm import DeepFM


class WideDeep(DeepFM):
    name = "widedeep"

    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,
        feat_vals: jnp.ndarray,
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        cfg = self.cfg
        feat_vals = feat_vals.astype(jnp.float32)

        # Wide: linear over sparse features (first-order part of DeepFM).
        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        y_wide = jnp.sum(w * feat_vals, axis=1)

        # Deep: tower over embedded features.
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)
        xv = v * feat_vals[..., None]
        deep_in = xv.reshape(xv.shape[0], cfg.field_size * cfg.embedding_size)
        y_d, new_state = common.apply_tower(
            params["tower"], state, deep_in, train=train,
            dropout_keep=cfg.dropout_rates, use_bn=cfg.batch_norm,
            bn_decay=cfg.batch_norm_decay, rng=rng,
            compute_dtype=jnp.dtype(cfg.compute_dtype), data_axis=data_axis)

        logits = params["fm_b"][0] + y_wide + y_d
        return logits, new_state
