"""Multi-task ranking heads over the feature→tower graph.

One embedding stage (the same ``fm_w``/``fm_v`` entries every single-task
graph uses), a shared bottom chosen by ``--multitask``, and one named head
per ``--tasks`` entry, each producing a logit — ``apply`` returns ``[B, T]``
instead of the single-task ``[B]``:

  * ``shared_bottom`` — one shared DNN hidden stack; per-task linear heads.
  * ``mmoe`` — Multi-gate Mixture-of-Experts (Ma et al., KDD 2018):
    ``--mmoe_experts`` independent hidden stacks, a per-task softmax gate
    over the expert outputs, per-task heads on the mixtures.
  * ``esmm`` — Entire-Space Multi-task Model (Ma et al., SIGIR 2018) for
    CTR+CVR: per-task towers; the CVR head trains through the observable
    pCTCVR = pCTR · pCVR on the full exposure space (no sample-selection
    bias), so the loss couples the tasks while serving stays per-task.

Per-task losses combine under ``--task_weights`` (default: all 1.0).
Labels arrive as columns of the batch dict: task 0 reads ``label``, task 1
the optional ``label2`` column (see data/example_codec.py).

Sparse embedding updates, row-sharding, and the serving export all work
unchanged: the embedding stage is inherited from :class:`graph.GraphModel`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..config import Config
from . import common
from . import graph


class MultiTaskModel(graph.GraphModel):
    """Named task heads over a shared embedding + interaction bottom."""

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.task_names = tuple(cfg.task_names)
        self.arch = cfg.multitask
        self.name = f"multitask_{self.arch}"

    # -- parameters ----------------------------------------------------
    def init(self, rng: jax.Array) -> Tuple[common.Params, common.State]:
        cfg = self.cfg
        t_count = self.num_tasks
        k_w, k_v, k_body = jax.random.split(rng, 3)
        fm_w = self.emb.init_entry(k_w, ())
        fm_v = self.emb.init_entry(k_v, (cfg.embedding_size,))
        d = cfg.field_size * cfg.embedding_size
        hdim = cfg.deep_layer_sizes[-1] if cfg.deep_layer_sizes else d
        params: common.Params = {
            "fm_b": jnp.zeros((t_count,), jnp.float32),
            "fm_w": fm_w, "fm_v": fm_v,
        }
        if self.arch == "esmm":
            towers: List[common.Params] = []
            states: List[common.State] = []
            for t in range(t_count):
                tp, ts = common.init_tower(
                    jax.random.fold_in(k_body, t), d, cfg.deep_layer_sizes,
                    cfg.batch_norm)
                towers.append(tp)
                states.append(ts)
            params["towers"] = towers
            return params, {"towers": states}
        if self.arch == "mmoe":
            ekeys = jax.random.split(
                jax.random.fold_in(k_body, 1), cfg.mmoe_experts)
            experts, estates = [], []
            for i in range(cfg.mmoe_experts):
                ep, es = common.init_hidden_stack(
                    ekeys[i], d, cfg.deep_layer_sizes, cfg.batch_norm)
                experts.append(ep)
                estates.append(es)
            params["experts"] = experts
            k_gate = jax.random.fold_in(k_body, 2)
            params["gates"] = [
                {"w": common.glorot_uniform(
                    jax.random.fold_in(k_gate, t), (d, cfg.mmoe_experts))}
                for t in range(t_count)]
            k_head = jax.random.fold_in(k_body, 3)
            params["heads"] = [self._init_head(
                jax.random.fold_in(k_head, t), hdim) for t in range(t_count)]
            return params, {"experts": estates}
        # shared_bottom
        bp, bs = common.init_hidden_stack(
            jax.random.fold_in(k_body, 1), d, cfg.deep_layer_sizes,
            cfg.batch_norm)
        params["bottom"] = bp
        k_head = jax.random.fold_in(k_body, 3)
        params["heads"] = [self._init_head(
            jax.random.fold_in(k_head, t), hdim) for t in range(t_count)]
        return params, {"bottom": bs}

    @staticmethod
    def _init_head(key: jax.Array, hdim: int) -> common.Params:
        return {"w": common.glorot_uniform(key, (hdim, 1)),
                "b": jnp.zeros((1,), jnp.float32)}

    @staticmethod
    def _apply_head(head: common.Params, h: jnp.ndarray) -> jnp.ndarray:
        out = h @ head["w"].astype(h.dtype) + head["b"].astype(h.dtype)
        return out.astype(jnp.float32)[:, 0]

    # -- forward -------------------------------------------------------
    def apply(
        self,
        params: common.Params,
        state: common.State,
        feat_ids: jnp.ndarray,   # int32 [B, F]
        feat_vals: jnp.ndarray,  # f32 [B, F]
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        shard_axis: Optional[str] = None,
        data_axis: Optional[str] = None,
        emb_rows: Optional[Dict[str, Any]] = None,
        emb_plan: Optional[Dict[str, Any]] = None,
    ) -> Tuple[jnp.ndarray, common.State]:
        """Returns per-task logits [B, T] + new model state."""
        cfg = self.cfg
        cdt = jnp.dtype(cfg.compute_dtype)
        feat_vals = feat_vals.astype(jnp.float32)

        # Shared embedding stage: linear term + embedded features.
        w = self._emb_lookup(params, "fm_w", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F]
        v = self._emb_lookup(params, "fm_v", feat_ids, shard_axis,
                             emb_rows, emb_plan)  # [B,F,K]
        xv = v * feat_vals[..., None]
        y_first = graph.first_order(w, feat_vals)  # shared wide term [B]
        deep_in = xv.reshape(xv.shape[0],
                             cfg.field_size * cfg.embedding_size)
        stack_kw = dict(
            train=train, dropout_keep=cfg.dropout_rates,
            use_bn=cfg.batch_norm, bn_decay=cfg.batch_norm_decay,
            compute_dtype=cdt, data_axis=data_axis)

        if self.arch == "esmm":
            outs, states = [], []
            for t in range(self.num_tasks):
                r = None if rng is None else jax.random.fold_in(rng, t)
                y, ns = common.apply_tower(
                    params["towers"][t], state["towers"][t], deep_in,
                    rng=r, **stack_kw)
                outs.append(params["fm_b"][t] + y_first + y)
                states.append(ns)
            return jnp.stack(outs, axis=1), {"towers": states}

        if self.arch == "mmoe":
            eouts, estates = [], []
            for i, ep in enumerate(params["experts"]):
                r = None if rng is None else jax.random.fold_in(rng, i)
                h, ns = common.apply_hidden_stack(
                    ep, state["experts"][i], deep_in, rng=r, **stack_kw)
                eouts.append(h)
                estates.append(ns)
            eo = jnp.stack(eouts, axis=1)  # [B, N, H]
            x0c = deep_in.astype(cdt)
            outs = []
            for t in range(self.num_tasks):
                gate = jax.nn.softmax(
                    x0c @ params["gates"][t]["w"].astype(cdt), axis=-1)
                mix = jnp.sum(eo * gate[..., None].astype(eo.dtype), axis=1)
                outs.append(params["fm_b"][t] + y_first
                            + self._apply_head(params["heads"][t], mix))
            return jnp.stack(outs, axis=1), {"experts": estates}

        # shared_bottom
        h, ns = common.apply_hidden_stack(
            params["bottom"], state["bottom"], deep_in, rng=rng, **stack_kw)
        outs = [params["fm_b"][t] + y_first
                + self._apply_head(params["heads"][t], h)
                for t in range(self.num_tasks)]
        return jnp.stack(outs, axis=1), {"bottom": ns}

    # -- task combination ----------------------------------------------
    def per_example_loss(self, logits: jnp.ndarray,
                         labels: jnp.ndarray) -> jnp.ndarray:
        """Weighted per-example combined loss: [B,T] logits+labels -> [B].

        ESMM replaces the independent per-task losses with its entire-space
        pair: BCE(pCTR, y_ctr) + BCE(pCTR·pCVR, y_ctr·y_cvr). The task
        weights still apply per term.
        """
        cfg = self.cfg
        wts = jnp.asarray(cfg.task_weight_values, jnp.float32)
        labels = labels.astype(jnp.float32)
        if self.arch == "esmm":
            y_ctr = labels[:, 0]
            y_cvr = labels[:, 1]
            l_ctr = optax.sigmoid_binary_cross_entropy(logits[:, 0], y_ctr)
            eps = jnp.float32(1e-7)
            p_ctcvr = jnp.clip(
                jax.nn.sigmoid(logits[:, 0]) * jax.nn.sigmoid(logits[:, 1]),
                eps, 1.0 - eps)
            y_ctcvr = y_ctr * y_cvr
            l_ctcvr = -(y_ctcvr * jnp.log(p_ctcvr)
                        + (1.0 - y_ctcvr) * jnp.log1p(-p_ctcvr))
            return wts[0] * l_ctr + wts[1] * l_ctcvr
        if cfg.loss_type == "log_loss":
            per_task = optax.sigmoid_binary_cross_entropy(logits, labels)
        else:  # square_loss
            per_task = jnp.square(jax.nn.sigmoid(logits) - labels)
        return per_task @ wts

    def probs_from_logits(self, logits: jnp.ndarray) -> jnp.ndarray:
        """Named per-task probabilities [B,T] (column t = task_names[t]).
        For ESMM the CVR column is the *conditional* CVR — multiply the
        columns to recover pCTCVR downstream if needed."""
        return jax.nn.sigmoid(logits)
