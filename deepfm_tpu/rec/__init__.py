"""Retrieval→ranking cascade components (README "Retrieval→ranking
cascade"): the candidate index over twin-tower item embeddings
(:mod:`~deepfm_tpu.rec.index`) and the two-stage serving engine that
composes retrieve→rank over the publish/hot-swap machinery
(:mod:`~deepfm_tpu.rec.cascade`)."""

from .index import CandidateIndex  # noqa: F401
