"""Candidate index over twin-tower item embeddings: brute-force + ANN.

``CandidateIndex`` answers "top-k items for this user vector" for the
retrieval stage of the cascade. Two structures behind one interface
(``--index_kind``):

  * ``brute`` — the exact baseline: one jitted ``top_k(q @ V.T)`` over the
    whole item matrix. At CTR vocab scale a [V, D] f32 matmul per query
    batch is a single MXU-friendly GEMM, so brute force is not a strawman —
    it is the correct default until the corpus outgrows a device.
  * ``ann`` — quantized partition scan (IVF-flat shape): spherical k-means
    partitions the items; a query probes the ``nprobe`` nearest partitions
    and scans only their members, dequantizing int8 rows (per-row scale) on
    the fly. Approximate — so its recall@k is MEASURED against brute force
    on sample queries and stamped into the saved artifact; a deployment
    reads the stamp instead of trusting the structure.

``save``/``load`` round-trip the index as ``index.npz`` + ``index_meta.json``
inside a servable artifact dir (see :mod:`~deepfm_tpu.rec.cascade`).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INDEX_FILE = "index.npz"
INDEX_META_FILE = "index_meta.json"


def _spherical_kmeans(vectors: np.ndarray, num_partitions: int, *,
                      iters: int = 8, seed: int = 0
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(centroids [P, D] unit-norm, assignment [V]) by cosine k-means.
    Deterministic (seeded init); empty clusters re-seed from the farthest
    points so every partition stays non-empty."""
    v = vectors.shape[0]
    rng = np.random.default_rng(seed)
    centroids = vectors[rng.choice(v, size=num_partitions, replace=False)]
    centroids = centroids / np.maximum(
        np.linalg.norm(centroids, axis=1, keepdims=True), 1e-8)
    assign = np.zeros((v,), np.int64)
    for _ in range(iters):
        sims = vectors @ centroids.T                     # [V, P]
        assign = np.argmax(sims, axis=1)
        for p in range(num_partitions):
            members = vectors[assign == p]
            if members.shape[0] == 0:
                # re-seed from the point worst-served by its centroid
                worst = int(np.argmin(sims[np.arange(v), assign]))
                centroids[p] = vectors[worst]
                assign[worst] = p
            else:
                centroids[p] = members.mean(axis=0)
            centroids[p] /= max(float(np.linalg.norm(centroids[p])), 1e-8)
    return centroids.astype(np.float32), assign


class CandidateIndex:
    """Top-k retrieval over an item-embedding matrix.

    ``vectors`` [V, D] float32 (unit-norm from the item tower); ``ids`` [V]
    maps matrix rows to item ids (default ``arange(V)``).
    """

    def __init__(self, vectors: np.ndarray, *,
                 ids: Optional[np.ndarray] = None,
                 kind: str = "brute",
                 num_partitions: int = 0,
                 nprobe: int = 0,
                 seed: int = 0):
        vectors = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if vectors.ndim != 2 or vectors.shape[0] < 1:
            raise ValueError(f"vectors must be [V, D], got {vectors.shape}")
        if kind not in ("brute", "ann"):
            raise ValueError(f"kind must be brute|ann, got {kind!r}")
        self.vectors = vectors
        self.num_items, self.dim = vectors.shape
        self.ids = (np.arange(self.num_items, dtype=np.int64)
                    if ids is None else np.asarray(ids, np.int64))
        if self.ids.shape != (self.num_items,):
            raise ValueError(
                f"ids must be [V]={self.num_items}, got {self.ids.shape}")
        self.kind = kind
        self._topk_cache: Dict[int, object] = {}
        if kind == "ann":
            self.num_partitions = int(num_partitions) or max(
                1, int(np.sqrt(self.num_items)))
            self.num_partitions = min(self.num_partitions, self.num_items)
            self.nprobe = int(nprobe) or max(1, self.num_partitions // 4)
            self.nprobe = min(self.nprobe, self.num_partitions)
            self.centroids, self._assign = _spherical_kmeans(
                vectors, self.num_partitions, seed=seed)
            # Partition member lists + int8 rows with per-row dequant scale.
            order = np.argsort(self._assign, kind="stable")
            self._members = order.astype(np.int64)       # rows by partition
            counts = np.bincount(self._assign, minlength=self.num_partitions)
            self._part_offsets = np.concatenate(
                [[0], np.cumsum(counts)]).astype(np.int64)
            self._scales = np.maximum(
                np.abs(vectors).max(axis=1), 1e-8).astype(np.float32) / 127.0
            self._q = np.clip(
                np.round(vectors / self._scales[:, None]),
                -127, 127).astype(np.int8)
        else:
            self.num_partitions = 0
            self.nprobe = 0

    # -------------------------------------------------------------- search
    def _brute_topk(self, queries: np.ndarray, k: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
        fn = self._topk_cache.get(k)
        if fn is None:
            mat = jnp.asarray(self.vectors)

            def topk(q):
                return jax.lax.top_k(q @ mat.T, k)
            fn = jax.jit(topk)
            self._topk_cache[k] = fn
        scores, rows = fn(jnp.asarray(queries, jnp.float32))
        return np.asarray(scores), np.asarray(rows)

    def _ann_topk(self, queries: np.ndarray, k: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
        b = queries.shape[0]
        order = np.argsort(-(queries @ self.centroids.T), axis=1)  # [B, P]
        scores = np.full((b, k), -np.inf, np.float32)
        rows = np.zeros((b, k), np.int64)
        # Probe at least nprobe partitions AND until ~4k candidates have
        # accumulated: a fixed nprobe can hold fewer members than k when k
        # approaches the corpus size, which caps recall structurally.
        target = max(4 * k, 1)
        for i in range(b):
            segs, count, probes = [], 0, 0
            for p in order[i]:
                seg = self._members[
                    self._part_offsets[p]:self._part_offsets[p + 1]]
                segs.append(seg)
                count += seg.shape[0]
                probes += 1
                if probes >= self.nprobe and count >= target:
                    break
            cand = np.concatenate(segs)
            # quantized scan: dequantize only the probed rows
            deq = self._q[cand].astype(np.float32) * \
                self._scales[cand, None]
            s = deq @ queries[i]
            take = min(k, cand.shape[0])
            top = np.argpartition(-s, take - 1)[:take]
            top = top[np.argsort(-s[top], kind="stable")]
            scores[i, :take] = s[top]
            rows[i, :take] = cand[top]
        return scores, rows

    def search(self, queries: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(item_ids [B, k] int64, scores [B, k] f32), best first. ``k`` is
        clamped to the corpus size."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        if queries.shape[1] != self.dim:
            raise ValueError(
                f"query dim {queries.shape[1]} != index dim {self.dim}")
        k = min(int(k), self.num_items)
        if k < 1:
            raise ValueError("k must be >= 1")
        if self.kind == "brute":
            scores, rows = self._brute_topk(queries, k)
        else:
            scores, rows = self._ann_topk(queries, k)
        return self.ids[rows], scores

    def recall_at_k(self, queries: np.ndarray, k: int) -> float:
        """Fraction of brute-force top-k recovered by this index's search
        (averaged over queries). ``brute`` measures 1.0 by construction —
        measured anyway, never hardcoded."""
        got_ids, _ = self.search(queries, k)
        _, true_rows = self._brute_topk(
            np.atleast_2d(np.asarray(queries, np.float32)),
            min(int(k), self.num_items))
        true_ids = self.ids[true_rows]
        hits = sum(
            len(set(map(int, got_ids[i])) & set(map(int, true_ids[i])))
            for i in range(true_ids.shape[0]))
        return hits / float(true_ids.size)

    # ------------------------------------------------------------ artifact
    def save(self, out_dir: str, *,
             extra_meta: Optional[Dict] = None) -> Dict:
        """Write ``index.npz`` + ``index_meta.json`` under ``out_dir``;
        returns the meta dict (recall stamp included via ``extra_meta``)."""
        os.makedirs(out_dir, exist_ok=True)
        np.savez_compressed(
            os.path.join(out_dir, INDEX_FILE),
            vectors=self.vectors, ids=self.ids)
        meta = {
            "kind": self.kind,
            "num_items": int(self.num_items),
            "dim": int(self.dim),
            "num_partitions": int(self.num_partitions),
            "nprobe": int(self.nprobe),
        }
        meta.update(extra_meta or {})
        tmp = os.path.join(out_dir, INDEX_META_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=2)
        os.replace(tmp, os.path.join(out_dir, INDEX_META_FILE))
        return meta

    @classmethod
    def load(cls, in_dir: str) -> Tuple["CandidateIndex", Dict]:
        """(index, meta) from a dir written by :meth:`save`. The structure
        is rebuilt deterministically from the stored exact vectors."""
        with open(os.path.join(in_dir, INDEX_META_FILE)) as f:
            meta = json.load(f)
        data = np.load(os.path.join(in_dir, INDEX_FILE))
        idx = cls(data["vectors"], ids=data["ids"], kind=meta["kind"],
                  num_partitions=meta.get("num_partitions", 0),
                  nprobe=meta.get("nprobe", 0))
        return idx, meta
