"""Retrieve→rank cascade serving: one artifact, two stages, one hot swap.

Closes the tentpole loop (README "Retrieval→ranking cascade"): a published
artifact dir carries THREE servables —

  * the ranker (``export_serving``'s StableHLO + params, history-aware via
    the packed-column signature),
  * the twin towers (``towers.npz`` + ``towers_config.json``),
  * the candidate index (``index.npz`` + ``index_meta.json``, recall@k
    stamped).

``export_cascade`` writes the retrieval files FIRST and lets
``export_serving`` finish the dir, so the existing ``ARTIFACT_COMPLETE``
marker certifies all three stages at once. :class:`CascadeEngine` serves
them end-to-end: user history → user tower → index top-N → packed ranking
batch through a :class:`~deepfm_tpu.serve.engine.ServingEngine` → top-k.
Hot swap is ATOMIC across stages: one ``LatestWatcher`` loads ranker +
towers + index off to the side as a single :class:`CascadeModel` and swaps
the composite with one assignment — no request ever ranks new candidates
with an old ranker or vice versa.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..config import Config
from ..data import fileio
from ..models.twin_tower import TwinTower
from ..serve.admission import (DEGRADE_RUNGS, VALUE_DEFAULT,
                               AdmissionController, DegradationLadder)
from ..serve.engine import ServingEngine
from ..serve.stats import ServingStats
from ..utils import export as export_lib
from .index import CandidateIndex

TOWERS_FILE = "towers.npz"
TOWERS_CONFIG_FILE = "towers_config.json"

#: which feature field holds the candidate item id (the cascade convention
#: shared with ``train_twin_tower``'s positive extraction)
ITEM_SLOT = 0


def _flatten_params(params) -> Tuple[list, object]:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [np.asarray(x) for x in leaves], treedef


def save_towers(tower_params, cfg: Config, out_dir: str) -> None:
    """``towers.npz`` (leaves in tree-flatten order) + the config needed to
    rebuild the same tree structure at load time."""
    leaves, _ = _flatten_params(tower_params)
    fileio.makedirs(out_dir)
    np.savez_compressed(os.path.join(out_dir, TOWERS_FILE),
                        **{f"p{i}": leaf for i, leaf in enumerate(leaves)})
    with open(os.path.join(out_dir, TOWERS_CONFIG_FILE), "w") as f:
        json.dump({"config": cfg.to_dict()}, f, indent=2)


def load_towers(in_dir: str) -> Tuple[TwinTower, Dict]:
    """(model, params) from :func:`save_towers` output. The param tree is
    rebuilt from the stored config (same treedef as ``init``), so leaf
    order — not leaf names — is the contract."""
    with open(os.path.join(in_dir, TOWERS_CONFIG_FILE)) as f:
        cfg = Config.from_dict(json.load(f)["config"])
    model = TwinTower(cfg)
    template = model.init(jax.random.PRNGKey(0))
    _, treedef = jax.tree_util.tree_flatten(template)
    data = np.load(os.path.join(in_dir, TOWERS_FILE))
    leaves = [data[f"p{i}"] for i in range(len(data.files))]
    return model, jax.tree_util.tree_unflatten(treedef, leaves)


def export_cascade(ranker_model, ranker_state, cfg: Config, out_dir: str, *,
                   tower_params, index: CandidateIndex,
                   index_meta: Optional[Dict] = None) -> str:
    """Write a complete cascade artifact: towers + index, THEN the ranker
    export (which writes ``ARTIFACT_COMPLETE`` last — the marker certifies
    every stage). ``index_meta`` carries measured stamps (recall@k)."""
    fileio.makedirs(out_dir)
    save_towers(tower_params, cfg, out_dir)
    index.save(out_dir, extra_meta=index_meta)
    return export_lib.export_serving(ranker_model, ranker_state, cfg, out_dir)


def cascade_extra_export(cfg: Config, tower_params, index: CandidateIndex, *,
                         index_meta: Optional[Dict] = None
                         ) -> Callable[[str], None]:
    """``Publisher(extra_export=...)`` hook: stamps the frozen retrieval
    stage into every published ranker version (online training republishes
    the ranker continuously; retraining towers/index is a batch job)."""
    def hook(staging_dir: str) -> None:
        save_towers(tower_params, cfg, staging_dir)
        index.save(staging_dir, extra_meta=index_meta)
    return hook


class CascadeModel:
    """ONE loaded artifact version: ranker + towers + index, swap-atomic.

    Callable with the engine's ``(feat_ids, feat_vals)`` signature (ranking
    only — packed columns), and carries the retrieval stage alongside so a
    single reference assignment swaps both."""

    def __init__(self, path: str, *, buckets: Sequence[int]):
        self.path = path
        self.rank_fn = export_lib.load_serving(path, buckets=buckets)
        with fileio.open_stream(
                fileio.join(path, "model_config.json"), "r") as f:
            meta = json.load(f)
        self.field_size = int(meta["config"]["field_size"])
        self.hist_len = int(meta.get("history_len", 0))
        self.tower_model, self.tower_params = load_towers(path)
        self.index, self.index_meta = CandidateIndex.load(path)
        self._user_fn = jax.jit(self.tower_model.user_embed)

    # engine-facing predict: delegate, keep prewarm metadata visible
    def __call__(self, feat_ids, feat_vals):
        return self.rank_fn(feat_ids, feat_vals)

    @property
    def buckets(self):
        return getattr(self.rank_fn, "buckets", None)

    @property
    def input_cols(self):
        return getattr(self.rank_fn, "input_cols", None)

    def user_embed(self, hist_ids: np.ndarray,
                   hist_mask: np.ndarray) -> np.ndarray:
        return np.asarray(self._user_fn(
            self.tower_params, hist_ids.astype(np.int32),
            hist_mask.astype(np.float32)))


class CascadeEngine:
    """Two-stage serving over the publish/hot-swap machinery.

    ``recommend(hist_ids, hist_mask, feat_ids, feat_vals, k)``:

      1. user tower embeds the history;
      2. the candidate index retrieves ``retrieve_k`` item ids;
      3. each candidate is substituted into the request's item slot
         (field ``ITEM_SLOT``), history packed alongside, and the batch
         ranked through the inner :class:`ServingEngine` (dynamic batching
         + bucketed shapes + backpressure all apply);
      4. the top ``k`` candidates by ranker probability come back.

    An empty history is legal end-to-end: the user tower pools zeros (the
    index then returns ITS notion of head items) and the ranker's attention
    contributes exact zeros — finite probabilities, never NaN (the
    masked-softmax regression the drill pins).

    **Overload plane.** ``slo_ms``/``shed_watermark`` build an
    :class:`~deepfm_tpu.serve.admission.AdmissionController` for the inner
    ranking engine (low-value requests get a typed ``AdmissionShed``).
    ``degrade_retrieve_k`` > 0 additionally arms the graceful-degradation
    ladder: under pressure ``recommend`` first shrinks the candidate set to
    ``degrade_retrieve_k`` (rung ``reduced_retrieve``), then skips the
    ranker entirely and answers in retrieval order (rung
    ``retrieval_only`` — scores are the index's inner-product scores, NOT
    calibrated probabilities). Every rung change is a counted, span-traced
    transition; per-request degradation is counted per rung.
    """

    def __init__(self, publish_dir: str, *, retrieve_k: int = 50,
                 poll_secs: float = 2.0, max_batch: int = 256,
                 max_delay_ms: float = 5.0,
                 buckets: Optional[Sequence[int]] = None,
                 queue_rows: int = 0,
                 slo_ms: float = 0.0, shed_watermark: int = 0,
                 degrade_retrieve_k: int = 0,
                 watcher_kw: Optional[dict] = None,
                 engine_kw: Optional[dict] = None):
        if retrieve_k < 1:
            raise ValueError("retrieve_k must be >= 1")
        if degrade_retrieve_k < 0 or degrade_retrieve_k > retrieve_k:
            raise ValueError(
                f"degrade_retrieve_k must be in 0..retrieve_k="
                f"{retrieve_k}, got {degrade_retrieve_k}")
        self.retrieve_k = int(retrieve_k)
        self.degrade_retrieve_k = int(degrade_retrieve_k)
        resolved = tuple(buckets) if buckets is not None \
            else export_lib.serving_buckets(max_batch)
        stats = ServingStats()
        wkw = {"poll_secs": poll_secs}
        wkw.update(watcher_kw or {})  # caller overrides (tests drive polls)
        self._watcher = export_lib.LatestWatcher(
            publish_dir,
            loader=lambda path: CascadeModel(path, buckets=resolved),
            on_swap=lambda path: stats.record_swap(),
            **wkw)
        ekw = dict(engine_kw or {})
        if (slo_ms > 0 or shed_watermark > 0) \
                and "admission" not in ekw and "admission_kw" not in ekw:
            ekw["admission_kw"] = {"slo_ms": slo_ms,
                                   "shed_watermark": shed_watermark}
        self._engine = ServingEngine(
            self._watcher, max_batch=max_batch, max_delay_ms=max_delay_ms,
            buckets=resolved, queue_rows=queue_rows, stats=stats, **ekw)
        self._ladder: Optional[DegradationLadder] = None
        if self.degrade_retrieve_k > 0:
            self._ladder = DegradationLadder(stats=stats)
            # Without an admission gate the ladder still needs a pressure
            # scale: the same watermark default (half the queue).
            self._degrade_watermark = (
                self._engine.admission.shed_watermark
                if self._engine.admission is not None
                else max(1, int(shed_watermark)
                         or self._engine.queue_rows // 2))

    @property
    def watcher(self) -> export_lib.LatestWatcher:
        return self._watcher

    @property
    def engine(self) -> ServingEngine:
        return self._engine

    @property
    def stats(self) -> ServingStats:
        return self._engine.stats

    def current(self) -> CascadeModel:
        model = self._watcher._fn
        if model is None:
            raise RuntimeError("no cascade artifact published yet")
        return model

    # ----------------------------------------------------- degraded modes
    @property
    def ladder(self) -> Optional[DegradationLadder]:
        return self._ladder

    def _pressure(self) -> float:
        """The ladder's drive signal: the admission controller's combined
        depth+delay pressure when one is armed, raw queue depth over the
        degrade watermark otherwise."""
        pending = self._engine.pending_rows
        adm = self._engine.admission
        if adm is not None:
            return adm.pressure(pending)
        return pending / self._degrade_watermark

    def ladder_rung(self) -> int:
        """Advance the degradation ladder against CURRENT pressure and
        return the rung (0 = full cascade). Called per recommend(); also
        callable idle (the drill uses it to observe recovery after a
        chaos window drains)."""
        if self._ladder is None:
            return 0
        return self._ladder.update(self._pressure())

    # ------------------------------------------------------------- serving
    def retrieve(self, hist_ids: np.ndarray, hist_mask: np.ndarray,
                 k: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Retrieval stage only: (item_ids [B, k], scores [B, k])."""
        model = self.current()
        hist_ids = np.atleast_2d(np.asarray(hist_ids, np.int32))
        hist_mask = np.atleast_2d(np.asarray(hist_mask, np.float32))
        users = model.user_embed(hist_ids, hist_mask)
        return model.index.search(users, k or self.retrieve_k)

    def recommend(self, hist_ids: np.ndarray, hist_mask: np.ndarray,
                  feat_ids: np.ndarray, feat_vals: np.ndarray, *,
                  k: int = 10, timeout: Optional[float] = 30.0,
                  value: str = VALUE_DEFAULT
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """ONE user's end-to-end recommendation: (item_ids [k], probs [k]).

        ``hist_ids``/``hist_mask`` [L]; ``feat_ids``/``feat_vals`` [F] the
        request context (field ``ITEM_SLOT`` is overwritten per candidate).
        The SAME loaded model version serves both stages of this request
        even if a hot swap lands mid-flight. ``value`` is the admission
        value class of the inner ranking request.

        With the degradation ladder armed, an over-budget fleet answers
        degraded instead of failing: rung 1 ranks only
        ``degrade_retrieve_k`` candidates; rung 2 skips the ranker and the
        returned scores are RETRIEVAL scores (inner products), not
        probabilities — callers can tell from the counted, traced rung.
        """
        model = self.current()
        hist_ids = np.asarray(hist_ids, np.int32).reshape(1, -1)
        hist_mask = np.asarray(hist_mask, np.float32).reshape(1, -1)
        feat_ids = np.asarray(feat_ids, np.int32).reshape(-1)
        feat_vals = np.asarray(feat_vals, np.float32).reshape(-1)
        if feat_ids.shape[0] != model.field_size:
            raise ValueError(
                f"expected {model.field_size} context fields, "
                f"got {feat_ids.shape[0]}")
        rung = self.ladder_rung()
        retrieve_k = self.retrieve_k if rung == 0 \
            else self.degrade_retrieve_k
        users = model.user_embed(hist_ids, hist_mask)
        cand_ids, cand_scores = model.index.search(users, retrieve_k)
        cand_ids = cand_ids[0]                              # [N]
        n = cand_ids.shape[0]
        if rung > 0:
            self.stats.record_degraded(DEGRADE_RUNGS[rung])
        if rung >= 2:
            # retrieval_only: serve the index's order — the request costs
            # one tower embed + one ANN search, no ranking flush at all.
            k = min(int(k), n)
            return cand_ids[:k], cand_scores[0][:k]
        ids = np.tile(feat_ids, (n, 1)).astype(np.int32)    # [N, F]
        vals = np.tile(feat_vals, (n, 1)).astype(np.float32)
        ids[:, ITEM_SLOT] = cand_ids
        if model.hist_len:
            h_ids, h_mask = _fit_history(hist_ids[0], hist_mask[0],
                                         model.hist_len)
            ids = np.concatenate(
                [ids, np.tile(h_ids, (n, 1))], axis=1)
            vals = np.concatenate(
                [vals, np.tile(h_mask, (n, 1))], axis=1)
        probs = np.asarray(
            self._engine.predict(ids, vals, timeout=timeout,
                                 value=value)).reshape(-1)
        k = min(int(k), n)
        top = np.argsort(-probs, kind="stable")[:k]
        return cand_ids[top], probs[top]

    # ----------------------------------------------------------- lifecycle
    def close(self, timeout: Optional[float] = None) -> None:
        self._engine.close(timeout=timeout)
        self._watcher.close()

    def __enter__(self) -> "CascadeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _fit_history(hist_ids: np.ndarray, hist_mask: np.ndarray,
                 hist_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """Pad/truncate a request's history to the artifact's trained length
    (keep the most recent tail on truncation)."""
    ln = hist_ids.shape[0]
    out_ids = np.zeros((hist_len,), np.int32)
    out_mask = np.zeros((hist_len,), np.float32)
    n = min(ln, hist_len)
    out_ids[:n] = hist_ids[ln - n:]
    out_mask[:n] = hist_mask[ln - n:]
    return out_ids, out_mask
