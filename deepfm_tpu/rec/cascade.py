"""Retrieve→rank cascade serving: one artifact, two stages, one hot swap.

Closes the tentpole loop (README "Retrieval→ranking cascade"): a published
artifact dir carries THREE servables —

  * the ranker (``export_serving``'s StableHLO + params, history-aware via
    the packed-column signature),
  * the twin towers (``towers.npz`` + ``towers_config.json``),
  * the candidate index (``index.npz`` + ``index_meta.json``, recall@k
    stamped).

``export_cascade`` writes the retrieval files FIRST and lets
``export_serving`` finish the dir, so the existing ``ARTIFACT_COMPLETE``
marker certifies all three stages at once. :class:`CascadeEngine` serves
them end-to-end: user history → user tower → index top-N → packed ranking
batch through a :class:`~deepfm_tpu.serve.engine.ServingEngine` → top-k.
Hot swap is ATOMIC across stages: one ``LatestWatcher`` loads ranker +
towers + index off to the side as a single :class:`CascadeModel` and swaps
the composite with one assignment — no request ever ranks new candidates
with an old ranker or vice versa.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data import fileio
from ..models.twin_tower import TwinTower
from ..obs import trace as trace_lib
from ..serve.admission import (DEGRADE_RUNGS, VALUE_DEFAULT,
                               AdmissionController, DegradationLadder)
from ..serve.cache import ResultCache, request_fingerprint
from ..serve.engine import ServingEngine
from ..serve.stats import ServingStats
from ..utils import export as export_lib
from .index import CandidateIndex

TOWERS_FILE = "towers.npz"
TOWERS_CONFIG_FILE = "towers_config.json"

#: which feature field holds the candidate item id (the cascade convention
#: shared with ``train_twin_tower``'s positive extraction)
ITEM_SLOT = 0


def _flatten_params(params) -> Tuple[list, object]:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [np.asarray(x) for x in leaves], treedef


def save_towers(tower_params, cfg: Config, out_dir: str) -> None:
    """``towers.npz`` (leaves in tree-flatten order) + the config needed to
    rebuild the same tree structure at load time."""
    leaves, _ = _flatten_params(tower_params)
    fileio.makedirs(out_dir)
    np.savez_compressed(os.path.join(out_dir, TOWERS_FILE),
                        **{f"p{i}": leaf for i, leaf in enumerate(leaves)})
    with open(os.path.join(out_dir, TOWERS_CONFIG_FILE), "w") as f:
        json.dump({"config": cfg.to_dict()}, f, indent=2)


def load_towers(in_dir: str) -> Tuple[TwinTower, Dict]:
    """(model, params) from :func:`save_towers` output. The param tree is
    rebuilt from the stored config (same treedef as ``init``), so leaf
    order — not leaf names — is the contract."""
    with open(os.path.join(in_dir, TOWERS_CONFIG_FILE)) as f:
        cfg = Config.from_dict(json.load(f)["config"])
    model = TwinTower(cfg)
    template = model.init(jax.random.PRNGKey(0))
    _, treedef = jax.tree_util.tree_flatten(template)
    data = np.load(os.path.join(in_dir, TOWERS_FILE))
    leaves = [data[f"p{i}"] for i in range(len(data.files))]
    return model, jax.tree_util.tree_unflatten(treedef, leaves)


def export_cascade(ranker_model, ranker_state, cfg: Config, out_dir: str, *,
                   tower_params, index: CandidateIndex,
                   index_meta: Optional[Dict] = None) -> str:
    """Write a complete cascade artifact: towers + index, THEN the ranker
    export (which writes ``ARTIFACT_COMPLETE`` last — the marker certifies
    every stage). ``index_meta`` carries measured stamps (recall@k)."""
    fileio.makedirs(out_dir)
    save_towers(tower_params, cfg, out_dir)
    index.save(out_dir, extra_meta=index_meta)
    return export_lib.export_serving(ranker_model, ranker_state, cfg, out_dir)


def cascade_extra_export(cfg: Config, tower_params, index: CandidateIndex, *,
                         index_meta: Optional[Dict] = None
                         ) -> Callable[[str], None]:
    """``Publisher(extra_export=...)`` hook: stamps the frozen retrieval
    stage into every published ranker version (online training republishes
    the ranker continuously; retraining towers/index is a batch job)."""
    def hook(staging_dir: str) -> None:
        save_towers(tower_params, cfg, staging_dir)
        index.save(staging_dir, extra_meta=index_meta)
    return hook


class CascadeModel:
    """ONE loaded artifact version: ranker + towers + index, swap-atomic.

    Callable with the engine's ``(feat_ids, feat_vals)`` signature (ranking
    only — packed columns), and carries the retrieval stage alongside so a
    single reference assignment swaps both."""

    def __init__(self, path: str, *, buckets: Sequence[int]):
        self.path = path
        self.rank_fn = export_lib.load_serving(path, buckets=buckets)
        with fileio.open_stream(
                fileio.join(path, "model_config.json"), "r") as f:
            meta = json.load(f)
        self.field_size = int(meta["config"]["field_size"])
        self.hist_len = int(meta.get("history_len", 0))
        self.tower_model, self.tower_params = load_towers(path)
        self.index, self.index_meta = CandidateIndex.load(path)
        self._user_fn = jax.jit(self.tower_model.user_embed)
        # Fused cascade program cache: one jitted program per
        # (batch_bucket, seq_len, retrieve_k, k) — same bounded-compile
        # discipline as BucketedPredict. Living on the MODEL means a hot
        # swap drops every stale program with the old version for free.
        self._fused_cache: Dict[Tuple[int, int, int, int], Callable] = {}
        self.fused_failed = False   # set on first structural fusion error

    # engine-facing predict: delegate, keep prewarm metadata visible
    def __call__(self, feat_ids, feat_vals):
        return self.rank_fn(feat_ids, feat_vals)

    @property
    def buckets(self):
        return getattr(self.rank_fn, "buckets", None)

    @property
    def input_cols(self):
        return getattr(self.rank_fn, "input_cols", None)

    def user_embed(self, hist_ids: np.ndarray,
                   hist_mask: np.ndarray) -> np.ndarray:
        return np.asarray(self._user_fn(
            self.tower_params, hist_ids.astype(np.int32),
            hist_mask.astype(np.float32)))

    # ------------------------------------------------------ fused program
    @property
    def supports_fused(self) -> bool:
        """The fused device program needs a TRACEABLE ranker (the artifact
        loader attaches ``raw_call`` when the StableHLO/params path allows
        it) and a fusable index — ``brute`` is one ``top_k`` over a matmul;
        the ANN's host-side partition scan cannot live inside jit."""
        return (getattr(self.rank_fn, "raw_call", None) is not None
                and self.index.kind == "brute"
                and not self.fused_failed)

    def fused_program(self, batch: int, seq_len: int, retrieve_k: int,
                      k: int) -> Callable:
        """ONE jitted program for the whole per-request cascade at this
        shape: user tower -> device top-k retrieval -> candidate
        substitution into ``ITEM_SLOT`` -> history fitting -> ranker ->
        device top-k of the ranked probabilities. Everything between the
        request arrays and the final (ids, probs) stays on device — no
        host round-trip between stages. Compiled once per shape key and
        cached on this model version.

        Stage-for-stage it computes exactly what the staged path computes:
        the same ``q @ V.T`` + ``lax.top_k`` retrieval (same tie-break:
        lowest index first, matching the staged ``argsort(kind="stable")``),
        the same zero-padded history fit, and the ranker through the same
        exported program — pinned bit-equal in ``tests/test_cascade.py``.
        """
        key = (int(batch), int(seq_len), int(retrieve_k), int(k))
        fn = self._fused_cache.get(key)
        if fn is not None:
            return fn
        raw = self.rank_fn.raw_call
        mat = jnp.asarray(self.index.vectors)                    # [V, D]
        item_ids = jnp.asarray(self.index.ids.astype(np.int32))  # [V]
        field = int(self.field_size)
        hist_len = int(self.hist_len)
        tower_params = self.tower_params
        user_fn = self.tower_model.user_embed
        b, n, kk = key[0], int(retrieve_k), int(k)
        fit = min(int(seq_len), hist_len)

        def prog(hist_ids, hist_mask, feat_ids, feat_vals):
            users = user_fn(tower_params, hist_ids, hist_mask)   # [B, D]
            _, rows = jax.lax.top_k(users @ mat.T, n)            # [B, N]
            cands = item_ids[rows]                               # [B, N]
            ids = jnp.broadcast_to(feat_ids[:, None, :], (b, n, field))
            ids = ids.at[:, :, ITEM_SLOT].set(cands)
            vals = jnp.broadcast_to(feat_vals[:, None, :], (b, n, field))
            if hist_len:
                # static _fit_history: keep the most recent tail, zero-pad
                h_ids = jnp.zeros((b, hist_len), jnp.int32)
                h_ids = h_ids.at[:, :fit].set(hist_ids[:, seq_len - fit:])
                h_mask = jnp.zeros((b, hist_len), jnp.float32)
                h_mask = h_mask.at[:, :fit].set(
                    hist_mask[:, seq_len - fit:])
                ids = jnp.concatenate(
                    [ids, jnp.broadcast_to(h_ids[:, None, :],
                                           (b, n, hist_len))], axis=2)
                vals = jnp.concatenate(
                    [vals, jnp.broadcast_to(h_mask[:, None, :],
                                            (b, n, hist_len))], axis=2)
            probs = raw(ids.reshape(b * n, -1).astype(jnp.int32),
                        vals.reshape(b * n, -1).astype(jnp.float32))
            if isinstance(probs, dict):
                raise TypeError(
                    "fused cascade needs a single-output ranker; "
                    "multitask artifacts use the staged path")
            probs = jnp.reshape(probs, (b, n))
            top_p, top_i = jax.lax.top_k(probs, kk)
            top_ids = jnp.take_along_axis(cands, top_i, axis=1)
            return top_ids, top_p

        fn = jax.jit(prog)
        self._fused_cache[key] = fn
        return fn


class CascadeEngine:
    """Two-stage serving over the publish/hot-swap machinery.

    ``recommend(hist_ids, hist_mask, feat_ids, feat_vals, k)``:

      1. user tower embeds the history;
      2. the candidate index retrieves ``retrieve_k`` item ids;
      3. each candidate is substituted into the request's item slot
         (field ``ITEM_SLOT``), history packed alongside, and the batch
         ranked through the inner :class:`ServingEngine` (dynamic batching
         + bucketed shapes + backpressure all apply);
      4. the top ``k`` candidates by ranker probability come back.

    An empty history is legal end-to-end: the user tower pools zeros (the
    index then returns ITS notion of head items) and the ranker's attention
    contributes exact zeros — finite probabilities, never NaN (the
    masked-softmax regression the drill pins).

    **Overload plane.** ``slo_ms``/``shed_watermark`` build an
    :class:`~deepfm_tpu.serve.admission.AdmissionController` for the inner
    ranking engine (low-value requests get a typed ``AdmissionShed``).
    ``degrade_retrieve_k`` > 0 additionally arms the graceful-degradation
    ladder: under pressure ``recommend`` first shrinks the candidate set to
    ``degrade_retrieve_k`` (rung ``reduced_retrieve``), then skips the
    ranker entirely and answers in retrieval order (rung
    ``retrieval_only`` — scores are the index's inner-product scores, NOT
    calibrated probabilities). Every rung change is a counted, span-traced
    transition; per-request degradation is counted per rung.
    """

    def __init__(self, publish_dir: str, *, retrieve_k: int = 50,
                 poll_secs: float = 2.0, max_batch: int = 256,
                 max_delay_ms: float = 5.0,
                 buckets: Optional[Sequence[int]] = None,
                 queue_rows: int = 0,
                 slo_ms: float = 0.0, shed_watermark: int = 0,
                 degrade_retrieve_k: int = 0,
                 fused: bool = False,
                 user_cache_rows: int = 0,
                 cache_rows: int = 0, cache_ttl_s: float = 0.0,
                 coalesce: bool = False,
                 watcher_kw: Optional[dict] = None,
                 engine_kw: Optional[dict] = None):
        if retrieve_k < 1:
            raise ValueError("retrieve_k must be >= 1")
        if degrade_retrieve_k < 0 or degrade_retrieve_k > retrieve_k:
            raise ValueError(
                f"degrade_retrieve_k must be in 0..retrieve_k="
                f"{retrieve_k}, got {degrade_retrieve_k}")
        if user_cache_rows < 0:
            raise ValueError(
                f"user_cache_rows must be >= 0, got {user_cache_rows}")
        self.retrieve_k = int(retrieve_k)
        self.degrade_retrieve_k = int(degrade_retrieve_k)
        resolved = tuple(buckets) if buckets is not None \
            else export_lib.serving_buckets(max_batch)
        stats = ServingStats()
        wkw = {"poll_secs": poll_secs}
        wkw.update(watcher_kw or {})  # caller overrides (tests drive polls)
        self._watcher = export_lib.LatestWatcher(
            publish_dir,
            loader=lambda path: CascadeModel(path, buckets=resolved),
            on_swap=lambda path: stats.record_swap(),
            **wkw)
        ekw = dict(engine_kw or {})
        if (slo_ms > 0 or shed_watermark > 0) \
                and "admission" not in ekw and "admission_kw" not in ekw:
            ekw["admission_kw"] = {"slo_ms": slo_ms,
                                   "shed_watermark": shed_watermark}
        # Fast-path levers forward to the inner ranking engine: the result
        # cache there caches whole ranking batches under the same
        # (version, fingerprint) law as standalone serving.
        ekw.setdefault("cache_rows", cache_rows)
        ekw.setdefault("cache_ttl_s", cache_ttl_s)
        ekw.setdefault("coalesce", coalesce)
        self._engine = ServingEngine(
            self._watcher, max_batch=max_batch, max_delay_ms=max_delay_ms,
            buckets=resolved, queue_rows=queue_rows, stats=stats, **ekw)
        # Fused device program (opt-in; falls back per-model on any
        # structural fusion failure) + the per-user tower-embedding cache.
        self.fused = bool(fused)
        self._fused_buckets = resolved
        self.fused_calls = 0
        self._user_cache = ResultCache(user_cache_rows) \
            if user_cache_rows > 0 else None
        self.user_cache_hits = 0
        self.user_cache_misses = 0
        self._fast_lock = threading.Lock()
        stats.set_policy(serve_fused_cascade=self.fused,
                         serve_cache_user_rows=int(user_cache_rows))
        self._ladder: Optional[DegradationLadder] = None
        if self.degrade_retrieve_k > 0:
            self._ladder = DegradationLadder(stats=stats)
            # Without an admission gate the ladder still needs a pressure
            # scale: the same watermark default (half the queue).
            self._degrade_watermark = (
                self._engine.admission.shed_watermark
                if self._engine.admission is not None
                else max(1, int(shed_watermark)
                         or self._engine.queue_rows // 2))

    @property
    def watcher(self) -> export_lib.LatestWatcher:
        return self._watcher

    @property
    def engine(self) -> ServingEngine:
        return self._engine

    @property
    def stats(self) -> ServingStats:
        return self._engine.stats

    def current(self) -> CascadeModel:
        model = self._watcher._fn
        if model is None:
            raise RuntimeError("no cascade artifact published yet")
        return model

    # ----------------------------------------------------- degraded modes
    @property
    def ladder(self) -> Optional[DegradationLadder]:
        return self._ladder

    def _pressure(self) -> float:
        """The ladder's drive signal: the admission controller's combined
        depth+delay pressure when one is armed, raw queue depth over the
        degrade watermark otherwise."""
        pending = self._engine.pending_rows
        adm = self._engine.admission
        if adm is not None:
            return adm.pressure(pending)
        return pending / self._degrade_watermark

    def ladder_rung(self) -> int:
        """Advance the degradation ladder against CURRENT pressure and
        return the rung (0 = full cascade). Called per recommend(); also
        callable idle (the drill uses it to observe recovery after a
        chaos window drains)."""
        if self._ladder is None:
            return 0
        return self._ladder.update(self._pressure())

    # ------------------------------------------------------------- serving
    def _user_embed(self, model: CascadeModel, hist_ids: np.ndarray,
                    hist_mask: np.ndarray) -> np.ndarray:
        """User-tower embedding with the per-user cache in front: keyed
        ``(artifact path, fingerprint(history))`` so a hot swap — a new
        path — invalidates every cached embedding for free, exactly like
        the result cache's version key. Hits return bit-identical copies
        of the tower's output; a Zipf head user pays the tower once per
        artifact version instead of once per request."""
        if self._user_cache is None:
            return model.user_embed(hist_ids, hist_mask)
        fp = request_fingerprint(hist_ids, hist_mask)
        hit = self._user_cache.get(model.path, fp)
        if hit is not None:
            with self._fast_lock:
                self.user_cache_hits += 1
            trace_lib.instant("serve.cache", event="user_hit",
                              rows=int(hist_ids.shape[0]))
            return hit
        users = model.user_embed(hist_ids, hist_mask)
        self._user_cache.put(model.path, fp, users,
                             int(hist_ids.shape[0]))
        with self._fast_lock:
            self.user_cache_misses += 1
        return users

    def retrieve(self, hist_ids: np.ndarray, hist_mask: np.ndarray,
                 k: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Retrieval stage only: (item_ids [B, k], scores [B, k])."""
        model = self.current()
        hist_ids = np.atleast_2d(np.asarray(hist_ids, np.int32))
        hist_mask = np.atleast_2d(np.asarray(hist_mask, np.float32))
        users = self._user_embed(model, hist_ids, hist_mask)
        return model.index.search(users, k or self.retrieve_k)

    def recommend(self, hist_ids: np.ndarray, hist_mask: np.ndarray,
                  feat_ids: np.ndarray, feat_vals: np.ndarray, *,
                  k: int = 10, timeout: Optional[float] = 30.0,
                  value: str = VALUE_DEFAULT
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """ONE user's end-to-end recommendation: (item_ids [k], probs [k]).

        ``hist_ids``/``hist_mask`` [L]; ``feat_ids``/``feat_vals`` [F] the
        request context (field ``ITEM_SLOT`` is overwritten per candidate).
        The SAME loaded model version serves both stages of this request
        even if a hot swap lands mid-flight. ``value`` is the admission
        value class of the inner ranking request.

        With the degradation ladder armed, an over-budget fleet answers
        degraded instead of failing: rung 1 ranks only
        ``degrade_retrieve_k`` candidates; rung 2 skips the ranker and the
        returned scores are RETRIEVAL scores (inner products), not
        probabilities — callers can tell from the counted, traced rung.
        """
        model = self.current()
        hist_ids = np.asarray(hist_ids, np.int32).reshape(1, -1)
        hist_mask = np.asarray(hist_mask, np.float32).reshape(1, -1)
        feat_ids = np.asarray(feat_ids, np.int32).reshape(-1)
        feat_vals = np.asarray(feat_vals, np.float32).reshape(-1)
        if feat_ids.shape[0] != model.field_size:
            raise ValueError(
                f"expected {model.field_size} context fields, "
                f"got {feat_ids.shape[0]}")
        rung = self.ladder_rung()
        if rung == 0 and self.fused and model.supports_fused:
            try:
                ids_k, probs_k = self._recommend_fused(
                    model, hist_ids, hist_mask, feat_ids[None],
                    feat_vals[None], k)
                return ids_k[0], probs_k[0]
            except Exception:  # noqa: BLE001 — structural; staged fallback
                model.fused_failed = True
                trace_lib.instant("serve.cascade_fused", event="fallback")
        retrieve_k = self.retrieve_k if rung == 0 \
            else self.degrade_retrieve_k
        users = self._user_embed(model, hist_ids, hist_mask)
        cand_ids, cand_scores = model.index.search(users, retrieve_k)
        cand_ids = cand_ids[0]                              # [N]
        n = cand_ids.shape[0]
        if rung > 0:
            self.stats.record_degraded(DEGRADE_RUNGS[rung])
        if rung >= 2:
            # retrieval_only: serve the index's order — the request costs
            # one tower embed + one ANN search, no ranking flush at all.
            k = min(int(k), n)
            return cand_ids[:k], cand_scores[0][:k]
        ids = np.tile(feat_ids, (n, 1)).astype(np.int32)    # [N, F]
        vals = np.tile(feat_vals, (n, 1)).astype(np.float32)
        ids[:, ITEM_SLOT] = cand_ids
        if model.hist_len:
            h_ids, h_mask = _fit_history(hist_ids[0], hist_mask[0],
                                         model.hist_len)
            ids = np.concatenate(
                [ids, np.tile(h_ids, (n, 1))], axis=1)
            vals = np.concatenate(
                [vals, np.tile(h_mask, (n, 1))], axis=1)
        probs = np.asarray(
            self._engine.predict(ids, vals, timeout=timeout,
                                 value=value)).reshape(-1)
        k = min(int(k), n)
        top = np.argsort(-probs, kind="stable")[:k]
        return cand_ids[top], probs[top]

    # ----------------------------------------------------- fused fast path
    def _recommend_fused(self, model: CascadeModel, hist_ids: np.ndarray,
                         hist_mask: np.ndarray, feat_ids: np.ndarray,
                         feat_vals: np.ndarray, k: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
        """Run [B] users through the single fused device program. The
        batch pads up to the engine's pow2 bucket ladder (pad users are
        all-zeros and sliced away), so at most ``len(buckets)`` programs
        compile per (seq_len, retrieve_k, k) — the same bounded-compile
        discipline as the staged ranker. Completions are recorded into
        the SAME stats reservoirs as staged requests."""
        t0 = time.monotonic()
        b = int(hist_ids.shape[0])
        bucket = export_lib.next_bucket(b, self._fused_buckets)
        if bucket != b:
            hist_ids = np.concatenate(
                [hist_ids, np.zeros((bucket - b,) + hist_ids.shape[1:],
                                    np.int32)])
            hist_mask = np.concatenate(
                [hist_mask, np.zeros((bucket - b,) + hist_mask.shape[1:],
                                     np.float32)])
            feat_ids = np.concatenate(
                [feat_ids, np.zeros((bucket - b,) + feat_ids.shape[1:],
                                    np.int32)])
            feat_vals = np.concatenate(
                [feat_vals, np.zeros((bucket - b,) + feat_vals.shape[1:],
                                     np.float32)])
        n = min(self.retrieve_k, model.index.num_items)
        kk = min(int(k), n)
        fn = model.fused_program(bucket, int(hist_ids.shape[1]), n, kk)
        top_ids, top_p = fn(hist_ids.astype(np.int32),
                            hist_mask.astype(np.float32),
                            feat_ids.astype(np.int32),
                            feat_vals.astype(np.float32))
        top_ids = np.asarray(top_ids)[:b]
        top_p = np.asarray(top_p)[:b]
        lat_ms = 1000.0 * (time.monotonic() - t0)
        with self._fast_lock:
            self.fused_calls += 1
        for _ in range(b):
            self.stats.record_request_done(lat_ms)
        # int64 ids on the way out, matching the staged index.search dtype
        return top_ids.astype(np.int64), top_p

    def recommend_batch(self, hist_ids: np.ndarray, hist_mask: np.ndarray,
                        feat_ids: np.ndarray, feat_vals: np.ndarray, *,
                        k: int = 10, timeout: Optional[float] = 30.0,
                        value: str = VALUE_DEFAULT
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """[B] users end-to-end at once: (item_ids [B, k], probs [B, k]).

        With the fused program armed (``fused=True`` and a fusable
        artifact, full-cascade rung) the whole batch is ONE device
        dispatch; otherwise each row runs the staged path. Output is
        row-for-row what per-row :meth:`recommend` returns — same items,
        probabilities to float ULP (batching changes XLA's row
        vectorization; the B=1 fused path is bit-equal to staged,
        pinned in ``tests/test_cascade.py``)."""
        hist_ids = np.atleast_2d(np.asarray(hist_ids, np.int32))
        hist_mask = np.atleast_2d(np.asarray(hist_mask, np.float32))
        feat_ids = np.atleast_2d(np.asarray(feat_ids, np.int32))
        feat_vals = np.atleast_2d(np.asarray(feat_vals, np.float32))
        model = self.current()
        if self.fused and model.supports_fused and self.ladder_rung() == 0:
            try:
                return self._recommend_fused(model, hist_ids, hist_mask,
                                             feat_ids, feat_vals, k)
            except Exception:  # noqa: BLE001 — structural; staged fallback
                model.fused_failed = True
                trace_lib.instant("serve.cascade_fused", event="fallback")
        out_ids, out_ps = [], []
        for i in range(hist_ids.shape[0]):
            ids_i, p_i = self.recommend(
                hist_ids[i], hist_mask[i], feat_ids[i], feat_vals[i],
                k=k, timeout=timeout, value=value)
            out_ids.append(ids_i)
            out_ps.append(p_i)
        return np.stack(out_ids), np.stack(out_ps)

    # ----------------------------------------------------------- lifecycle
    def close(self, timeout: Optional[float] = None) -> None:
        self._engine.close(timeout=timeout)
        self._watcher.close()

    def __enter__(self) -> "CascadeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _fit_history(hist_ids: np.ndarray, hist_mask: np.ndarray,
                 hist_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """Pad/truncate a request's history to the artifact's trained length
    (keep the most recent tail on truncation).

    Short-circuits: a history-free artifact (``hist_len`` 0 — previously
    this built and sliced zero-length scratch arrays per candidate batch)
    returns empty arrays immediately, and an already-fitting history is
    passed through without a re-fit copy."""
    hist_len = int(hist_len)
    if hist_len <= 0:
        return np.zeros((0,), np.int32), np.zeros((0,), np.float32)
    ln = hist_ids.shape[0]
    if ln == hist_len:
        return (np.asarray(hist_ids, np.int32),
                np.asarray(hist_mask, np.float32))
    out_ids = np.zeros((hist_len,), np.int32)
    out_mask = np.zeros((hist_len,), np.float32)
    n = min(ln, hist_len)
    out_ids[:n] = hist_ids[ln - n:]
    out_mask[:n] = hist_mask[ln - n:]
    return out_ids, out_mask
