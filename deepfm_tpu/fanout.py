"""Local worker fan-out: spawn ``worker_per_host`` training processes on this
host, each with its own JAX process id — the TPU-native analog of the
reference's MPI launch (``mpirun -np 4`` via ``processes_per_host=4``,
``2-hvd-gpu/deepfm-sagemaker-hvd-gpu.ipynb:87-92``).

Usage (one command per host; see scripts/launch_slice.sh for the multi-host
wrapper):

    python -m deepfm_tpu.fanout --worker_per_host 4 \
        --num_hosts 2 --host_index 0 --coordinator_address host0:12355 \
        --task_type train --data_dir ... <any launch.py flags>

Spawns ``worker_per_host`` copies of ``python -m deepfm_tpu.launch`` with:
  * ``process_id``   = host_index * worker_per_host + local_worker
  * ``num_processes`` = num_hosts * worker_per_host
  * ``dist_mode=1`` rendezvous on the coordinator (defaults to a local port
    for single-host runs)
  * ``TPU_VISIBLE_DEVICES=<local_worker>`` so each worker binds one local
    chip (the GPU-pinning analog of ``visible_device_list = local_rank``,
    reference ``2-hvd-gpu/...py:355-357``); skipped when JAX_PLATFORMS=cpu
    (CPU test clusters share the virtual devices).

The parent streams children's output and exits nonzero if any child fails.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
from typing import List, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _pump(stream, sink, prefix: str) -> None:
    for line in iter(stream.readline, ""):
        sink.write(f"[{prefix}] {line}")
        sink.flush()
    stream.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "deepfm_tpu.fanout",
        description="spawn worker_per_host launch.py processes on this host")
    ap.add_argument("--worker_per_host", type=int, required=True)
    ap.add_argument("--num_hosts", type=int, default=1)
    ap.add_argument("--host_index", type=int, default=0)
    ap.add_argument("--coordinator_address", default="",
                    help="host:port all workers rendezvous on "
                         "(default: localhost:<free port>; required for "
                         "num_hosts > 1)")
    args, passthrough = ap.parse_known_args(argv)

    n = args.worker_per_host
    if n < 1:
        raise SystemExit("--worker_per_host must be >= 1")
    if args.num_hosts > 1 and not args.coordinator_address:
        raise SystemExit(
            "--coordinator_address is required for num_hosts > 1 "
            "(every host must rendezvous on host 0's address)")
    coord = args.coordinator_address or f"localhost:{_free_port()}"
    world = args.num_hosts * n

    procs = []
    pumps = []
    for local in range(n):
        pid = args.host_index * n + local
        cmd = [
            sys.executable, "-m", "deepfm_tpu.launch",
            *passthrough,
            "--dist_mode", "1",
            "--num_processes", str(world),
            "--process_id", str(pid),
            "--coordinator_address", coord,
            "--worker_per_host", str(n),
        ]
        env = dict(os.environ)
        if env.get("JAX_PLATFORMS", "").lower() != "cpu":
            # One chip per local worker (GPU-pinning analog, ref :355-357).
            env["TPU_VISIBLE_DEVICES"] = str(local)
        p = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        procs.append(p)
        t = threading.Thread(
            target=_pump, args=(p.stdout, sys.stdout, f"worker {pid}"),
            daemon=True)
        t.start()
        pumps.append(t)

    # Watch all children; one failure terminates the siblings (they would
    # otherwise block forever inside collectives waiting for the dead rank).
    import time

    rc = 0
    remaining = set(range(len(procs)))
    while remaining:
        for i in sorted(remaining):
            r = procs[i].poll()
            if r is None:
                continue
            remaining.discard(i)
            if r != 0:
                gpid = args.host_index * n + i
                print(f"fanout: worker {gpid} exited rc={r}", file=sys.stderr)
                rc = rc or r
        if rc and remaining:
            print(f"fanout: terminating {len(remaining)} remaining worker(s)",
                  file=sys.stderr)
            for i in remaining:
                procs[i].terminate()
            for i in remaining:
                try:
                    procs[i].wait(timeout=15)
                except subprocess.TimeoutExpired:
                    procs[i].kill()
            remaining.clear()
        if remaining:
            time.sleep(0.2)
    for t in pumps:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
