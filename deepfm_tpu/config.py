"""Configuration system for deepfm_tpu.

Reproduces the reference's full flag surface (``tf.app.flags`` definitions at
``1-ps-cpu/DeepFM-dist-ps-for-multipleCPU-multiInstance.py:35-71`` and
``2-hvd-gpu/DeepFM-hvd-tfrecord-vectorized-map.py:40-68``) as a single typed
dataclass with an argparse CLI front-end, plus environment-variable defaults
mirroring the SageMaker container contract (``SM_HOSTS``, ``SM_CURRENT_HOST``,
``SM_CHANNELS``, ``SM_NUM_CPUS`` — reference ``1-ps-cpu/...py:64-67,346``).

TPU-first deltas from the reference:
  * ``dist_mode`` selects the JAX process topology instead of TF_CONFIG roles.
  * ``mesh_data`` / ``mesh_model`` describe the 2-D device mesh (data
    parallelism x embedding row-sharding) instead of PS/Horovod knobs.
  * the MKL/OMP thread flags are replaced by host-pipeline worker counts.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence


def _env_json(name: str, default: Any) -> Any:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return json.loads(raw)
    except (json.JSONDecodeError, TypeError):
        return default


@dataclasses.dataclass
class Config:
    """Full training configuration.

    Field-by-field parity with the reference flag tables; reference flag name
    noted where it differs.
    """

    # ---- task & topology (reference: dist_mode, task_type) ----
    task_type: str = "train"          # train | eval | infer | export
    dist_mode: int = 0                # 0: single/auto, 1: local fake cluster, 2: multi-process
    num_processes: int = 1            # world size for dist_mode>0 (SM_HOSTS analog)
    process_id: int = 0               # this process's rank (SM_CURRENT_HOST analog)
    coordinator_address: str = ""     # jax.distributed coordinator (host:port)

    # ---- model hyperparameters (reference: model flags) ----
    model: str = "deepfm"             # deepfm | widedeep | dcnv2 | dlrm | din | bst
    feature_size: int = 117581        # vocabulary size (reference ipynb:85)
    field_size: int = 39              # number of fields (reference ipynb:90)
    embedding_size: int = 32          # latent dim (reference flag default, ...py:44)
    deep_layers: str = "128,64,32"    # DNN tower widths (reference ipynb:90)
    dropout: str = "0.5,0.5,0.5"      # per-layer keep... reference semantics: dropout rates
    batch_norm: bool = False
    batch_norm_decay: float = 0.9
    cross_layers: int = 3             # DCN-v2 only: number of cross layers
    cross_rank: int = 0               # DCN-v2: low-rank dim for cross W (0 = full rank)
    l2_reg: float = 1e-4
    loss_type: str = "log_loss"       # log_loss | square_loss

    # ---- multi-task ranking (README "Multi-task ranking", TUNING §2.12) ----
    # Comma list of task names. One name = the single-task zoo (--model
    # picks the graph); two names (e.g. "ctr,cvr") build the multi-task
    # model: task 0 reads the batch's `label` column, task 1 the optional
    # `label2` column.
    tasks: str = "ctr"
    # Per-task loss weights as a comma list ("" = all 1.0). Same length as
    # --tasks when set.
    task_weights: str = ""
    # Multi-task architecture: shared_bottom (one shared hidden stack,
    # per-task heads), mmoe (mixture-of-experts with per-task softmax
    # gates; Ma et al., KDD 2018), esmm (entire-space CTR+CVR; Ma et al.,
    # SIGIR 2018 — requires exactly the 2-task contract).
    multitask: str = "shared_bottom"  # shared_bottom | mmoe | esmm
    mmoe_experts: int = 4             # expert count for --multitask mmoe

    # ---- retrieval->ranking cascade (README "Retrieval→ranking cascade",
    #      TUNING §2.14) ----
    # User-history sequence length. 0 disables history; > 0 makes the
    # pipeline decode the optional ragged hist_ids/hist_vals TFRecord pair
    # into fixed [B, history_max_len] id/mask columns (padded/truncated)
    # that sequence models (din/bst) attend over. Incompatible with the
    # two-label multi-task contract and with embedding_update=sparse (the
    # sparse plan covers feat_ids only).
    history_max_len: int = 0
    # Candidate-index structure for the retrieval stage (rec/index.py):
    # "brute" = exact jit top-k over all item embeddings; "ann" = quantized
    # partition scan (approximate; recall@k is measured against brute force
    # and stamped into the exported index artifact).
    index_kind: str = "brute"

    # ---- optimization ----
    optimizer: str = "Adam"           # Adam | Adagrad | Momentum | ftrl
    learning_rate: float = 5e-4
    scale_lr_by_world: bool = True    # reference hvd: lr * hvd.size() (2-hvd-gpu/...py:149)
    num_epochs: int = 1
    batch_size: int = 1024            # GLOBAL batch size (split over data axis)

    # ---- input pipeline (reference: pipe_mode, shard flags) ----
    data_dir: str = ""
    val_data_dir: str = ""
    pipe_mode: int = 0                # 0: file mode, 1: streaming mode (Pipe analog)
    channels: str = ""                # JSON list of channel names (SM_CHANNELS analog)
    enable_s3_shard: bool = False     # files pre-sharded per process (ShardedByS3Key analog)
    enable_data_multi_path: bool = False  # one channel/dir per local worker (hvd flag ...py:68)
    worker_per_host: int = 1          # reference 2-hvd-gpu/...py:64
    shuffle_buffer: int = 10000
    shuffle_files: bool = True
    drop_remainder: bool = True
    prefetch_batches: int = 4
    reader_threads: int = 4           # host decode parallelism (MKL/OMP analog)
    # Decode worker PROCESSES feeding shared-memory slabs (0 = in-process
    # decode). Threads stop helping once the GIL-bound shuffle/stage work
    # dominates; processes sidestep the GIL entirely (see TUNING.md
    # "input_workers vs reader_threads"). Needs the native decoder; batch
    # order is bit-identical to the in-process path at equal seeds.
    input_workers: int = 0
    # Decoded-epoch cache (data/cache.py): frame+decode once, serve later
    # epochs from contiguous column slabs through the same shuffle pool.
    # "ram" holds the columns in-process; "disk" persists memory-mapped
    # .npy slabs under decoded_cache_dir (default: <model_dir>/decoded_cache)
    # keyed by a dataset fingerprint — stale entries rebuild automatically.
    decoded_cache: str = "off"        # off | ram | disk
    decoded_cache_dir: str = ""
    # Device-resident dataset (train/loop.py): when the decoded epoch fits
    # device_dataset_hbm_fraction of accelerator memory, upload the columns
    # once and run each epoch as an on-device multi-step program — zero
    # per-step host->device traffic. Falls back to the staged path with a
    # RuntimeWarning when over budget or feature-incompatible.
    device_dataset: bool = False
    device_dataset_hbm_fraction: float = 0.6
    use_native_decoder: bool = True   # C++ TFRecord decode path
    # Fused decode->assemble: one C call per shuffle-pool drain writes
    # decoded records straight into the transfer-layout pool. Kill switch
    # only — emission is bit-identical with it off (per-chunk scatter) —
    # but it is part of the consumption-layout fingerprint so a resumed
    # run never mixes probe outcomes mid-epoch. No-op without the native
    # decoder or on a stale prebuilt .so lacking the entry point.
    native_assembly: bool = True
    # CRC32C-check every record. Default False for speed: skipping the
    # check buys ~15-20% host decode throughput on a 1-core host (TUNING.md).
    # NOTE this is a deliberate parity DEVIATION, not parity: TF's record
    # reader does verify the length-field CRC (and data CRC unless the
    # dataset opts out), so the reference pipeline was checking. Flip on
    # for untrusted or long-haul-transferred data.
    verify_crc: bool = False
    steps_per_loop: int = 8           # optimizer steps per host dispatch (lax.scan)
    transfer_ahead: int = 2           # host->device staging depth (batches ahead)
    # Device staging slots (TUNING §2.13). 2 = double-buffered: the staging
    # thread transfers dispatch k+1's superbatch into the free slot while
    # the device computes dispatch k, fencing on slot reuse (transfer k
    # blocks until dispatch k-2 completed ON device). 1 = single-buffered:
    # every transfer fences on the previous dispatch's completion — H2D
    # serializes with compute (the A/B baseline, and an HBM escape hatch
    # when two staged superbatches don't fit). The trajectory is
    # bit-identical either way; only timing moves.
    staging_buffers: int = 2          # 1 | 2 device staging slots
    # Gradient accumulation (TUNING §2.13): accumulate this many microbatch
    # gradients (each a full --batch_size batch) before ONE optimizer
    # apply — effective batch = batch_size * grad_accum_steps * data
    # parallelism, at one microbatch of activation memory. state.step and
    # every step-counted cadence (log/save/resume) keep counting
    # MICROBATCHES; Adam's bias-correction count ticks once per apply.
    grad_accum_steps: int = 1         # microbatches per optimizer apply
    # ---- fault tolerance (I/O layer; see README "Fault tolerance") ----
    on_bad_record: str = "raise"      # raise | skip corrupt/truncated records
    max_bad_records: int = 0          # skip budget when skipping (0 = unlimited)
    io_retries: int = 4               # attempts per I/O op (1 = no retry)
    io_retry_backoff_secs: float = 0.1  # base of exponential full-jitter backoff
    io_retry_deadline_secs: float = 0.0  # per-op wall-clock cap (0 = none)
    # ---- training-runtime resilience (see README "Preemption & self-healing") ----
    # Policy for a non-finite loss / non-finite params after a dispatch:
    # abort raises (checked at log cadence — free); skip drops the poisoned
    # dispatch's update; rollback restores the last checkpoint and replays
    # from its recorded offset. skip/rollback sync the loss every dispatch.
    on_nonfinite: str = "abort"       # abort | skip | rollback
    max_rollbacks: int = 3            # shared skip+rollback budget per run
    # Abort (exit code 43) when no dispatch completes within this many
    # seconds; also bounds input-worker ring reads. 0 disables.
    dispatch_timeout_s: float = 0.0
    # Warn + count when |loss - EMA| exceeds this many EMA std-devs
    # (after warmup). Advisory only; 0 disables.
    loss_spike_zscore: float = 0.0
    # ---- online training & hot publishing (README "Online training") ----
    # Continuous training: the train channel is an UNBOUNDED stream — a
    # directory (or manifest file) that keeps receiving TFRecord shards
    # (data/stream.py tails it; a high-water-mark sidecar in model_dir
    # makes restarts replay-exact). Requires pipe_mode=1. The run ends on
    # SIGTERM (exit 42, resumable) or after stream_idle_timeout_secs
    # without new data.
    online_mode: bool = False
    # Publish a servable artifact (delta params checkpoint + export) every
    # N steps / secs into publish_dir (default: <model_dir>/publish),
    # atomically, off the training hot path. 0 disables that cadence.
    publish_every_steps: int = 0
    publish_every_secs: float = 0.0
    publish_dir: str = ""
    # A publish still in flight after this long trips the watchdog (exit
    # 43) — same contract as dispatch_timeout_s. 0 disables.
    publish_timeout_s: float = 600.0
    # Sliding eval window for the online AUC: slices older than this many
    # steps are evicted. 0 = cumulative (never evict).
    online_eval_window_steps: int = 0
    # Stream watcher cadence: how often the source is re-listed for new
    # shards, and how long with no new data before the stream reports EOF
    # (0 = wait forever; stop with SIGTERM).
    stream_poll_secs: float = 2.0
    stream_idle_timeout_secs: float = 0.0
    # ---- serving runtime (serve/; README "Serving") ----
    # Dynamic batcher policy: a flush fires when serve_max_batch rows are
    # queued (max-batch policy) or serve_max_delay_ms elapsed since the
    # FIRST queued request (deadline policy), whichever comes first.
    serve_max_batch: int = 256
    serve_max_delay_ms: float = 5.0
    # Bounded request queue in ROWS; submit past it raises the typed
    # ServerOverloaded (backpressure, never a hang). 0 = 8 * serve_max_batch.
    serve_queue_rows: int = 0
    # Batch-shape buckets as a comma list ("8,32,256"); every flush pads to
    # the next bucket so at most len(buckets) predict programs compile.
    # "" = the power-of-two ladder up to serve_max_batch.
    serve_buckets: str = ""
    # Frontend wedge watchdog: a predict or response write stalled past this
    # many seconds aborts with exit code 43 (same contract as
    # dispatch_timeout_s). 0 disables.
    serve_timeout_s: float = 0.0
    # Pipelined batching depth: how many formed flushes may be handed off
    # but not yet completed. 1 = strict flush-then-refill (the pre-pipeline
    # engine); 2 (default) forms flush k+1 while flush k executes.
    serve_inflight: int = 2
    # Priority lane: requests of at most this many rows get head-of-line
    # bypass into every forming batch (never stranded behind a max-batch
    # fill of large requests). 0 disables the lane.
    serve_small_rows: int = 0
    # ---- serving fast path (serve/cache.py; README "Serving fast path",
    # TUNING §2.20) ----
    # Version-keyed LRU result cache, capacity in ROWS (same unit as
    # serve_queue_rows): a request whose (ids, vals) bytes match a response
    # already flushed under the CURRENT model version resolves immediately,
    # bit-identical to the cached flush. Hot swaps invalidate for free
    # (the key carries the artifact version). 0 disables the cache.
    serve_cache_rows: int = 0
    # Cache entry TTL in seconds (lazy expiry at lookup). 0 = no TTL; LRU
    # eviction alone bounds staleness within a model version.
    serve_cache_ttl_s: float = 0.0
    # In-flight request coalescing: concurrent byte-identical requests
    # attach to one leader future; a single device execution fans out to
    # every joined caller. Off by default (exact pre-existing behavior).
    serve_coalesce: bool = False
    # Per-user tower-embedding cache in the cascade (entries = users): a
    # head user's repeat request skips the user-tower forward pass. Keyed
    # by (artifact version, history bytes) — swap-safe. 0 disables.
    serve_cache_user_rows: int = 0
    # Fused cascade program: collapse user-embed -> index top-k ->
    # candidate-substitute -> rank into ONE jitted per-bucket batch
    # program (device-side top-k, vectorized ITEM_SLOT substitution and
    # history fitting). Brute index only; falls back to the staged path
    # (counted) when the artifact can't fuse. Off by default.
    serve_fused_cascade: bool = False
    # ---- overload plane (serve/admission.py; README "Overload &
    # degradation", TUNING §2.18) ----
    # Per-request latency SLO: the admission gate sheds low-value classes
    # when the EWMA queue delay crosses half this budget. 0 disables the
    # delay signal (depth-only gating if a watermark is set).
    serve_slo_ms: float = 0.0
    # Queue-depth shed watermark in rows (pressure 1.0). 0 = half the
    # resolved serve_queue_rows. Either serve_slo_ms or
    # serve_shed_watermark > 0 arms the admission controller.
    serve_shed_watermark: int = 0
    # Request hedging floor (ReplicatedEngine): a request still pending
    # after max(this, fleet p99) ms is re-submitted to the least-loaded
    # other replica; first completion wins, the loser is cancelled and
    # counted. 0 disables hedging.
    serve_hedge_ms: float = 0.0
    # Degraded-mode candidate count (CascadeEngine): under pressure the
    # cascade first shrinks retrieve_k to this, then skips the ranker and
    # serves retrieval order. 0 disables the degradation ladder.
    degrade_retrieve_k: int = 0
    # ---- experimentation plane (serve/experiment.py + train/promote.py;
    # README "Experimentation & gated deployment", TUNING §2.19) ----
    # Traffic-split mode in front of the engine: off (single-arm), shadow
    # (challenger duplicated on an isolated side lane, response never
    # returned), canary (small live slice with an instant kill-switch), ab
    # (live split). Any mode but off needs a challenger artifact.
    experiment_mode: str = "off"
    # Seed of the pure hash-split arm assignment — same seed, same request
    # ids, same split, bit-for-bit (the replayability contract).
    experiment_seed: int = 0
    # Challenger traffic share in permille (0-1000), so a 0.5% canary (5)
    # is expressible. In shadow mode this is the duplication rate.
    experiment_permille: int = 50
    # Shadow-lane latency SLO in ms: a shadow response slower than this is
    # counted (shadow_slo_misses) — never waited on. 0 disables the count.
    experiment_shadow_slo_ms: float = 0.0
    # Promotion gates (train/promote.py): a candidate must pass EVERY gate
    # for this many consecutive health windows before LATEST advances; one
    # breach rolls it back; two failed candidacies quarantine the version.
    experiment_gate_windows: int = 2
    # Minimum per-arm samples for a window to be judged at all (thinner
    # windows hold — they neither advance nor demote).
    experiment_min_samples: int = 50
    # Gate thresholds: challenger AUC may trail control by at most
    # -min_auc_delta; challenger p99 must stay within max_p99_ratio x
    # control p99 AND under the absolute max_p99_ms ceiling (0 = off);
    # more than max_nonfinite NaN/Inf predictions is a
    # breach; |mean predicted - observed CTR| must stay under
    # max_calibration_err; a candidate older than max_candidate_age_s
    # (0 = off) breaches the staleness gate.
    experiment_min_auc_delta: float = -0.02
    experiment_max_p99_ratio: float = 1.5
    experiment_max_p99_ms: float = 0.0
    experiment_max_nonfinite: int = 0
    experiment_max_calibration_err: float = 0.2
    experiment_max_candidate_age_s: float = 0.0

    # ---- mesh / parallelism (replaces TF_CONFIG + horovod knobs) ----
    mesh_data: int = 0                # data-parallel axis size (0 = all devices)
    mesh_model: int = 1               # embedding row-shard axis size
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"   # MXU-friendly activations/matmuls
    remat: bool = False               # jax.checkpoint the DNN tower
    use_pallas: bool = True           # fused Pallas FM kernel when on TPU
    # Row-sharded lookup collective: masked_psum (traffic ∝ batch; the CTR
    # default) or allgather_table (traffic ∝ table; huge-batch/small-table
    # regimes). See TUNING.md "Sharded embedding lookup".
    embedding_lookup: str = "masked_psum"
    # ---- embedding scale (README "Embedding scale", TUNING §2.11) ----
    # Gradient application to the embedding tables: "dense" (the bit-exact
    # reference — full-table optimizer sweep every step) or "sparse" (dedup
    # the batch's ids, segment-sum cotangents, lazy timestamped Adam on the
    # touched rows only — step cost ∝ unique ids, not vocab). sparse
    # requires Adam and a single-device (1x1) mesh; L2 decays touched rows
    # only (documented deviation, tolerance-pinned against dense).
    embedding_update: str = "dense"   # dense | sparse
    # Hash-bucketed multi-table embeddings: comma list of per-table bucket
    # counts ("" = one monolithic feature_size table). N tables replace the
    # monolithic table; ids map to (table, bucket) by deterministic uint32
    # mixing, so feature_size may exceed any single allocation.
    embedding_buckets: str = ""
    # How ids pick their table in hashed mode: "hash" (id-mixed, balanced)
    # or "field" (field index mod N — per-field tables).
    embedding_assign: str = "hash"
    # Hot/cold tiered storage: "hot_cold" keeps an HBM-resident hot-row
    # cache (embedding_hot_rows slots) over a host-RAM cold store, with the
    # cold fetch for dispatch t+1 prefetched on the staging thread while
    # dispatch t computes. Requires embedding_update=sparse, the monolithic
    # table layout, and a single-device mesh.
    embedding_tiering: str = "off"    # off | hot_cold
    embedding_hot_rows: int = 0       # hot-cache capacity in rows (tiering)
    # Cold-store precision: float32; int8 or fp8_e4m3 store quantized rows
    # with a per-row dequant scale (fetch dequantizes, writeback
    # requantizes) at 1/4 the float32 host bytes. fp8 keeps ~2 mantissa
    # bits of relative precision per element vs int8's fixed grid.
    embedding_cold_dtype: str = "float32"  # float32 | int8 | fp8_e4m3
    # Sparse embedding-plane kernel selection (ops/pallas_embedding.py):
    # "auto" = Pallas kernels on TPU where the probe passes, the optimized
    # XLA legs (counting plan build, fused one-leaf backward, select
    # writeback, fused cache install) elsewhere; "pallas" forces Pallas
    # where possible; "xla" forces the optimized XLA legs even on TPU;
    # "off" is the kill switch — the seed formulation everywhere,
    # bit-for-bit. TUNING §2.11 has the selection table.
    embedding_kernels: str = "auto"   # auto | pallas | xla | off
    # Model-parallel row sharding of the embedding tables under the SPARSE
    # update path: "rows" partitions every logical table (monolithic or
    # hash-bucketed) contiguously over the model mesh axis with the
    # lazy-Adam moments sharded alongside, so per-device embedding HBM
    # drops ~1/mesh_model. Per step the batch's dedup plan is bucketed by
    # owner shard, request sets cross lax.all_to_all, owners gather and
    # update only their own rows, and a second all_to_all returns the
    # embeddings (ops/embedding.py exchange_*). On one device (or
    # mesh_model=1) this routes to the literal unsharded sparse program —
    # bit-identical by construction. TUNING §2.11 has the decision guide.
    embedding_shard: str = "off"      # off | rows

    # ---- checkpoint / export / logging ----
    model_dir: str = ""               # checkpoint dir (shared storage; reference :434)
    servable_model_dir: str = ""      # serving export dir (reference :52)
    clear_existing_model: bool = False  # reference 2-hvd-gpu/...py:60
    log_steps: int = 10               # reference flag :47 (value 10 in ipynb:90)
    save_checkpoints_steps: int = 1000
    keep_checkpoint_max: int = 3
    # Consecutive interval-save failures tolerated before aborting; each
    # failure logs and defers to the next interval (final forced save
    # always hard-fails). 0 = fail on the first save error.
    max_save_failures: int = 3
    eval_start_delay_secs: int = 0    # reference TrainSpec/EvalSpec (1-ps-cpu/...py:440-441)
    eval_throttle_secs: int = 0
    auc_num_thresholds: int = 200     # parity with tf.metrics.auc default
    seed: int = 42
    profile_dir: str = ""             # jax.profiler trace output ('' = disabled)
    # TensorBoard scalar summaries (loss/examples_per_sec at log_steps
    # cadence + per-eval AUC), chief-only — the Estimator summary-writer
    # analog ('' = disabled).
    tensorboard_dir: str = ""
    profile_steps: int = 20           # steps traced per run (bounded window)
    # Unified telemetry plane (obs/, TUNING.md §2.17). Span tracing over the
    # host seams (staging ring, input workers, serving batcher, publisher),
    # exported as Chrome trace_event JSON: off = every site a no-op,
    # ring = bounded buffer (wraparound drops counted), full = unbounded.
    trace: str = "off"
    trace_dir: str = ""               # trace JSON destination ('' = model_dir or cwd)
    trace_buffer: int = 65536         # ring capacity in events (trace=ring)
    # Periodic JSONL dump of the unified metrics registry (0 = off).
    metrics_snapshot_secs: float = 0.0

    # ------------------------------------------------------------------
    def __post_init__(self) -> None:
        self.validate()

    def validate(self) -> None:
        if self.task_type not in ("train", "eval", "infer", "export"):
            raise ValueError(f"unknown task_type: {self.task_type!r}")
        if self.trace not in ("off", "ring", "full"):
            raise ValueError(
                f"trace must be off|ring|full, got {self.trace!r}")
        if self.trace_buffer < 1:
            raise ValueError("trace_buffer must be >= 1")
        if self.metrics_snapshot_secs < 0:
            raise ValueError("metrics_snapshot_secs must be >= 0")
        if self.model not in ("deepfm", "widedeep", "dcnv2", "dlrm", "din",
                              "bst"):
            raise ValueError(f"unknown model: {self.model!r}")
        if self.history_max_len < 0:
            raise ValueError("history_max_len must be >= 0")
        if self.index_kind not in ("brute", "ann"):
            raise ValueError(
                f"index_kind must be brute|ann, got {self.index_kind!r}")
        if self.history_max_len > 0:
            if self.num_tasks > 1:
                raise ValueError(
                    "history_max_len > 0 is incompatible with multi-task "
                    "training (the stream carries ONE optional schema "
                    "extension: label2 OR hist_ids/hist_vals)")
            if self.embedding_update == "sparse":
                raise ValueError(
                    "history_max_len > 0 requires embedding_update=dense "
                    "(the sparse row plan covers feat_ids only, so history "
                    "gradients would be dropped)")
            if self.device_dataset:
                raise ValueError(
                    "history_max_len > 0 is incompatible with "
                    "device_dataset (history batches run the eager host "
                    "pipeline)")
            if self.pipe_mode == 1:
                raise ValueError(
                    "history_max_len > 0 requires file mode (pipe_mode=0); "
                    "the streaming pipeline does not decode the history "
                    "pair yet")
        names = self.task_names
        if not names:
            raise ValueError("tasks must name at least one task")
        if len(names) != len(set(names)):
            raise ValueError(f"task names must be unique, got {self.tasks!r}")
        if len(names) > 2:
            raise ValueError(
                "at most 2 tasks are supported (the input contract carries "
                f"label + label2), got {self.tasks!r}")
        if self.multitask not in ("shared_bottom", "mmoe", "esmm"):
            raise ValueError(
                f"multitask must be shared_bottom|mmoe|esmm, got "
                f"{self.multitask!r}")
        if self.mmoe_experts < 1:
            raise ValueError("mmoe_experts must be >= 1")
        try:
            weights = self.task_weight_values
        except ValueError as exc:
            raise ValueError(
                f"task_weights must be a comma list of floats, got "
                f"{self.task_weights!r}") from exc
        if len(weights) != len(names):
            raise ValueError(
                f"task_weights has {len(weights)} entries for "
                f"{len(names)} tasks ({self.tasks!r})")
        if any(w < 0 for w in weights):
            raise ValueError(
                f"task_weights must be >= 0, got {self.task_weights!r}")
        if self.optimizer.lower() not in ("adam", "adagrad", "momentum", "ftrl", "sgd"):
            raise ValueError(f"unknown optimizer: {self.optimizer!r}")
        if self.loss_type not in ("log_loss", "square_loss"):
            raise ValueError(f"unknown loss_type: {self.loss_type!r}")
        if self.embedding_lookup not in ("masked_psum", "allgather_table"):
            raise ValueError(
                f"unknown embedding_lookup: {self.embedding_lookup!r}")
        if self.feature_size <= 0 or self.field_size <= 0 or self.embedding_size <= 0:
            raise ValueError("feature_size/field_size/embedding_size must be positive")
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if self.mesh_model < 1:
            raise ValueError("mesh_model must be >= 1")
        if self.steps_per_loop < 1:
            raise ValueError("steps_per_loop must be >= 1")
        if self.staging_buffers not in (1, 2):
            raise ValueError(
                f"staging_buffers must be 1 or 2, got {self.staging_buffers}")
        if self.grad_accum_steps < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        if self.grad_accum_steps > 1:
            if self.steps_per_loop % self.grad_accum_steps != 0:
                raise ValueError(
                    f"grad_accum_steps={self.grad_accum_steps} must divide "
                    f"steps_per_loop={self.steps_per_loop} (each dispatch "
                    "covers a whole number of accumulation groups)")
            if self.device_dataset:
                raise ValueError(
                    "grad_accum_steps > 1 is not supported with "
                    "device_dataset (the on-device gather path applies the "
                    "optimizer per batch)")
            if self.embedding_tiering != "off":
                raise ValueError(
                    "grad_accum_steps > 1 is not supported with "
                    "embedding_tiering (the hot/cold planner transacts one "
                    "batch per optimizer step)")
        if self.on_bad_record not in ("raise", "skip"):
            raise ValueError(
                f"on_bad_record must be 'raise' or 'skip', "
                f"got {self.on_bad_record!r}")
        if self.max_bad_records < 0:
            raise ValueError("max_bad_records must be >= 0")
        if self.input_workers < 0:
            raise ValueError("input_workers must be >= 0")
        if self.io_retries < 1:
            raise ValueError("io_retries must be >= 1")
        if self.io_retry_backoff_secs < 0 or self.io_retry_deadline_secs < 0:
            raise ValueError("io retry backoff/deadline must be >= 0")
        if self.max_save_failures < 0:
            raise ValueError("max_save_failures must be >= 0")
        if self.on_nonfinite not in ("abort", "skip", "rollback"):
            raise ValueError(
                f"on_nonfinite must be abort|skip|rollback, got "
                f"{self.on_nonfinite!r}")
        if self.max_rollbacks < 0:
            raise ValueError("max_rollbacks must be >= 0")
        if self.dispatch_timeout_s < 0:
            raise ValueError("dispatch_timeout_s must be >= 0")
        if self.loss_spike_zscore < 0:
            raise ValueError("loss_spike_zscore must be >= 0")
        if self.publish_every_steps < 0 or self.publish_every_secs < 0:
            raise ValueError("publish_every_steps/secs must be >= 0")
        if self.publish_timeout_s < 0:
            raise ValueError("publish_timeout_s must be >= 0")
        if self.online_eval_window_steps < 0:
            raise ValueError("online_eval_window_steps must be >= 0")
        if self.stream_poll_secs <= 0:
            raise ValueError("stream_poll_secs must be > 0")
        if self.stream_idle_timeout_secs < 0:
            raise ValueError("stream_idle_timeout_secs must be >= 0")
        if self.online_mode and self.pipe_mode != 1:
            raise ValueError(
                "online_mode requires pipe_mode=1 (the unbounded stream "
                "source is a streaming-mode producer)")
        if self.online_mode and self.num_epochs != 1:
            raise ValueError(
                "online_mode streams each shard once; num_epochs must be 1")
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_max_delay_ms < 0:
            raise ValueError("serve_max_delay_ms must be >= 0")
        if self.serve_queue_rows < 0:
            raise ValueError("serve_queue_rows must be >= 0 (0 = auto)")
        if self.serve_queue_rows and self.serve_queue_rows < self.serve_max_batch:
            raise ValueError(
                "serve_queue_rows must hold at least one serve_max_batch")
        if self.serve_timeout_s < 0:
            raise ValueError("serve_timeout_s must be >= 0")
        if self.serve_inflight < 1:
            raise ValueError(
                "serve_inflight must be >= 1 (1 = strict flush-then-refill)")
        if not 0 <= self.serve_small_rows <= self.serve_max_batch:
            raise ValueError(
                "serve_small_rows must be in 0..serve_max_batch "
                f"(got {self.serve_small_rows} vs "
                f"serve_max_batch={self.serve_max_batch})")
        if self.serve_cache_rows < 0:
            raise ValueError("serve_cache_rows must be >= 0 (0 disables)")
        if self.serve_cache_ttl_s < 0:
            raise ValueError("serve_cache_ttl_s must be >= 0 (0 = no TTL)")
        if self.serve_cache_user_rows < 0:
            raise ValueError(
                "serve_cache_user_rows must be >= 0 (0 disables)")
        if self.serve_slo_ms < 0:
            raise ValueError("serve_slo_ms must be >= 0 (0 disables)")
        if self.serve_shed_watermark < 0:
            raise ValueError(
                "serve_shed_watermark must be >= 0 (0 = half the queue)")
        if self.serve_hedge_ms < 0:
            raise ValueError("serve_hedge_ms must be >= 0 (0 disables)")
        if self.degrade_retrieve_k < 0:
            raise ValueError(
                "degrade_retrieve_k must be >= 0 (0 disables the ladder)")
        if self.experiment_mode not in ("off", "shadow", "canary", "ab"):
            raise ValueError(
                f"experiment_mode must be off|shadow|canary|ab, got "
                f"{self.experiment_mode!r}")
        if not 0 <= self.experiment_permille <= 1000:
            raise ValueError(
                f"experiment_permille must be in 0..1000, got "
                f"{self.experiment_permille}")
        if self.experiment_shadow_slo_ms < 0:
            raise ValueError(
                "experiment_shadow_slo_ms must be >= 0 (0 disables)")
        if self.experiment_gate_windows < 1:
            raise ValueError(
                f"experiment_gate_windows must be >= 1, got "
                f"{self.experiment_gate_windows}")
        if self.experiment_min_samples < 1:
            raise ValueError(
                f"experiment_min_samples must be >= 1, got "
                f"{self.experiment_min_samples}")
        if self.experiment_max_p99_ratio <= 0:
            raise ValueError(
                f"experiment_max_p99_ratio must be > 0, got "
                f"{self.experiment_max_p99_ratio}")
        if self.experiment_max_p99_ms < 0:
            raise ValueError(
                "experiment_max_p99_ms must be >= 0 (0 disables)")
        if self.experiment_max_nonfinite < 0:
            raise ValueError(
                f"experiment_max_nonfinite must be >= 0, got "
                f"{self.experiment_max_nonfinite}")
        if self.experiment_max_calibration_err < 0:
            raise ValueError(
                f"experiment_max_calibration_err must be >= 0, got "
                f"{self.experiment_max_calibration_err}")
        if self.experiment_max_candidate_age_s < 0:
            raise ValueError(
                "experiment_max_candidate_age_s must be >= 0 (0 disables)")
        bucket_sizes = self.serve_bucket_sizes
        if any(b < 1 for b in bucket_sizes):
            raise ValueError(
                f"serve_buckets must be positive ints, got {self.serve_buckets!r}")
        if bucket_sizes and max(bucket_sizes) > self.serve_max_batch:
            raise ValueError(
                f"serve_buckets {self.serve_buckets!r} exceeds "
                f"serve_max_batch={self.serve_max_batch}")
        if self.embedding_update not in ("dense", "sparse"):
            raise ValueError(
                f"embedding_update must be dense|sparse, got "
                f"{self.embedding_update!r}")
        if self.embedding_update == "sparse":
            if self.optimizer.lower() != "adam":
                raise ValueError(
                    "embedding_update=sparse implements the lazy/timestamped "
                    "row update for Adam only; use --optimizer Adam or "
                    "--embedding_update dense")
            if self.mesh_model > 1 and self.embedding_shard != "rows":
                raise ValueError(
                    "embedding_update=sparse under mesh_model>1 needs the "
                    "row-exchange plane: set --embedding_shard rows (or "
                    "--embedding_update dense)")
        try:
            buckets = self.embedding_bucket_sizes
        except ValueError as exc:
            raise ValueError(
                f"embedding_buckets must be a comma list of positive ints, "
                f"got {self.embedding_buckets!r}") from exc
        if any(b < 1 for b in buckets):
            raise ValueError(
                f"embedding_buckets must be positive ints, got "
                f"{self.embedding_buckets!r}")
        if buckets and self.mesh_model > 1:
            if self.embedding_shard != "rows":
                raise ValueError(
                    "hash-bucketed multi-table embeddings (embedding_"
                    "buckets) row-shard only via --embedding_shard rows; "
                    "otherwise mesh_model must be 1")
            bad = [b for b in buckets if b % self.mesh_model]
            if bad:
                raise ValueError(
                    f"embedding_shard=rows needs every bucket count "
                    f"divisible by mesh_model={self.mesh_model}; "
                    f"got {bad}")
        if self.embedding_assign not in ("hash", "field"):
            raise ValueError(
                f"embedding_assign must be hash|field, got "
                f"{self.embedding_assign!r}")
        if self.embedding_tiering not in ("off", "hot_cold"):
            raise ValueError(
                f"embedding_tiering must be off|hot_cold, got "
                f"{self.embedding_tiering!r}")
        if self.embedding_cold_dtype not in ("float32", "int8", "fp8_e4m3"):
            raise ValueError(
                f"embedding_cold_dtype must be float32|int8|fp8_e4m3, got "
                f"{self.embedding_cold_dtype!r}")
        if self.embedding_kernels not in ("auto", "pallas", "xla", "off"):
            raise ValueError(
                f"embedding_kernels must be auto|pallas|xla|off, got "
                f"{self.embedding_kernels!r}")
        if self.embedding_shard not in ("off", "rows"):
            raise ValueError(
                f"embedding_shard must be off|rows, got "
                f"{self.embedding_shard!r}")
        if self.embedding_shard == "rows":
            if self.embedding_update != "sparse":
                raise ValueError(
                    "embedding_shard=rows rides the sparse row plane; set "
                    "--embedding_update sparse")
            if self.embedding_tiering != "off":
                raise ValueError(
                    "embedding_shard=rows and embedding_tiering are "
                    "mutually exclusive (pick HBM capacity from more chips "
                    "OR from the host cold store — TUNING §2.11)")
            if self.grad_accum_steps > 1:
                raise ValueError(
                    "embedding_shard=rows does not compose with "
                    "grad_accum_steps > 1 yet (the merged-plan accumulation "
                    "path is single-device)")
            if self.device_dataset:
                raise ValueError(
                    "embedding_shard=rows is not supported with "
                    "device_dataset (the on-device gather feed is "
                    "single-device)")
        if self.embedding_tiering == "hot_cold":
            if self.embedding_update != "sparse":
                raise ValueError(
                    "embedding_tiering=hot_cold requires "
                    "embedding_update=sparse (the hot cache only holds rows "
                    "the sparse update touches)")
            if buckets:
                raise ValueError(
                    "embedding_tiering=hot_cold supports the monolithic "
                    "table layout only (unset embedding_buckets)")
            if self.embedding_hot_rows < 1:
                raise ValueError(
                    "embedding_tiering=hot_cold needs embedding_hot_rows "
                    ">= 1 (hot-cache capacity)")
            if self.embedding_hot_rows >= self.feature_size:
                raise ValueError(
                    "embedding_hot_rows >= feature_size: the whole table "
                    "fits in HBM — turn tiering off")
            if self.device_dataset:
                raise ValueError(
                    "embedding_tiering=hot_cold and device_dataset are "
                    "mutually exclusive (tiering owns the staged feed)")
            if self.on_nonfinite == "rollback":
                raise ValueError(
                    "embedding_tiering=hot_cold does not support "
                    "on_nonfinite=rollback (checkpoints capture only the "
                    "hot tier); use abort or skip")
            if self.online_mode:
                raise ValueError(
                    "embedding_tiering=hot_cold does not support "
                    "online_mode yet (published artifacts would hold only "
                    "the hot tier)")
        if self.decoded_cache not in ("off", "ram", "disk"):
            raise ValueError(
                f"decoded_cache must be off|ram|disk, got "
                f"{self.decoded_cache!r}")
        if not 0.0 < self.device_dataset_hbm_fraction <= 1.0:
            raise ValueError(
                "device_dataset_hbm_fraction must be in (0, 1]")
        if self.device_dataset and self.decoded_cache == "off":
            raise ValueError(
                "device_dataset requires decoded_cache=ram|disk (the device "
                "upload reads the cached columns)")

    # ---- derived views ------------------------------------------------
    @property
    def deep_layer_sizes(self) -> List[int]:
        return [int(x) for x in self.deep_layers.split(",") if x.strip()]

    @property
    def dropout_rates(self) -> List[float]:
        return [float(x) for x in self.dropout.split(",") if x.strip()]

    @property
    def task_names(self) -> List[str]:
        return [t.strip() for t in self.tasks.split(",") if t.strip()]

    @property
    def num_tasks(self) -> int:
        return len(self.task_names)

    @property
    def task_weight_values(self) -> List[float]:
        vals = [float(x) for x in self.task_weights.split(",") if x.strip()]
        if not vals:
            return [1.0] * self.num_tasks
        return vals

    @property
    def serve_bucket_sizes(self) -> List[int]:
        return [int(x) for x in self.serve_buckets.split(",") if x.strip()]

    @property
    def embedding_bucket_sizes(self) -> List[int]:
        return [int(x) for x in self.embedding_buckets.split(",") if x.strip()]

    @property
    def channel_names(self) -> List[str]:
        if not self.channels:
            return []
        val = self.channels
        if isinstance(val, str):
            try:
                parsed = json.loads(val)
            except json.JSONDecodeError:
                parsed = [c for c in val.split(",") if c]
            return list(parsed)
        return list(val)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Config":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)


def _add_bool_arg(p: argparse.ArgumentParser, name: str, default: bool, help_: str) -> None:
    p.add_argument(f"--{name}", type=lambda s: s.lower() in ("1", "true", "yes"),
                   default=default, help=help_)


def build_arg_parser(defaults: Optional[Config] = None) -> argparse.ArgumentParser:
    """argparse mirror of the dataclass; hyperparameter-dict→argv compatible.

    The SageMaker launcher passed hyperparameters as ``--key value`` argv
    (reference ``deepfm-sagemaker-ps-cpu.ipynb:89-95``); this parser accepts
    the same shape.
    """
    d = defaults or Config()
    p = argparse.ArgumentParser("deepfm_tpu", description="TPU-native DeepFM trainer")
    for f in dataclasses.fields(Config):
        default = getattr(d, f.name)
        if f.type == "bool" or isinstance(default, bool):
            _add_bool_arg(p, f.name, default, f"(default: {default})")
        elif isinstance(default, int):
            p.add_argument(f"--{f.name}", type=int, default=default)
        elif isinstance(default, float):
            p.add_argument(f"--{f.name}", type=float, default=default)
        else:
            p.add_argument(f"--{f.name}", type=str, default=default)
    return p


def parse_args(argv: Optional[Sequence[str]] = None) -> Config:
    # Environment defaults mirroring the SageMaker env contract.
    env = Config(
        channels=os.environ.get("SM_CHANNELS", ""),
        data_dir=os.environ.get("SM_CHANNEL_TRAINING", ""),
        val_data_dir=os.environ.get("SM_CHANNEL_EVAL", ""),
        model_dir=os.environ.get("DEEPFM_MODEL_DIR", ""),
        num_processes=len(_env_json("SM_HOSTS", [None])) or 1,
    )
    ns = build_arg_parser(env).parse_args(argv)
    return Config.from_dict(vars(ns))
