"""Retry/backoff primitives for the I/O stack.

The reference inherited fault tolerance from managed infrastructure: TF's
record readers retry transient S3 hiccups internally and SageMaker restarts
failed jobs. Our TPU-native stack owns every byte of the input path, so the
equivalent policy lives here: bounded attempts, exponential backoff with
full jitter (the AWS-recommended shape — decorrelates retry storms across a
pod's worker fleet), a retryable-exception classifier, and an optional
per-op deadline.

Everything time-related is injectable (``sleep``, ``clock``, jitter seed) so
fault-injection tests run in milliseconds with zero real sleeps.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Optional

# Non-transient OSError subclasses: retrying a missing file or a permission
# wall only delays the real error. Everything else in the OSError family
# (connection resets, timeouts, EIO from a flaky mount) is presumed
# transient — the object-store failure mode this module exists for.
_FATAL_OS_ERRORS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
)

# tf.errors.OpError subclasses that are NOT worth retrying, matched by class
# name so TF never has to be imported to classify.
_FATAL_TF_ERRORS = frozenset({
    "NotFoundError",
    "PermissionDeniedError",
    "InvalidArgumentError",
    "UnimplementedError",
    "FailedPreconditionError",
})


def default_is_retryable(exc: BaseException) -> bool:
    """Classify an exception as transient (retry) or permanent (raise).

    ``tf.io.gfile`` raises ``tf.errors.OpError`` subclasses — which are NOT
    ``OSError``s — for remote-path failures, so classification walks the MRO
    by class name rather than importing TensorFlow.
    """
    if isinstance(exc, _FATAL_OS_ERRORS):
        return False
    if isinstance(exc, OSError):  # IOError/ConnectionError/TimeoutError...
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ == "OpError" and "tensorflow" in (
                getattr(klass, "__module__", "") or ""):
            return type(exc).__name__ not in _FATAL_TF_ERRORS
    return False


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff and full jitter.

    ``max_attempts`` counts total tries (1 = no retry). Delay before retry
    ``i`` (0-based) is uniform in ``[0, min(max_delay, base_delay * 2**i)]``.
    ``deadline`` (seconds, measured on ``clock``) bounds the whole op: once
    exceeded no further attempt is made. ``sleep``/``clock`` are injectable
    so tests drive backoff with a fake clock and zero real sleeping.
    """

    max_attempts: int = 4
    base_delay: float = 0.1
    max_delay: float = 5.0
    deadline: Optional[float] = None
    is_retryable: Callable[[BaseException], bool] = default_is_retryable
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    jitter_seed: Optional[int] = None

    def backoff_delay(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter delay before retry number ``attempt`` (0-based)."""
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        return rng.uniform(0.0, max(cap, 0.0))

    def call(self, fn: Callable[..., Any], *args: Any, op_name: str = "",
             on_retry: Optional[Callable[[BaseException, int], None]] = None,
             **kwargs: Any) -> Any:
        """Run ``fn(*args, **kwargs)``, retrying transient failures.

        ``on_retry(exc, attempt)`` fires before each backoff sleep (attempt
        is 1-based: the number of the attempt that just failed) — the hook
        the pipeline uses to aggregate retry counts into ``DataHealth``.
        """
        rng = random.Random(self.jitter_seed)
        start = self.clock()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                attempt += 1
                if not self.is_retryable(e):
                    raise
                out_of_budget = attempt >= max(self.max_attempts, 1)
                past_deadline = (self.deadline is not None
                                 and self.clock() - start >= self.deadline)
                if out_of_budget or past_deadline:
                    reason = ("deadline" if past_deadline else
                              f"{attempt} attempts")
                    e.args = ((f"{op_name or 'I/O op'} failed after "
                               f"{reason}: {e}"),)
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)
                self.sleep(self.backoff_delay(attempt - 1, rng))

    def with_(self, **kw: Any) -> "RetryPolicy":
        return dataclasses.replace(self, **kw)


def retrying(policy: Optional[RetryPolicy] = None, *, op_name: str = ""):
    """Decorator form of ``RetryPolicy.call``."""
    pol = policy or RetryPolicy()

    def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return pol.call(fn, *args, op_name=op_name or fn.__name__,
                            **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco


def policy_from_config(cfg: Any) -> RetryPolicy:
    """Build the I/O retry policy from Config knobs (see config.py)."""
    return RetryPolicy(
        max_attempts=max(int(getattr(cfg, "io_retries", 4)), 1),
        base_delay=float(getattr(cfg, "io_retry_backoff_secs", 0.1)),
        deadline=(float(cfg.io_retry_deadline_secs)
                  if getattr(cfg, "io_retry_deadline_secs", 0) else None),
    )
