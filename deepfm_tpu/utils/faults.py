"""Deterministic fault injection for the I/O and checkpoint stack.

The reference validated fault tolerance by killing SageMaker instances; our
equivalent is scripted and reproducible: :class:`FlakyFS` installs itself as
the ``fileio`` fault injector (see ``fileio.set_fault_injector``) and raises
``IOError`` at exact call counts and byte offsets — every planned fault
fires exactly once, so two runs with the same plan see the identical fault
sequence. Faults are injected INSIDE the retry loop, which is the point:
the healing machinery (``RetryPolicy`` backoff, ``ResilientStream``
reopen-and-seek, checkpoint save deferral) is what gets exercised, not
bypassed.

Used by the ``faults``-marked tests and ``scripts/fault_drill.py``. No
sleeps here — pair with a zero-delay ``RetryPolicy`` for millisecond tests.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import random
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..data import fileio


class InjectedFault(IOError):
    """Marker subclass so tests can tell injected faults from real ones.
    An IOError, so the default retryable classification applies."""


# -- numerical-fault seam (NaN batches) ---------------------------------
#
# One-shot registry consumed by the train task: a test (or drill) arms a
# plan with set_nan_plan(); _task_train takes it and wraps its pipeline in
# a BatchPoisoner. Registry + wrapper live here (not in the data layer)
# because poisoned batches are a FAULT, scripted and deterministic like
# every other plan in this module — production pipelines never import it.

_nan_plan_lock = threading.Lock()
_nan_plan: Optional[Dict] = None


def set_nan_plan(batches: Iterable[int], *, value: float = float("nan"),
                 key: str = "feat_vals") -> None:
    """Arm a one-shot plan: poison these 0-based batch indices of the NEXT
    pipeline the train task builds (taken once, then cleared)."""
    global _nan_plan
    with _nan_plan_lock:
        _nan_plan = dict(batches=tuple(int(b) for b in batches),
                         value=float(value), key=str(key))


def take_nan_plan() -> Optional[Dict]:
    """Consume the armed plan (None when nothing is armed)."""
    global _nan_plan
    with _nan_plan_lock:
        plan, _nan_plan = _nan_plan, None
        return plan


class BatchPoisoner:
    """Pipeline wrapper that overwrites ``key`` of the planned batch
    indices with ``value`` (NaN by default).

    Deliberately exposes ONLY ``__iter__`` and ``health`` — hiding
    ``iter_superbatches``/``decoded_cache`` forces the generic staged path
    (device-resident and zero-copy feeds bypass per-batch host hooks, so a
    poisoned run always goes through the one code path where the poison is
    visible). Batch indices count per wrapper lifetime, across epochs of
    the wrapped pipeline."""

    def __init__(self, pipeline, *, batches: Tuple[int, ...],
                 value: float = float("nan"), key: str = "feat_vals"):
        self._pipeline = pipeline
        self._batches = frozenset(int(b) for b in batches)
        self._value = value
        self._key = key
        self.poisoned = 0

    @property
    def health(self):
        return getattr(self._pipeline, "health", None)

    def __iter__(self):
        for i, batch in enumerate(self._pipeline):
            if i in self._batches:
                batch = dict(batch)
                arr = batch[self._key].copy()
                arr[...] = self._value
                batch[self._key] = arr
                self.poisoned += 1
            yield batch


# -- publish crash seam --------------------------------------------------
#
# One-shot registry consumed by train/publish.py: a test arms a crash at a
# named stage of the publish sequence ("before_rename",
# "after_rename_before_latest"); the publisher raises InjectedFault at that
# exact point, simulating a process death mid-publish. The atomicity tests
# then assert the LATEST pointer still resolves to the previous good
# artifact and nothing half-written is visible.

_publish_crash_lock = threading.Lock()
_publish_crash: Optional[str] = None


def set_publish_crash(stage: str) -> None:
    """Arm a one-shot crash at publish stage ``stage`` (taken once)."""
    global _publish_crash
    with _publish_crash_lock:
        _publish_crash = str(stage)


def check_publish_crash(stage: str) -> None:
    """Called by the publisher at each stage; raises iff armed for it."""
    global _publish_crash
    with _publish_crash_lock:
        if _publish_crash != stage:
            return
        _publish_crash = None
    raise InjectedFault(f"injected publish crash at stage {stage!r}")


# Hot/cold tiered-embedding seam (data/hot_cold.py): arm N one-shot cold-
# store fetch failures; the runtime's fetch retry must heal them without
# corrupting the hot cache or the training trajectory (tests/test_hot_cold).

_cold_fetch_lock = threading.Lock()
_cold_fetch_fails: int = 0


def set_cold_fetch_plan(fail_count: int) -> None:
    """Arm the next ``fail_count`` cold-store fetches to raise (one fault
    per fetch call; the runtime's retry consumes them)."""
    global _cold_fetch_fails
    with _cold_fetch_lock:
        _cold_fetch_fails = int(fail_count)


def check_cold_fetch() -> None:
    """Called by the cold store at each fetch; raises while armed."""
    global _cold_fetch_fails
    with _cold_fetch_lock:
        if _cold_fetch_fails <= 0:
            return
        _cold_fetch_fails -= 1
    raise InjectedFault("injected cold-store fetch failure")


# Serving-executor latency seam (serve/engine.py): arm the next ``calls``
# flushes to each sleep ``delay_s`` before predict. Count-based (not
# wall-clock) so the overload drill's "slow period" ends after a DETERMINED
# amount of work regardless of host speed — the recovery half of the
# degradation-ladder assertion cannot be starved by a slow machine.

_exec_slow_lock = threading.Lock()
_exec_slow_delay_s: float = 0.0
_exec_slow_calls: int = 0


def set_executor_slow(delay_s: float, calls: int) -> None:
    """Arm the next ``calls`` serving flushes to sleep ``delay_s`` each
    (0 calls disarms)."""
    global _exec_slow_delay_s, _exec_slow_calls
    with _exec_slow_lock:
        _exec_slow_delay_s = float(delay_s)
        _exec_slow_calls = int(calls)


def executor_slow_delay() -> float:
    """Consume one armed slow flush; returns the delay to sleep (0 when
    disarmed). Called by the engine's executor at every flush."""
    global _exec_slow_calls
    with _exec_slow_lock:
        if _exec_slow_calls <= 0:
            return 0.0
        _exec_slow_calls -= 1
        return _exec_slow_delay_s


def executor_slow_remaining() -> int:
    with _exec_slow_lock:
        return _exec_slow_calls


# Env seams for subprocess drills (scripts/online_drill.py,
# scripts/production_drill.py): the train task calls install_env_faults()
# at startup. Two ways in, one mechanism (docs/TUNING.md has the full seam
# table):
#
#   * DEEPFM_TPU_READ_FAULT_EVERY=k — the original single-knob var; still
#     honored (it becomes a read_faults event of the schedule below).
#   * DEEPFM_TPU_CHAOS_SCHEDULE=<json|@path> — a serialized ChaosSchedule;
#     every process-local kind (read faults, publish crash, cold-fetch
#     failures, NaN batches, step-indexed preempt/fault triggers) is armed
#     from the one seeded plan, so a drill configures ALL its chaos through
#     a single bit-exactly replayable object instead of N ad-hoc env vars.
READ_FAULT_ENV = "DEEPFM_TPU_READ_FAULT_EVERY"
CHAOS_ENV = "DEEPFM_TPU_CHAOS_SCHEDULE"
# One-shot arming guard across supervised restarts: a JSON file recording
# which schedule events were already armed in a previous incarnation of the
# process (publish crashes and NaN plans must fire once per drill, not once
# per restart). Unset = re-arm on every process start.
CHAOS_STATE_ENV = "DEEPFM_TPU_CHAOS_STATE"


def install_env_faults() -> Optional["FlakyFS"]:
    import os
    schedule = ChaosSchedule.from_env(os.environ)
    if schedule is None:
        return None
    return schedule.install(state_path=os.environ.get(CHAOS_STATE_ENV) or None)


class FlakyStream(io.RawIOBase):
    """Read-stream wrapper raising scripted faults; otherwise transparent."""

    def __init__(self, fs: "FlakyFS", path: str, inner):
        super().__init__()
        self._fs = fs
        self._path = path
        self._inner = inner
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        if self._fs.hide_seek:
            return False
        try:
            return bool(self._inner.seekable())
        except Exception:
            return hasattr(self._inner, "seek")

    def seek(self, offset: int, whence: int = 0) -> int:
        if self._fs.hide_seek:
            raise io.UnsupportedOperation(
                "seek disabled by FlakyFS(hide_seek=True)")
        pos = self._inner.seek(offset, whence)
        self._pos = pos if pos is not None else offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        self._fs.maybe_fail_read(self._path, self._pos, n)
        chunk = self._inner.read(n)
        if chunk:
            self._pos += len(chunk)
        return chunk

    def close(self) -> None:
        try:
            self._inner.close()
        finally:
            super().close()


class FlakyFS:
    """Deterministic fault plan over the whole fileio layer.

    Parameters script the faults:
      * ``read_fail_every=k``: every k-th ``read()`` call (counted globally
        across all streams, including retry re-reads) raises once. ``k=1``
        makes every read fail — a permanent outage.
      * ``read_fail_offsets``: iterable of ``(path_substring, byte_offset)``;
        the first read on a matching stream that would cover that offset
        raises, once per entry.
      * ``op_failures``: e.g. ``{"glob": 2, "open": 1}`` — the first N calls
        of that fileio op raise, one fault per call.
      * ``save_failures=n``: the first n ``CheckpointManager._do_save``
        calls raise (patched while the context is active; the checkpoint
        module is imported lazily so data-only tests never pull in jax).

    Use as a context manager; counters (``injected_read_faults`` etc.) let
    tests assert DataHealth reported *exactly* the injected fault count.
    """

    def __init__(self, *, read_fail_every: int = 0,
                 read_fail_offsets: Iterable[Tuple[str, int]] = (),
                 op_failures: Optional[Dict[str, int]] = None,
                 save_failures: int = 0,
                 hide_seek: bool = False,
                 error_factory=None):
        self.read_fail_every = int(read_fail_every)
        self._offset_plan = [(str(sub), int(off))
                             for sub, off in read_fail_offsets]
        self._offset_fired = [False] * len(self._offset_plan)
        self._op_remaining = dict(op_failures or {})
        self._save_remaining = int(save_failures)
        self.hide_seek = hide_seek
        self._error = error_factory or (lambda msg: InjectedFault(msg))
        self._lock = threading.Lock()
        self._read_calls = 0
        self.injected_read_faults = 0
        self.injected_op_faults = 0
        self.injected_save_faults = 0
        self._ckpt_patch = None  # (cls, original _do_save) while active

    # -- fileio injector duck-type -------------------------------------
    def on_op(self, op: str, path: str) -> None:
        with self._lock:
            left = self._op_remaining.get(op, 0)
            if left <= 0:
                return
            self._op_remaining[op] = left - 1
            self.injected_op_faults += 1
        raise self._error(f"injected {op} fault on {path}")

    def wrap_stream(self, path: str, stream) -> FlakyStream:
        return FlakyStream(self, path, stream)

    # -- read-fault plan -----------------------------------------------
    def maybe_fail_read(self, path: str, pos: int, n: int) -> None:
        with self._lock:
            self._read_calls += 1
            idx = self._read_calls
            fail = (self.read_fail_every > 0
                    and idx % self.read_fail_every == 0)
            reason = f"read #{idx}"
            if not fail:
                end = pos + (n if n and n > 0 else 1)
                for i, (sub, off) in enumerate(self._offset_plan):
                    if (not self._offset_fired[i] and sub in path
                            and pos <= off < end):
                        self._offset_fired[i] = True
                        fail = True
                        reason = f"offset {off}"
                        break
            if fail:
                self.injected_read_faults += 1
        if fail:
            raise self._error(
                f"injected transient read fault ({reason}) on {path} "
                f"at byte {pos}")

    # -- checkpoint-save plan ------------------------------------------
    def _patch_checkpoint_saves(self) -> None:
        from . import checkpoint as ckpt_lib  # noqa: PLC0415 (lazy: jax/orbax)
        fs = self
        original = ckpt_lib.CheckpointManager._do_save

        def flaky_do_save(mgr_self, step, state, force):
            with fs._lock:
                inject = fs._save_remaining > 0
                if inject:
                    fs._save_remaining -= 1
                    fs.injected_save_faults += 1
            if inject:
                raise fs._error(
                    f"injected checkpoint-save fault at step {step}")
            return original(mgr_self, step, state, force)

        ckpt_lib.CheckpointManager._do_save = flaky_do_save
        self._ckpt_patch = (ckpt_lib.CheckpointManager, original)

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "FlakyFS":
        fileio.set_fault_injector(self)
        if self._save_remaining > 0:
            self._patch_checkpoint_saves()
        return self

    def __exit__(self, *exc) -> None:
        fileio.set_fault_injector(None)
        if self._ckpt_patch is not None:
            cls, original = self._ckpt_patch
            cls._do_save = original
            self._ckpt_patch = None


# -- chaos schedule ------------------------------------------------------
#
# The seams above grew one drill at a time: FlakyFS (fault drill),
# set_publish_crash (publish atomicity tests), set_cold_fetch_plan
# (hot/cold tiering), set_nan_plan (guard tests), and the step-indexed
# DEEPFM_TPU_PREEMPT_* env triggers (preemption drill). Each is armed by a
# different call at a different place, so a whole-system drill had no way
# to say "this exact storm, reproducibly". ChaosSchedule is that one plan:
# a seeded, time-indexed event list that serializes to JSON (bit-exact:
# same seed + params -> byte-identical JSON -> same fingerprint), crosses
# process boundaries via one env var, and arms every existing seam without
# changing any of them.


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault: ``kind`` at ``at_s`` seconds from drill start.

    ``at_s`` is advisory for process-local kinds (they are armed at
    process start and fire at their seam's natural trigger point); it is
    the actual firing time for driver-side kinds (``preempt``), which the
    drill process executes against its own clock.
    """

    at_s: float
    kind: str
    arg: Tuple[Tuple[str, Any], ...] = ()

    def get(self, key: str, default: Any = None) -> Any:
        for k, v in self.arg:
            if k == key:
                return v
        return default

    @staticmethod
    def make(at_s: float, kind: str, **arg: Any) -> "ChaosEvent":
        return ChaosEvent(round(float(at_s), 3), str(kind),
                          tuple(sorted(arg.items())))


class ChaosSchedule:
    """Seeded, time-indexed fault plan unifying every injection seam.

    * ``generate(seed, ...)`` draws event times from ``random.Random(seed)``
      — a pure function of its arguments, so the same call reproduces the
      identical plan (``fingerprint()`` pins that).
    * ``to_json()``/``from_json()`` round-trip the plan canonically
      (sorted keys, fixed float rounding) for logs and env transport.
    * ``install()`` arms every PROCESS_KIND through the existing seams:
      read faults -> a FlakyFS fileio injector; ``publish_crash`` ->
      :func:`set_publish_crash`; ``cold_fetch`` ->
      :func:`set_cold_fetch_plan`; ``nan_batches`` -> :func:`set_nan_plan`;
      ``preempt_after_steps``/``fault_after_steps``/``hold_after_steps`` ->
      the ``DEEPFM_TPU_*`` step-trigger env vars the train task reads
      AFTER :func:`install_env_faults` runs. One-shot kinds are guarded by
      ``state_path`` so a supervised restart does not re-arm them.
    * DRIVER_KINDS (``preempt``: send SIGTERM at ``at_s``) are executed by
      the drill process itself via :meth:`due` — a subprocess cannot
      SIGTERM itself usefully from an env var.
    """

    PROCESS_KINDS = ("read_faults", "publish_crash", "cold_fetch",
                     "nan_batches", "preempt_after_steps",
                     "fault_after_steps", "hold_after_steps")
    # executor_slow is driver-side: the drill process owns the serving
    # engine, so it arms set_executor_slow() itself when the event is due.
    # The challenger_* kinds poison the experimentation plane's candidate
    # model (gated deployment drill): driver-side too, because the drill
    # owns the candidate build — challenger_nan arms set_nan_plan() on the
    # candidate trainer (params go NaN through the real batch-poison seam),
    # challenger_stale freezes the candidate at stale params, and
    # challenger_slow delays only the challenger engine's predicts.
    DRIVER_KINDS = ("preempt", "executor_slow", "challenger_nan",
                    "challenger_stale", "challenger_slow")
    #: kinds that must fire once per drill, not once per process start
    ONESHOT_KINDS = ("publish_crash", "cold_fetch", "nan_batches")
    KINDS = PROCESS_KINDS + DRIVER_KINDS

    def __init__(self, events: Iterable[ChaosEvent], *,
                 seed: Optional[int] = None):
        events = tuple(sorted(events, key=lambda e: e.at_s))
        for ev in events:
            if ev.kind not in self.KINDS:
                raise ValueError(
                    f"unknown chaos kind {ev.kind!r} (know {self.KINDS})")
        self.events = events
        self.seed = seed

    @classmethod
    def generate(cls, seed: int, *, horizon_s: float,
                 read_fault_every: int = 0,
                 publish_crashes: int = 0,
                 publish_crash_stage: str = "before_rename",
                 preemptions: int = 0,
                 cold_fetch_fails: int = 0,
                 nan_batches: int = 0,
                 executor_slow_events: int = 0,
                 executor_slow_ms: float = 0.0,
                 executor_slow_calls: int = 0,
                 challenger_nan_events: int = 0,
                 challenger_nan_batches: int = 3,
                 challenger_stale_events: int = 0,
                 challenger_slow_events: int = 0,
                 challenger_slow_ms: float = 0.0,
                 challenger_slow_calls: int = 0) -> "ChaosSchedule":
        """Draw a plan for a drill of ``horizon_s`` seconds. Event times
        land in the middle 20-80% of the horizon (chaos during steady
        state, not during come-up or drain). stdlib ``random`` on purpose:
        its sequence is pinned by the language spec, so the plan is stable
        across library versions."""
        rng = random.Random(int(seed))
        events: List[ChaosEvent] = []
        if read_fault_every > 0:
            events.append(ChaosEvent.make(
                0.0, "read_faults", every=int(read_fault_every)))
        for _ in range(int(publish_crashes)):
            events.append(ChaosEvent.make(
                rng.uniform(0.2, 0.8) * horizon_s, "publish_crash",
                stage=str(publish_crash_stage)))
        for _ in range(int(preemptions)):
            events.append(ChaosEvent.make(
                rng.uniform(0.2, 0.8) * horizon_s, "preempt"))
        if cold_fetch_fails > 0:
            events.append(ChaosEvent.make(
                0.0, "cold_fetch", fails=int(cold_fetch_fails)))
        if nan_batches > 0:
            batches = sorted(rng.sample(range(2, 50), int(nan_batches)))
            events.append(ChaosEvent.make(
                0.0, "nan_batches", batches=tuple(batches)))
        for _ in range(int(executor_slow_events)):
            # Early in the 20-80% window on purpose: the slow period must
            # finish inside the horizon so the drill can also assert
            # RECOVERY, not just engagement.
            events.append(ChaosEvent.make(
                rng.uniform(0.2, 0.5) * horizon_s, "executor_slow",
                delay_ms=round(float(executor_slow_ms), 3),
                calls=int(executor_slow_calls)))
        # Challenger poisoning (experimentation drill). New draws come
        # AFTER every existing kind's, so schedules generated with only the
        # old parameters stay bit-identical to what they always were.
        for _ in range(int(challenger_nan_events)):
            batches = sorted(rng.sample(range(0, 20),
                                        int(challenger_nan_batches)))
            events.append(ChaosEvent.make(
                rng.uniform(0.2, 0.8) * horizon_s, "challenger_nan",
                batches=tuple(batches)))
        for _ in range(int(challenger_stale_events)):
            events.append(ChaosEvent.make(
                rng.uniform(0.2, 0.8) * horizon_s, "challenger_stale"))
        for _ in range(int(challenger_slow_events)):
            events.append(ChaosEvent.make(
                rng.uniform(0.2, 0.8) * horizon_s, "challenger_slow",
                delay_ms=round(float(challenger_slow_ms), 3),
                calls=int(challenger_slow_calls)))
        return cls(events, seed=int(seed))

    # -- serialization --------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed,
             "events": [{"at_s": ev.at_s, "kind": ev.kind,
                         "arg": dict(ev.arg)} for ev in self.events]},
            sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, text: str) -> "ChaosSchedule":
        spec = json.loads(text)
        events = []
        for ev in spec["events"]:
            arg = {k: (tuple(v) if isinstance(v, list) else v)
                   for k, v in ev.get("arg", {}).items()}
            events.append(ChaosEvent.make(ev["at_s"], ev["kind"], **arg))
        return cls(events, seed=spec.get("seed"))

    @classmethod
    def from_env(cls, environ) -> Optional["ChaosSchedule"]:
        """The one entry point for env-carried chaos: merges the serialized
        schedule (CHAOS_ENV, inline JSON or ``@/path``) with the legacy
        READ_FAULT_ENV knob — the old var keeps working by BECOMING a
        ``read_faults`` event (schedule wins if both specify read faults).
        None when neither var asks for anything."""
        schedule = None
        spec = environ.get(CHAOS_ENV, "")
        if spec:
            if spec.startswith("@"):
                with open(spec[1:], encoding="utf-8") as f:
                    spec = f.read()
            schedule = cls.from_json(spec)
        every = int(environ.get(READ_FAULT_ENV, "0") or 0)
        if every > 0 and (schedule is None
                          or not schedule.events_of("read_faults")):
            events = schedule.events if schedule is not None else ()
            seed = schedule.seed if schedule is not None else None
            schedule = cls(
                events + (ChaosEvent.make(0.0, "read_faults", every=every),),
                seed=seed)
        return schedule

    def fingerprint(self) -> str:
        """Stable hex id of the exact plan (stamped into drill reports; two
        runs with equal fingerprints replayed the identical chaos)."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:16]

    # -- queries --------------------------------------------------------
    def events_of(self, *kinds: str) -> Tuple[ChaosEvent, ...]:
        return tuple(ev for ev in self.events if ev.kind in kinds)

    def due(self, now_s: float, fired: set) -> List[ChaosEvent]:
        """Driver-side pump: DRIVER_KINDS events scheduled at or before
        ``now_s`` not yet in ``fired`` (which this call updates)."""
        out = []
        for i, ev in enumerate(self.events):
            if ev.kind in self.DRIVER_KINDS and i not in fired \
                    and ev.at_s <= now_s:
                fired.add(i)
                out.append(ev)
        return out

    # -- process-local arming -------------------------------------------
    def install(self, state_path: Optional[str] = None) -> Optional[FlakyFS]:
        """Arm every process-local kind through its existing seam.

        Continuous kinds (read faults, step triggers) re-arm on every call
        — a restarted process lives in the same weather. ONESHOT_KINDS arm
        at most once per ``state_path`` (atomically updated JSON list of
        armed event keys), so one scheduled publish crash fires once per
        drill even across supervised restarts."""
        import os
        armed: List[str] = []
        if state_path and os.path.exists(state_path):
            with open(state_path, encoding="utf-8") as f:
                armed = json.load(f)
        newly: List[str] = []
        fs: Optional[FlakyFS] = None
        for i, ev in enumerate(self.events):
            key = f"{i}:{ev.kind}"
            if ev.kind in self.ONESHOT_KINDS and key in armed:
                continue
            if ev.kind == "read_faults":
                fs = FlakyFS(read_fail_every=int(ev.get("every", 0)))
                fileio.set_fault_injector(fs)
            elif ev.kind == "publish_crash":
                set_publish_crash(ev.get("stage", "before_rename"))
                newly.append(key)
            elif ev.kind == "cold_fetch":
                set_cold_fetch_plan(int(ev.get("fails", 0)))
                newly.append(key)
            elif ev.kind == "nan_batches":
                set_nan_plan(ev.get("batches", ()))
                newly.append(key)
            elif ev.kind == "preempt_after_steps":
                os.environ["DEEPFM_TPU_PREEMPT_AFTER_STEPS"] = str(
                    int(ev.get("steps", 0)))
            elif ev.kind == "fault_after_steps":
                os.environ["DEEPFM_TPU_FAULT_AFTER_STEPS"] = str(
                    int(ev.get("steps", 0)))
            elif ev.kind == "hold_after_steps":
                os.environ["DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS"] = str(
                    int(ev.get("steps", 0)))
        if newly and state_path:
            tmp = state_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(armed + newly, f)
            os.replace(tmp, state_path)
        return fs
