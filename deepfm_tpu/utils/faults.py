"""Deterministic fault injection for the I/O and checkpoint stack.

The reference validated fault tolerance by killing SageMaker instances; our
equivalent is scripted and reproducible: :class:`FlakyFS` installs itself as
the ``fileio`` fault injector (see ``fileio.set_fault_injector``) and raises
``IOError`` at exact call counts and byte offsets — every planned fault
fires exactly once, so two runs with the same plan see the identical fault
sequence. Faults are injected INSIDE the retry loop, which is the point:
the healing machinery (``RetryPolicy`` backoff, ``ResilientStream``
reopen-and-seek, checkpoint save deferral) is what gets exercised, not
bypassed.

Used by the ``faults``-marked tests and ``scripts/fault_drill.py``. No
sleeps here — pair with a zero-delay ``RetryPolicy`` for millisecond tests.
"""

from __future__ import annotations

import io
import threading
from typing import Dict, Iterable, Optional, Tuple

from ..data import fileio


class InjectedFault(IOError):
    """Marker subclass so tests can tell injected faults from real ones.
    An IOError, so the default retryable classification applies."""


# -- numerical-fault seam (NaN batches) ---------------------------------
#
# One-shot registry consumed by the train task: a test (or drill) arms a
# plan with set_nan_plan(); _task_train takes it and wraps its pipeline in
# a BatchPoisoner. Registry + wrapper live here (not in the data layer)
# because poisoned batches are a FAULT, scripted and deterministic like
# every other plan in this module — production pipelines never import it.

_nan_plan_lock = threading.Lock()
_nan_plan: Optional[Dict] = None


def set_nan_plan(batches: Iterable[int], *, value: float = float("nan"),
                 key: str = "feat_vals") -> None:
    """Arm a one-shot plan: poison these 0-based batch indices of the NEXT
    pipeline the train task builds (taken once, then cleared)."""
    global _nan_plan
    with _nan_plan_lock:
        _nan_plan = dict(batches=tuple(int(b) for b in batches),
                         value=float(value), key=str(key))


def take_nan_plan() -> Optional[Dict]:
    """Consume the armed plan (None when nothing is armed)."""
    global _nan_plan
    with _nan_plan_lock:
        plan, _nan_plan = _nan_plan, None
        return plan


class BatchPoisoner:
    """Pipeline wrapper that overwrites ``key`` of the planned batch
    indices with ``value`` (NaN by default).

    Deliberately exposes ONLY ``__iter__`` and ``health`` — hiding
    ``iter_superbatches``/``decoded_cache`` forces the generic staged path
    (device-resident and zero-copy feeds bypass per-batch host hooks, so a
    poisoned run always goes through the one code path where the poison is
    visible). Batch indices count per wrapper lifetime, across epochs of
    the wrapped pipeline."""

    def __init__(self, pipeline, *, batches: Tuple[int, ...],
                 value: float = float("nan"), key: str = "feat_vals"):
        self._pipeline = pipeline
        self._batches = frozenset(int(b) for b in batches)
        self._value = value
        self._key = key
        self.poisoned = 0

    @property
    def health(self):
        return getattr(self._pipeline, "health", None)

    def __iter__(self):
        for i, batch in enumerate(self._pipeline):
            if i in self._batches:
                batch = dict(batch)
                arr = batch[self._key].copy()
                arr[...] = self._value
                batch[self._key] = arr
                self.poisoned += 1
            yield batch


# -- publish crash seam --------------------------------------------------
#
# One-shot registry consumed by train/publish.py: a test arms a crash at a
# named stage of the publish sequence ("before_rename",
# "after_rename_before_latest"); the publisher raises InjectedFault at that
# exact point, simulating a process death mid-publish. The atomicity tests
# then assert the LATEST pointer still resolves to the previous good
# artifact and nothing half-written is visible.

_publish_crash_lock = threading.Lock()
_publish_crash: Optional[str] = None


def set_publish_crash(stage: str) -> None:
    """Arm a one-shot crash at publish stage ``stage`` (taken once)."""
    global _publish_crash
    with _publish_crash_lock:
        _publish_crash = str(stage)


def check_publish_crash(stage: str) -> None:
    """Called by the publisher at each stage; raises iff armed for it."""
    global _publish_crash
    with _publish_crash_lock:
        if _publish_crash != stage:
            return
        _publish_crash = None
    raise InjectedFault(f"injected publish crash at stage {stage!r}")


# Hot/cold tiered-embedding seam (data/hot_cold.py): arm N one-shot cold-
# store fetch failures; the runtime's fetch retry must heal them without
# corrupting the hot cache or the training trajectory (tests/test_hot_cold).

_cold_fetch_lock = threading.Lock()
_cold_fetch_fails: int = 0


def set_cold_fetch_plan(fail_count: int) -> None:
    """Arm the next ``fail_count`` cold-store fetches to raise (one fault
    per fetch call; the runtime's retry consumes them)."""
    global _cold_fetch_fails
    with _cold_fetch_lock:
        _cold_fetch_fails = int(fail_count)


def check_cold_fetch() -> None:
    """Called by the cold store at each fetch; raises while armed."""
    global _cold_fetch_fails
    with _cold_fetch_lock:
        if _cold_fetch_fails <= 0:
            return
        _cold_fetch_fails -= 1
    raise InjectedFault("injected cold-store fetch failure")


# Env seam for subprocess drills (scripts/online_drill.py): the train task
# calls install_env_faults() at startup; with DEEPFM_TPU_READ_FAULT_EVERY=k
# set, a process-wide FlakyFS making every k-th read fail once is installed,
# so a *launched* online job heals scripted transient faults — the in-process
# context-manager pattern can't reach a subprocess.
READ_FAULT_ENV = "DEEPFM_TPU_READ_FAULT_EVERY"


def install_env_faults() -> Optional["FlakyFS"]:
    import os
    every = int(os.environ.get(READ_FAULT_ENV, "0") or 0)
    if every <= 0:
        return None
    fs = FlakyFS(read_fail_every=every)
    fileio.set_fault_injector(fs)
    return fs


class FlakyStream(io.RawIOBase):
    """Read-stream wrapper raising scripted faults; otherwise transparent."""

    def __init__(self, fs: "FlakyFS", path: str, inner):
        super().__init__()
        self._fs = fs
        self._path = path
        self._inner = inner
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        if self._fs.hide_seek:
            return False
        try:
            return bool(self._inner.seekable())
        except Exception:
            return hasattr(self._inner, "seek")

    def seek(self, offset: int, whence: int = 0) -> int:
        if self._fs.hide_seek:
            raise io.UnsupportedOperation(
                "seek disabled by FlakyFS(hide_seek=True)")
        pos = self._inner.seek(offset, whence)
        self._pos = pos if pos is not None else offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        self._fs.maybe_fail_read(self._path, self._pos, n)
        chunk = self._inner.read(n)
        if chunk:
            self._pos += len(chunk)
        return chunk

    def close(self) -> None:
        try:
            self._inner.close()
        finally:
            super().close()


class FlakyFS:
    """Deterministic fault plan over the whole fileio layer.

    Parameters script the faults:
      * ``read_fail_every=k``: every k-th ``read()`` call (counted globally
        across all streams, including retry re-reads) raises once. ``k=1``
        makes every read fail — a permanent outage.
      * ``read_fail_offsets``: iterable of ``(path_substring, byte_offset)``;
        the first read on a matching stream that would cover that offset
        raises, once per entry.
      * ``op_failures``: e.g. ``{"glob": 2, "open": 1}`` — the first N calls
        of that fileio op raise, one fault per call.
      * ``save_failures=n``: the first n ``CheckpointManager._do_save``
        calls raise (patched while the context is active; the checkpoint
        module is imported lazily so data-only tests never pull in jax).

    Use as a context manager; counters (``injected_read_faults`` etc.) let
    tests assert DataHealth reported *exactly* the injected fault count.
    """

    def __init__(self, *, read_fail_every: int = 0,
                 read_fail_offsets: Iterable[Tuple[str, int]] = (),
                 op_failures: Optional[Dict[str, int]] = None,
                 save_failures: int = 0,
                 hide_seek: bool = False,
                 error_factory=None):
        self.read_fail_every = int(read_fail_every)
        self._offset_plan = [(str(sub), int(off))
                             for sub, off in read_fail_offsets]
        self._offset_fired = [False] * len(self._offset_plan)
        self._op_remaining = dict(op_failures or {})
        self._save_remaining = int(save_failures)
        self.hide_seek = hide_seek
        self._error = error_factory or (lambda msg: InjectedFault(msg))
        self._lock = threading.Lock()
        self._read_calls = 0
        self.injected_read_faults = 0
        self.injected_op_faults = 0
        self.injected_save_faults = 0
        self._ckpt_patch = None  # (cls, original _do_save) while active

    # -- fileio injector duck-type -------------------------------------
    def on_op(self, op: str, path: str) -> None:
        with self._lock:
            left = self._op_remaining.get(op, 0)
            if left <= 0:
                return
            self._op_remaining[op] = left - 1
            self.injected_op_faults += 1
        raise self._error(f"injected {op} fault on {path}")

    def wrap_stream(self, path: str, stream) -> FlakyStream:
        return FlakyStream(self, path, stream)

    # -- read-fault plan -----------------------------------------------
    def maybe_fail_read(self, path: str, pos: int, n: int) -> None:
        with self._lock:
            self._read_calls += 1
            idx = self._read_calls
            fail = (self.read_fail_every > 0
                    and idx % self.read_fail_every == 0)
            reason = f"read #{idx}"
            if not fail:
                end = pos + (n if n and n > 0 else 1)
                for i, (sub, off) in enumerate(self._offset_plan):
                    if (not self._offset_fired[i] and sub in path
                            and pos <= off < end):
                        self._offset_fired[i] = True
                        fail = True
                        reason = f"offset {off}"
                        break
            if fail:
                self.injected_read_faults += 1
        if fail:
            raise self._error(
                f"injected transient read fault ({reason}) on {path} "
                f"at byte {pos}")

    # -- checkpoint-save plan ------------------------------------------
    def _patch_checkpoint_saves(self) -> None:
        from . import checkpoint as ckpt_lib  # noqa: PLC0415 (lazy: jax/orbax)
        fs = self
        original = ckpt_lib.CheckpointManager._do_save

        def flaky_do_save(mgr_self, step, state, force):
            with fs._lock:
                inject = fs._save_remaining > 0
                if inject:
                    fs._save_remaining -= 1
                    fs.injected_save_faults += 1
            if inject:
                raise fs._error(
                    f"injected checkpoint-save fault at step {step}")
            return original(mgr_self, step, state, force)

        ckpt_lib.CheckpointManager._do_save = flaky_do_save
        self._ckpt_patch = (ckpt_lib.CheckpointManager, original)

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "FlakyFS":
        fileio.set_fault_injector(self)
        if self._save_remaining > 0:
            self._patch_checkpoint_saves()
        return self

    def __exit__(self, *exc) -> None:
        fileio.set_fault_injector(None)
        if self._ckpt_patch is not None:
            cls, original = self._ckpt_patch
            cls._do_save = original
            self._ckpt_patch = None
