"""Preemption handling: signal listener + graceful-exit contract.

The reference's preemption story was entirely implicit: SageMaker spot
interruptions killed the container and the relaunched job resumed from the
latest checkpoint in the shared ``model_dir`` (``1-ps-cpu/...py:434-435``),
losing up to ``save_checkpoints_steps`` of work. Here the trainer *notices*
the preemption: a :class:`PreemptionListener` converts SIGTERM/SIGINT into a
flag that the fit loop polls once per dispatch; on trigger the in-flight
dispatch finishes, a checkpoint + resume-meta sidecar are force-saved (so
mid-epoch resume is replay-exact), and the process exits with
:data:`EXIT_PREEMPTED` — a distinct code an orchestrator
(``scripts/supervise.py``) uses to tell "preempted, restart me" from
"crashed, give up".

The listener also exposes :meth:`PreemptionListener.trigger` — an injectable
trigger so tests and drills exercise the exact production code path without
delivering real signals.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional, Tuple

from . import logging as ulog

# Exit-code contract (documented in README "Preemption & self-healing"):
#   42 — preempted: a checkpoint + resume meta were saved; restart to resume.
#   43 — watchdog abort: no dispatch progress within --dispatch_timeout_s;
#        a restart MAY clear a transient stall (hung peer, wedged worker).
# Anything else is an ordinary crash an orchestrator should not blindly retry.
EXIT_PREEMPTED = 42
EXIT_WATCHDOG = 43
RESTARTABLE_EXIT_CODES = frozenset({EXIT_PREEMPTED, EXIT_WATCHDOG})


class Preempted(Exception):
    """Raised by the train task after the preemption checkpoint landed.

    Carries the global step of the saved checkpoint; the launcher maps this
    to :data:`EXIT_PREEMPTED`.
    """

    def __init__(self, step: int, reason: str = ""):
        msg = f"preempted at step {step}"
        if reason:
            msg += f" ({reason})"
        super().__init__(msg)
        self.step = int(step)
        self.reason = reason


class PreemptionListener:
    """SIGTERM/SIGINT -> flag, polled by the training loop.

    Signal handlers can only be installed from the main thread; elsewhere
    (e.g. a test driving ``tasks.run`` on a worker thread) the listener
    degrades to trigger-only mode — :meth:`trigger` remains the injectable
    test seam either way. ``install``/``uninstall`` save and restore the
    prior handlers, so nesting inside pytest or another framework's handler
    stack is safe.
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: dict = {}
        self._installed = False
        self.reason = ""

    # -- trigger paths --------------------------------------------------
    def _on_signal(self, signum, frame) -> None:  # noqa: ARG002
        # Async-signal context: set the flag and nothing else; the training
        # loop does the logging/saving at the next dispatch boundary.
        self.reason = f"signal {signum}"
        self._event.set()

    def trigger(self, reason: str = "injected") -> None:
        """Injectable trigger: same flag the signal handler sets."""
        self.reason = reason
        self._event.set()

    def triggered(self) -> bool:
        return self._event.is_set()

    def clear(self) -> None:
        """Reset the flag (tests reuse one process across drill phases)."""
        self.reason = ""
        self._event.clear()

    # -- handler lifecycle ----------------------------------------------
    def install(self) -> "PreemptionListener":
        if self._installed:
            return self
        self._installed = True
        if threading.current_thread() is not threading.main_thread():
            ulog.info("preemption listener on a non-main thread: "
                      "trigger-only mode (no signal handlers)")
            return self
        for sig in self._signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # non-main thread race / exotic sig
                pass
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()

    def __enter__(self) -> "PreemptionListener":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_LISTENER: Optional[PreemptionListener] = None
_LISTENER_LOCK = threading.Lock()


def get_listener() -> PreemptionListener:
    """Process-wide listener, installed on first use.

    A flag set BEFORE training starts is honored at the first dispatch
    (save-and-exit promptly) — a preemption notice during startup must not
    be lost. Tests that trigger injection therefore ``clear()`` between
    phases.
    """
    global _LISTENER
    with _LISTENER_LOCK:
        if _LISTENER is None:
            _LISTENER = PreemptionListener()
        return _LISTENER.install()
