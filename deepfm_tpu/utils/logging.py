"""Process-aware logging (chief logs by default; others opt in).

The reference relied on print-based env dumps + tf.logging INFO
(1-ps-cpu/...py:344-369,470). Here: stdlib logging, rank-prefixed, with
chief-only default to keep multi-process output readable.
"""

from __future__ import annotations

import logging as _logging
import os
import sys

_LOGGER = None
_ALL_RANKS = os.environ.get("DEEPFM_LOG_ALL_RANKS", "0") == "1"


def get_logger() -> _logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        logger = _logging.getLogger("deepfm_tpu")
        if not logger.handlers:
            h = _logging.StreamHandler(sys.stderr)
            h.setFormatter(_logging.Formatter(
                "%(asctime)s %(levelname)s deepfm_tpu: %(message)s",
                datefmt="%H:%M:%S"))
            logger.addHandler(h)
        logger.setLevel(_logging.INFO)
        _LOGGER = logger
    return _LOGGER


def _should_log() -> bool:
    if _ALL_RANKS:
        return True
    try:
        import jax
        return jax.process_index() == 0
    except Exception:
        return True


def info(msg: str) -> None:
    if _should_log():
        get_logger().info(msg)


def warning(msg: str) -> None:
    if _should_log():
        get_logger().warning(msg)


def error(msg: str) -> None:
    get_logger().error(msg)
