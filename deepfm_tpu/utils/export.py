"""Serving export: the SavedModel-analog artifact for TPU-native serving.

The reference exports a SavedModel with a raw serving signature
``{feat_ids: int64[None,F], feat_vals: float32[None,F]} -> {prob}``
(``1-ps-cpu/...py:451-467``, PREDICT branch ``:234-241``), chief/rank-0 only.

Here the servable artifact is a directory containing:
  * ``serving_fn.stablehlo`` — the predict function serialized with
    ``jax.export`` (StableHLO, batch-dim symbolic, lowered for CPU+TPU)
  * ``params.ckpt/`` — the inference parameters (Orbax standard format)
  * ``model_config.json`` — the model hyperparameters + signature schema

``load_serving`` reloads the artifact into a callable — the TF-Serving
round-trip analog used by tests and the infer benchmark.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from jax import export as jax_export

from ..config import Config
from ..data import fileio
from . import logging as ulog

_SERVING_FILE = "serving_fn.stablehlo"
_PARAMS_DIR = "params.ckpt"
_CONFIG_FILE = "model_config.json"


def _serving_fn(model, cfg: Config) -> Callable:
    def serve(params, model_state, feat_ids, feat_vals):
        logits, _ = model.apply(
            params, model_state, feat_ids.astype(jnp.int32),
            feat_vals.astype(jnp.float32), train=False, rng=None,
            shard_axis=None, data_axis=None)
        return jax.nn.sigmoid(logits)
    return serve


def export_serving(model, state, cfg: Config, out_dir: str) -> str:
    """Write the servable artifact; returns the artifact path.

    Chief-only by caller convention (reference rank-0 export,
    ``2-hvd-gpu/...py:429-431``). Params are fetched to host and saved
    unsharded so any single-device server can load them.
    """
    fileio.makedirs(out_dir)

    # 1. Params (device-gathered, unsharded).
    params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), state.params)
    model_state = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)), state.model_state)
    ckptr = ocp.StandardCheckpointer()
    params_path = fileio.join(fileio.normalize_dir(out_dir), _PARAMS_DIR)
    ckptr.save(params_path, {"params": params, "model_state": model_state},
               force=True)
    ckptr.wait_until_finished()

    # 2. Serialized serving function with symbolic batch dim.
    serve = _serving_fn(model, cfg)
    b = jax_export.symbolic_shape("b")[0]
    ids_spec = jax.ShapeDtypeStruct((b, cfg.field_size), jnp.int32)
    vals_spec = jax.ShapeDtypeStruct((b, cfg.field_size), jnp.float32)
    params_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    mstate_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), model_state)
    serialized = None
    try:
        exported = jax_export.export(
            jax.jit(serve), platforms=("cpu", "tpu"))(
                params_spec, mstate_spec, ids_spec, vals_spec)
        serialized = exported.serialize()
    except Exception as e:  # pragma: no cover - platform-specific lowering
        ulog.warning(f"stablehlo export skipped ({e}); params-only artifact")
    if serialized is not None:
        # Outside the guard: an I/O failure here is a real error (retryable
        # store hiccup, bad permissions), not a lowering limitation, and must
        # surface instead of silently degrading to a params-only artifact.
        with fileio.open_stream(fileio.join(out_dir, _SERVING_FILE), "wb") as f:
            f.write(serialized)

    # 3. Signature/config metadata.
    meta = {
        "signature": {
            "inputs": {
                "feat_ids": ["batch", cfg.field_size, "int32"],
                "feat_vals": ["batch", cfg.field_size, "float32"],
            },
            "outputs": {"prob": ["batch", "float32"]},
        },
        "model": cfg.model,
        "config": cfg.to_dict(),
        "step": int(jax.device_get(state.step)),
    }
    with fileio.open_stream(fileio.join(out_dir, _CONFIG_FILE), "w") as f:
        json.dump(meta, f, indent=2)
    ulog.info(f"exported servable model to {out_dir}")
    return out_dir


def load_serving(artifact_dir: str) -> Callable[[np.ndarray, np.ndarray], np.ndarray]:
    """Reload a servable artifact as ``f(feat_ids, feat_vals) -> probs``."""
    with fileio.open_stream(fileio.join(artifact_dir, _CONFIG_FILE), "r") as f:
        meta = json.load(f)
    cfg = Config.from_dict(meta["config"])
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(
        fileio.join(fileio.normalize_dir(artifact_dir), _PARAMS_DIR))
    params, model_state = restored["params"], restored["model_state"]

    hlo_path = fileio.join(artifact_dir, _SERVING_FILE)
    if fileio.exists(hlo_path):
        with fileio.open_stream(hlo_path, "rb") as f:
            exported = jax_export.deserialize(f.read())

        def serve(feat_ids: np.ndarray, feat_vals: np.ndarray) -> np.ndarray:
            return np.asarray(exported.call(
                params, model_state, feat_ids.astype(np.int32),
                feat_vals.astype(np.float32)))
        return serve

    # Fallback: rebuild from config (params-only artifact).
    from ..models import get_model
    model = get_model(cfg)
    fn = jax.jit(_serving_fn(model, cfg))

    def serve(feat_ids: np.ndarray, feat_vals: np.ndarray) -> np.ndarray:
        return np.asarray(fn(params, model_state, feat_ids, feat_vals))
    return serve
