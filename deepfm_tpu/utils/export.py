"""Serving export: the SavedModel-analog artifact for TPU-native serving.

The reference exports a SavedModel with a raw serving signature
``{feat_ids: int64[None,F], feat_vals: float32[None,F]} -> {prob}``
(``1-ps-cpu/...py:451-467``, PREDICT branch ``:234-241``), chief/rank-0 only.

Here the servable artifact is a directory containing:
  * ``serving_fn.stablehlo`` — the predict function serialized with
    ``jax.export`` (StableHLO, batch-dim symbolic, lowered for CPU+TPU)
  * ``params.ckpt/`` — the inference parameters (Orbax standard format)
  * ``model_config.json`` — the model hyperparameters + signature schema

``load_serving`` reloads the artifact into a callable — the TF-Serving
round-trip analog used by tests and the infer benchmark.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from jax import export as jax_export

from ..config import Config
from ..data import fileio
from . import logging as ulog

_SERVING_FILE = "serving_fn.stablehlo"
_PARAMS_DIR = "params.ckpt"
_CONFIG_FILE = "model_config.json"
_SAVEDMODEL_DIR = "saved_model"


def _serving_fn(model, cfg: Config) -> Callable:
    def serve(params, model_state, feat_ids, feat_vals):
        logits, _ = model.apply(
            params, model_state, feat_ids.astype(jnp.int32),
            feat_vals.astype(jnp.float32), train=False, rng=None,
            shard_axis=None, data_axis=None)
        return jax.nn.sigmoid(logits)
    return serve


def export_serving(model, state, cfg: Config, out_dir: str) -> str:
    """Write the servable artifact; returns the artifact path.

    Chief-only by caller convention (reference rank-0 export,
    ``2-hvd-gpu/...py:429-431``). Params are fetched to host and saved
    unsharded so any single-device server can load them.
    """
    fileio.makedirs(out_dir)

    # 1. Params (device-gathered, unsharded).
    params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), state.params)
    model_state = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)), state.model_state)
    ckptr = ocp.StandardCheckpointer()
    params_path = fileio.join(fileio.normalize_dir(out_dir), _PARAMS_DIR)
    ckptr.save(params_path, {"params": params, "model_state": model_state},
               force=True)
    ckptr.wait_until_finished()

    # 2. Serialized serving function with symbolic batch dim.
    serve = _serving_fn(model, cfg)
    b = jax_export.symbolic_shape("b")[0]
    ids_spec = jax.ShapeDtypeStruct((b, cfg.field_size), jnp.int32)
    vals_spec = jax.ShapeDtypeStruct((b, cfg.field_size), jnp.float32)
    params_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    mstate_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), model_state)
    serialized = None
    try:
        exported = jax_export.export(
            jax.jit(serve), platforms=("cpu", "tpu"))(
                params_spec, mstate_spec, ids_spec, vals_spec)
        serialized = exported.serialize()
    except Exception as e:  # pragma: no cover - platform-specific lowering
        ulog.warning(f"stablehlo export skipped ({e}); params-only artifact")
    if serialized is not None:
        # Outside the guard: an I/O failure here is a real error (retryable
        # store hiccup, bad permissions), not a lowering limitation, and must
        # surface instead of silently degrading to a params-only artifact.
        with fileio.open_stream(fileio.join(out_dir, _SERVING_FILE), "wb") as f:
            f.write(serialized)

    # 3. TF SavedModel (optional): the reference's actual serving artifact
    # (``export_savedmodel`` with the raw feat_ids/feat_vals signature,
    # ``1-ps-cpu/...py:458-467``) — a user's existing TF-Serving deployment
    # can load this directly. Emitted via jax2tf when TF is importable;
    # lowering failures degrade to the StableHLO+params artifact with a
    # warning, but write failures surface (same policy as the StableHLO
    # file above).
    _export_tf_savedmodel(serve, params, model_state, cfg, out_dir)

    # 4. Signature/config metadata.
    meta = {
        "signature": {
            "inputs": {
                "feat_ids": ["batch", cfg.field_size, "int32"],
                "feat_vals": ["batch", cfg.field_size, "float32"],
            },
            "outputs": {"prob": ["batch", "float32"]},
        },
        "model": cfg.model,
        "config": cfg.to_dict(),
        "step": int(jax.device_get(state.step)),
    }
    with fileio.open_stream(fileio.join(out_dir, _CONFIG_FILE), "w") as f:
        json.dump(meta, f, indent=2)
    ulog.info(f"exported servable model to {out_dir}")
    return out_dir


def _export_tf_savedmodel(serve: Callable, params, model_state, cfg: Config,
                          out_dir: str) -> None:
    """Write ``<out_dir>/saved_model`` loadable by TF Serving / tf.saved_model.

    The serving signature mirrors the reference exactly: inputs
    ``feat_ids`` int64[None, F] / ``feat_vals`` float32[None, F] (int64 per
    the reference's raw placeholders, ``1-ps-cpu/...py:458-461``), output
    ``prob`` float32[None].

    Weights are held as ``tf.Variable``s on the module (the jax2tf
    deployment pattern), NOT closed over as Python values — closure would
    freeze the embedding table into GraphDef constants and hit the 2GB
    proto limit at CTR scale. Lowering/trace failures degrade with a
    warning; ``tf.saved_model.save`` I/O failures propagate.
    """
    try:
        import tensorflow as tf  # noqa: PLC0415 (lazy, heavy)
        from jax.experimental import jax2tf  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover - env without TF
        ulog.warning(f"TF SavedModel export skipped (no tensorflow: {e})")
        return
    try:
        variables = tf.nest.map_structure(
            tf.Variable, (params, model_state))
        tf_fn = jax2tf.convert(
            lambda pv, ids, vals: serve(pv[0], pv[1], ids, vals),
            polymorphic_shapes=[None, "(b, _)", "(b, _)"],
            with_gradient=False)
        module = tf.Module()
        module.model_variables = variables  # tracked -> variables shard
        module.f = tf.function(
            lambda feat_ids, feat_vals: {
                "prob": tf_fn(variables, tf.cast(feat_ids, tf.int32),
                              feat_vals)},
            input_signature=[
                tf.TensorSpec([None, cfg.field_size], tf.int64,
                              name="feat_ids"),
                tf.TensorSpec([None, cfg.field_size], tf.float32,
                              name="feat_vals"),
            ])
        # Trace now: lowering errors belong to this guard, not to save().
        concrete = module.f.get_concrete_function()
    except Exception as e:  # pragma: no cover - TF-version specific
        ulog.warning(f"TF SavedModel export skipped ({e})")
        return
    sm_dir = fileio.join(out_dir, _SAVEDMODEL_DIR)
    try:
        tf.saved_model.save(module, sm_dir,
                            signatures={"serving_default": concrete})
    except tf.errors.UnimplementedError as e:
        # Storage scheme TF's filesystem layer doesn't support: a capability
        # gap, not a transient failure — degrade like a lowering failure.
        # (Real I/O errors — permissions, 5xx — are other types and raise.)
        ulog.warning(f"TF SavedModel export skipped (unsupported scheme: {e})")
        return
    ulog.info(f"wrote TF SavedModel to {sm_dir}")


def load_serving(artifact_dir: str) -> Callable[[np.ndarray, np.ndarray], np.ndarray]:
    """Reload a servable artifact as ``f(feat_ids, feat_vals) -> probs``."""
    with fileio.open_stream(fileio.join(artifact_dir, _CONFIG_FILE), "r") as f:
        meta = json.load(f)
    cfg = Config.from_dict(meta["config"])
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(
        fileio.join(fileio.normalize_dir(artifact_dir), _PARAMS_DIR))
    params, model_state = restored["params"], restored["model_state"]

    hlo_path = fileio.join(artifact_dir, _SERVING_FILE)
    if fileio.exists(hlo_path):
        with fileio.open_stream(hlo_path, "rb") as f:
            exported = jax_export.deserialize(f.read())

        def serve(feat_ids: np.ndarray, feat_vals: np.ndarray) -> np.ndarray:
            return np.asarray(exported.call(
                params, model_state, feat_ids.astype(np.int32),
                feat_vals.astype(np.float32)))
        return serve

    # Fallback: rebuild from config (params-only artifact).
    from ..models import get_model
    model = get_model(cfg)
    fn = jax.jit(_serving_fn(model, cfg))

    def serve(feat_ids: np.ndarray, feat_vals: np.ndarray) -> np.ndarray:
        return np.asarray(fn(params, model_state, feat_ids, feat_vals))
    return serve
