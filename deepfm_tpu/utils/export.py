"""Serving export: the SavedModel-analog artifact for TPU-native serving.

The reference exports a SavedModel with a raw serving signature
``{feat_ids: int64[None,F], feat_vals: float32[None,F]} -> {prob}``
(``1-ps-cpu/...py:451-467``, PREDICT branch ``:234-241``), chief/rank-0 only.

Here the servable artifact is a directory containing:
  * ``serving_fn.stablehlo`` — the predict function serialized with
    ``jax.export`` (StableHLO, batch-dim symbolic, lowered for CPU+TPU)
  * ``params.ckpt/`` — the inference parameters (Orbax standard format)
  * ``model_config.json`` — the model hyperparameters + signature schema

``load_serving`` reloads the artifact into a callable — the TF-Serving
round-trip analog used by tests and the infer benchmark.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from jax import export as jax_export

from ..config import Config
from ..data import fileio
from . import logging as ulog

_SERVING_FILE = "serving_fn.stablehlo"
_PARAMS_DIR = "params.ckpt"
_CONFIG_FILE = "model_config.json"
_SAVEDMODEL_DIR = "saved_model"

# Written LAST by export_serving: its presence certifies every other file in
# the artifact dir is complete. load_serving refuses dirs without it — a
# crashed or in-flight export must fail with a typed error, not a cryptic
# deserialization traceback halfway through restore.
COMPLETE_MARKER = "ARTIFACT_COMPLETE"

# Pointer file maintained next to published artifact dirs: its content is
# the basename of the newest complete artifact. Updated via write_atomic so
# readers only ever see a fully-published version.
LATEST_FILE = "LATEST"


class ArtifactIncomplete(RuntimeError):
    """A servable artifact dir is missing its completion marker (export
    crashed mid-write, or the caller raced an in-flight publish)."""


def _task_names(model) -> Tuple[str, ...]:
    return tuple(getattr(model, "task_names", ()) or ())


def _serving_hist_len(model, cfg: Config) -> int:
    """History columns in the serving signature: > 0 only for sequence
    models exported from a history-enabled config."""
    if getattr(model, "uses_history", False) and cfg.history_max_len > 0:
        return int(cfg.history_max_len)
    return 0


def serving_input_cols(model, cfg: Config) -> int:
    """Width of the artifact's feat_ids/feat_vals inputs. History-aware
    artifacts use the pipeline's packed-column convention — ids carry
    ``feat_ids ‖ hist_ids`` and vals carry ``feat_vals ‖ hist_mask``, width
    ``field_size + history_max_len`` — so the whole engine stack (buckets,
    padded_predict, dynamic batcher) serves them unchanged."""
    return cfg.field_size + _serving_hist_len(model, cfg)


def _serving_fn(model, cfg: Config) -> Callable:
    """Single-task: ``probs`` float32[B] (the reference signature, kept
    bit-for-bit). Multitask: ``{task_name: float32[B]}`` — one named
    probability head per task, in the model's declared task order.
    History-aware models split the packed input columns back into
    (feat, hist) before apply."""
    names = _task_names(model)
    multitask = len(names) > 1
    hist_len = _serving_hist_len(model, cfg)
    fs = cfg.field_size

    def serve(params, model_state, feat_ids, feat_vals):
        kwargs = {}
        if hist_len:
            kwargs = {"hist_ids": feat_ids[:, fs:].astype(jnp.int32),
                      "hist_mask": feat_vals[:, fs:].astype(jnp.float32)}
            feat_ids = feat_ids[:, :fs]
            feat_vals = feat_vals[:, :fs]
        logits, _ = model.apply(
            params, model_state, feat_ids.astype(jnp.int32),
            feat_vals.astype(jnp.float32), train=False, rng=None,
            shard_axis=None, data_axis=None, **kwargs)
        if multitask:
            probs = model.probs_from_logits(logits)  # [B, T]
            return {name: probs[:, t] for t, name in enumerate(names)}
        return jax.nn.sigmoid(logits)
    return serve


def export_serving(model, state, cfg: Config, out_dir: str) -> str:
    """Write the servable artifact; returns the artifact path.

    Chief-only by caller convention (reference rank-0 export,
    ``2-hvd-gpu/...py:429-431``). Params are fetched to host and saved
    unsharded so any single-device server can load them.
    """
    fileio.makedirs(out_dir)

    # 1. Params (device-gathered, unsharded).
    params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), state.params)
    model_state = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)), state.model_state)
    ckptr = ocp.StandardCheckpointer()
    params_path = fileio.join(fileio.normalize_dir(out_dir), _PARAMS_DIR)
    ckptr.save(params_path, {"params": params, "model_state": model_state},
               force=True)
    ckptr.wait_until_finished()

    # 2. Serialized serving function with symbolic batch dim. History-aware
    # models take packed columns (field_size + history_max_len wide).
    serve = _serving_fn(model, cfg)
    in_cols = serving_input_cols(model, cfg)
    b = jax_export.symbolic_shape("b")[0]
    ids_spec = jax.ShapeDtypeStruct((b, in_cols), jnp.int32)
    vals_spec = jax.ShapeDtypeStruct((b, in_cols), jnp.float32)
    params_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    mstate_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), model_state)
    serialized = None
    try:
        exported = jax_export.export(
            jax.jit(serve), platforms=("cpu", "tpu"))(
                params_spec, mstate_spec, ids_spec, vals_spec)
        serialized = exported.serialize()
    except Exception as e:  # pragma: no cover - platform-specific lowering
        ulog.warning(f"stablehlo export skipped ({e}); params-only artifact")
    if serialized is not None:
        # Outside the guard: an I/O failure here is a real error (retryable
        # store hiccup, bad permissions), not a lowering limitation, and must
        # surface instead of silently degrading to a params-only artifact.
        with fileio.open_stream(fileio.join(out_dir, _SERVING_FILE), "wb") as f:
            f.write(serialized)

    # 3. TF SavedModel (optional): the reference's actual serving artifact
    # (``export_savedmodel`` with the raw feat_ids/feat_vals signature,
    # ``1-ps-cpu/...py:458-467``) — a user's existing TF-Serving deployment
    # can load this directly. Emitted via jax2tf when TF is importable;
    # lowering failures degrade to the StableHLO+params artifact with a
    # warning, but write failures surface (same policy as the StableHLO
    # file above).
    _export_tf_savedmodel(serve, params, model_state, cfg, out_dir,
                          in_cols=in_cols)

    # 4. Signature/config metadata. Single-task keeps the historical "prob"
    # output name; multitask artifacts advertise one output per task name.
    names = _task_names(model)
    outputs = ({name: ["batch", "float32"] for name in names}
               if len(names) > 1 else {"prob": ["batch", "float32"]})
    meta = {
        "signature": {
            "inputs": {
                "feat_ids": ["batch", in_cols, "int32"],
                "feat_vals": ["batch", in_cols, "float32"],
            },
            "outputs": outputs,
        },
        "model": cfg.model,
        "history_len": _serving_hist_len(model, cfg),
        "config": cfg.to_dict(),
        "step": int(jax.device_get(state.step)),
    }
    with fileio.open_stream(fileio.join(out_dir, _CONFIG_FILE), "w") as f:
        json.dump(meta, f, indent=2)

    # 5. Completion marker — strictly last, atomically: the artifact is not
    # loadable until every byte above it is on disk.
    fileio.write_atomic(fileio.join(out_dir, COMPLETE_MARKER),
                        json.dumps({"step": meta["step"]}))
    ulog.info(f"exported servable model to {out_dir}")
    return out_dir


def _export_tf_savedmodel(serve: Callable, params, model_state, cfg: Config,
                          out_dir: str,
                          in_cols: Optional[int] = None) -> None:
    """Write ``<out_dir>/saved_model`` loadable by TF Serving / tf.saved_model.

    The serving signature mirrors the reference exactly: inputs
    ``feat_ids`` int64[None, F] / ``feat_vals`` float32[None, F] (int64 per
    the reference's raw placeholders, ``1-ps-cpu/...py:458-461``), output
    ``prob`` float32[None].

    Weights are held as ``tf.Variable``s on the module (the jax2tf
    deployment pattern), NOT closed over as Python values — closure would
    freeze the embedding table into GraphDef constants and hit the 2GB
    proto limit at CTR scale. Lowering/trace failures degrade with a
    warning; ``tf.saved_model.save`` I/O failures propagate.
    """
    if os.environ.get("DEEPFM_TPU_SKIP_TF_EXPORT", ""):
        # Drill/test seam (docs/TUNING.md seam table): the TF SavedModel
        # sidecar costs ~10s per publish and the jax-native serving runtime
        # never reads it — subprocess drills set this to keep the publish
        # cadence realistic. Production publishes leave it unset.
        return
    try:
        import tensorflow as tf  # noqa: PLC0415 (lazy, heavy)
        from jax.experimental import jax2tf  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover - env without TF
        ulog.warning(f"TF SavedModel export skipped (no tensorflow: {e})")
        return
    try:
        variables = tf.nest.map_structure(
            tf.Variable, (params, model_state))
        tf_fn = jax2tf.convert(
            lambda pv, ids, vals: serve(pv[0], pv[1], ids, vals),
            polymorphic_shapes=[None, "(b, _)", "(b, _)"],
            with_gradient=False)
        module = tf.Module()
        module.model_variables = variables  # tracked -> variables shard
        def _sig_out(feat_ids, feat_vals):
            out = tf_fn(variables, tf.cast(feat_ids, tf.int32), feat_vals)
            # Multitask serve fns already return a {task: probs} dict;
            # single-task keeps the reference's "prob" key.
            return out if isinstance(out, dict) else {"prob": out}

        cols = in_cols if in_cols is not None else cfg.field_size
        module.f = tf.function(
            _sig_out,
            input_signature=[
                tf.TensorSpec([None, cols], tf.int64,
                              name="feat_ids"),
                tf.TensorSpec([None, cols], tf.float32,
                              name="feat_vals"),
            ])
        # Trace now: lowering errors belong to this guard, not to save().
        concrete = module.f.get_concrete_function()
    except Exception as e:  # pragma: no cover - TF-version specific
        ulog.warning(f"TF SavedModel export skipped ({e})")
        return
    sm_dir = fileio.join(out_dir, _SAVEDMODEL_DIR)
    try:
        tf.saved_model.save(module, sm_dir,
                            signatures={"serving_default": concrete})
    except tf.errors.UnimplementedError as e:
        # Storage scheme TF's filesystem layer doesn't support: a capability
        # gap, not a transient failure — degrade like a lowering failure.
        # (Real I/O errors — permissions, 5xx — are other types and raise.)
        ulog.warning(f"TF SavedModel export skipped (unsupported scheme: {e})")
        return
    ulog.info(f"wrote TF SavedModel to {sm_dir}")


# --------------------------------------------------------------------------
# Bucketed prediction: the explicit per-shape compile cache
# --------------------------------------------------------------------------
#
# Both reload paths below compile one program per distinct batch shape they
# see (``exported.call`` specializes the symbolic batch dim per concrete
# shape; ``jax.jit`` caches per shape) — an implicit, unbounded compile
# cache. A serving engine flushing arbitrary batch sizes would compile
# arbitrarily many variants; bucketing makes the cache explicit and bounded:
# every call pads to the next bucket size, so at most ``len(buckets)``
# programs ever compile, and which sizes compile is a deployment decision
# instead of an accident of traffic.

def serving_buckets(max_batch: int) -> Tuple[int, ...]:
    """Power-of-two bucket ladder ``(1, 2, 4, ..., max_batch)``.

    ``max_batch`` itself is always the last bucket, even when it is not a
    power of two — the engine's largest flush must have a home.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b <<= 1
    buckets.append(int(max_batch))
    return tuple(buckets)


def next_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``n`` (buckets ascending)."""
    if n < 1:
        raise ValueError(f"batch of {n} rows cannot be bucketed")
    for b in buckets:
        if b >= n:
            return int(b)
    raise ValueError(
        f"batch of {n} rows exceeds the largest bucket ({buckets[-1]}); "
        "raise serve_max_batch or split the request")


def padded_predict(fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
                   feat_ids: np.ndarray, feat_vals: np.ndarray,
                   buckets: Sequence[int]) -> np.ndarray:
    """Run ``fn`` on the batch padded up to its bucket; return the real rows.

    Pad rows are zeros (id 0 is a valid embedding row; the serve path runs
    ``train=False`` so no batch statistic couples rows) and their outputs
    are sliced away before returning — output is row-for-row equal to the
    unpadded call (pinned by ``tests/test_serving.py``).
    """
    n = int(feat_ids.shape[0])
    b = next_bucket(n, buckets)
    if b == n:
        out = fn(feat_ids, feat_vals)
        if isinstance(out, dict):  # multitask: {task: probs}
            return {k: np.asarray(v) for k, v in out.items()}
        return np.asarray(out)
    ids = np.zeros((b,) + feat_ids.shape[1:], feat_ids.dtype)
    vals = np.zeros((b,) + feat_vals.shape[1:], feat_vals.dtype)
    ids[:n] = feat_ids
    vals[:n] = feat_vals
    out = fn(ids, vals)
    if isinstance(out, dict):
        return {k: np.asarray(v)[:n] for k, v in out.items()}
    return np.asarray(out)[:n]


class BucketedPredict:
    """``load_serving``-shaped callable with the bounded compile cache.

    Wraps a raw ``f(feat_ids, feat_vals) -> probs`` so only bucket shapes
    ever reach it. ``calls_per_bucket`` is observability for the serving
    stats (which bucket a deployment actually exercises).
    """

    def __init__(self, fn: Callable, buckets: Sequence[int]):
        bs = tuple(sorted({int(b) for b in buckets}))
        if not bs or bs[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.fn = fn
        self.buckets = bs
        self.calls_per_bucket: Dict[int, int] = {b: 0 for b in bs}

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def __call__(self, feat_ids: np.ndarray,
                 feat_vals: np.ndarray) -> np.ndarray:
        self.calls_per_bucket[next_bucket(len(feat_ids), self.buckets)] += 1
        return padded_predict(self.fn, feat_ids, feat_vals, self.buckets)


def load_serving(artifact_dir: str, *,
                 buckets: Optional[Sequence[int]] = None
                 ) -> Callable[[np.ndarray, np.ndarray], np.ndarray]:
    """Reload a servable artifact as ``f(feat_ids, feat_vals) -> probs``.

    With ``buckets`` the result is a :class:`BucketedPredict` — every call
    pads to the next bucket size so at most ``len(buckets)`` predict
    programs ever compile (the serving engine's shape policy).

    Raises :class:`ArtifactIncomplete` when the dir lacks its completion
    marker — the dir is mid-write, or an export crashed into it. Callers
    that poll (``watch_latest``) treat this as "try again later"; everything
    else should treat it as a corrupt deployment.
    """
    if not fileio.exists(fileio.join(artifact_dir, COMPLETE_MARKER)):
        raise ArtifactIncomplete(
            f"{artifact_dir} has no {COMPLETE_MARKER} marker — the artifact "
            "is incomplete (crashed or in-flight export); refusing to load")
    with fileio.open_stream(fileio.join(artifact_dir, _CONFIG_FILE), "r") as f:
        meta = json.load(f)
    cfg = Config.from_dict(meta["config"])
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(
        fileio.join(fileio.normalize_dir(artifact_dir), _PARAMS_DIR))
    params, model_state = restored["params"], restored["model_state"]

    hlo_path = fileio.join(artifact_dir, _SERVING_FILE)
    if fileio.exists(hlo_path):
        with fileio.open_stream(hlo_path, "rb") as f:
            exported = jax_export.deserialize(f.read())

        def serve(feat_ids: np.ndarray, feat_vals: np.ndarray) -> np.ndarray:
            out = exported.call(
                params, model_state, feat_ids.astype(np.int32),
                feat_vals.astype(np.float32))
            if isinstance(out, dict):  # multitask: {task: probs}
                return {k: np.asarray(v) for k, v in out.items()}
            return np.asarray(out)

        # Traceable predict for callers that fuse the ranker into a larger
        # jitted program (the cascade fast path): ``exported.call`` is
        # jax-traceable, so this composes under an outer ``jax.jit``.
        # Inputs must already be int32/float32 tracers of a bucket shape.
        def raw_call(feat_ids, feat_vals):
            return exported.call(params, model_state, feat_ids, feat_vals)
    else:
        # Fallback: rebuild from config (params-only artifact).
        from ..models import get_model
        model = get_model(cfg)
        fn_raw = _serving_fn(model, cfg)
        fn = jax.jit(fn_raw)

        def serve(feat_ids: np.ndarray, feat_vals: np.ndarray) -> np.ndarray:
            out = fn(params, model_state, feat_ids, feat_vals)
            if isinstance(out, dict):
                return {k: np.asarray(v) for k, v in out.items()}
            return np.asarray(out)

        def raw_call(feat_ids, feat_vals):
            return fn_raw(params, model_state, feat_ids, feat_vals)
    # Input width from the signature metadata: what a pre-warm caller (the
    # hot-swap watcher) needs to drive every bucket shape before the swap.
    in_cols = int(meta["signature"]["inputs"]["feat_ids"][1])
    serve.input_cols = in_cols
    serve.raw_call = raw_call
    if buckets is not None:
        wrapped = BucketedPredict(serve, buckets)
        wrapped.input_cols = in_cols
        wrapped.raw_call = raw_call
        return wrapped
    return serve


# --------------------------------------------------------------------------
# LATEST pointer + hot-swap consumer
# --------------------------------------------------------------------------

def write_latest(publish_dir: str, version: str) -> None:
    """Point ``<publish_dir>/LATEST`` at artifact dir ``version`` (basename).
    Atomic: a crashed update leaves the previous pointer intact."""
    fileio.write_atomic(fileio.join(publish_dir, LATEST_FILE), str(version))


def read_latest(publish_dir: str) -> Optional[str]:
    """Full path of the newest published artifact, or None when no pointer
    exists yet (or it dangles — points at a dir that is gone)."""
    pointer = fileio.join(publish_dir, LATEST_FILE)
    if not fileio.exists(pointer):
        return None
    with fileio.open_stream(pointer, "rb") as f:
        version = f.read().decode("utf-8").strip()
    if not version:
        return None
    path = fileio.join(publish_dir, version)
    return path if fileio.exists(path) else None


# Append-only audit sidecar next to LATEST: one JSON line per pointer move
# (publish / promote / rollback, plus quarantine bookkeeping), so every
# deployment decision is replayable from the publish dir alone.
POINTER_HISTORY_FILE = "pointer_history.jsonl"


def append_pointer_event(publish_dir: str, version: str, actor: str,
                         reason: str = "", *,
                         wall_time: Optional[float] = None) -> Dict[str, Any]:
    """Append one pointer-history event; returns the entry written (or the
    existing tail entry when this is a replay).

    Idempotent by design: the write protocol everywhere in this repo is
    *append history, then move the pointer* — a crash between the two means
    the healing retry re-runs both steps, so an append whose
    ``(version, actor, reason)`` exactly matches the current tail entry is
    skipped instead of duplicated. ``wall_time`` is injectable (the drill
    passes its logical clock; audit fingerprints exclude it either way).
    """
    entry = {"version": str(version), "actor": str(actor),
             "reason": str(reason),
             "wall_time": float(wall_time if wall_time is not None
                                else time.time())}
    path = fileio.join(publish_dir, POINTER_HISTORY_FILE)
    history = pointer_history(publish_dir)
    if history:
        tail = history[-1]
        if (tail.get("version") == entry["version"]
                and tail.get("actor") == entry["actor"]
                and tail.get("reason") == entry["reason"]):
            return tail
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True,
                           separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return entry


def pointer_history(publish_dir: str) -> list:
    """All pointer-history events, oldest first. Tolerant of a torn final
    line (a crash mid-append): the unparseable tail is dropped, matching
    the heal contract — the retried append rewrites it whole."""
    path = fileio.join(publish_dir, POINTER_HISTORY_FILE)
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                break   # torn tail — everything after it is suspect
    return out


# The pointer reader's companion (satellite contract: reading the pointer
# and reading its provenance are one surface).
read_latest.history = pointer_history


class LatestWatcher:
    """Hot-swap serving consumer: follow ``LATEST`` without dropping requests.

    Callable with the same ``(feat_ids, feat_vals) -> probs`` signature as
    :func:`load_serving`'s result. A poll (background thread, or
    :meth:`check_once` for callers that drive it themselves) notices a new
    ``LATEST`` pointer, loads the NEW artifact completely off to the side,
    then swaps it in with one attribute assignment — requests in flight keep
    executing the old function; requests after the swap get the new one; no
    request ever observes a half-loaded model. A load failure (incomplete or
    vanished artifact — e.g. the watcher raced a publish) keeps the current
    model and retries next poll.
    """

    def __init__(self, publish_dir: str, *, poll_secs: float = 2.0,
                 on_swap: Optional[Callable[[str], None]] = None,
                 on_error: Optional[Callable[[BaseException], None]] = None,
                 loader: Callable[[str], Callable] = load_serving,
                 start: bool = True,
                 prewarm: bool = True,
                 sleep: Optional[Callable[[float], None]] = None):
        self._publish_dir = publish_dir
        self._poll_secs = float(poll_secs)
        self._on_swap = on_swap
        self._on_error = on_error
        self._loader = loader
        self._prewarm = bool(prewarm)
        # Guards the (fn, current_path, swap_count) triple so current()
        # returns a CONSISTENT snapshot: a pipelined serving engine stamps
        # each flush with the version that executed it (the blackout
        # measure), and a torn read (new fn, old count) would mislabel the
        # first post-swap flush as pre-swap.
        self._swap_lock = threading.Lock()
        self._stop = threading.Event()
        self._sleep = sleep if sleep is not None else self._stop.wait
        self._fn: Optional[Callable] = None
        self.current_path: Optional[str] = None
        self.swap_count = 0
        # Buckets compiled off-thread before each swap (observability for
        # the blackout drill: prewarmed > 0 means the first post-swap
        # request of any bucket shape hits a warm compile cache).
        self.prewarmed_buckets = 0
        # Failed swap attempts (torn/marker-less/vanished artifact seen at
        # LATEST): the current model stayed live each time. A counter, not
        # just a warning — a serving drill asserting "zero dropped requests
        # across N swaps" also wants to know how many swaps never happened.
        self.swap_failures = 0
        # Unexpected poll-loop exceptions (loader bugs, filesystem faults
        # outside the anticipated ArtifactIncomplete/OSError/ValueError
        # classes). The poll thread NEVER dies on these — it keeps serving
        # the current model and retries — but dying silently and counting
        # are different things: this is the counter, surfaced through
        # ``ServingStats`` so a drill (or production alerting) can see a
        # watcher that is alive but failing.
        self.watcher_errors = 0
        self._thread: Optional[threading.Thread] = None
        self.check_once()
        if start:
            self._thread = threading.Thread(
                target=self._run, name="latest-watcher", daemon=True)
            self._thread.start()

    def check_once(self) -> bool:
        """Poll LATEST; swap if it moved. Returns True iff a swap happened."""
        path = read_latest(self._publish_dir)
        if path is None or path == self.current_path:
            return False
        try:
            fn = self._loader(path)
            if self._prewarm:
                self._warm_buckets(fn)
        except (ArtifactIncomplete, OSError, ValueError) as e:
            self.swap_failures += 1
            ulog.warning(f"hot-swap to {path} deferred ({e}); "
                         "keeping current model")
            return False
        with self._swap_lock:
            self._fn = fn  # the swap: one reference assignment
            self.current_path = path
            self.swap_count += 1
        if self._on_swap is not None:
            self._on_swap(path)
        return True

    def current(self):
        """Consistent ``(predict_fn, version)`` snapshot, where version is
        the ``swap_count`` that installed the function. Before the first
        artifact loads, the fn slot is the watcher itself (calling it
        raises the typed "no artifact published" error) at version 0. A
        versioned executor (the pipelined serving engine) uses this to
        stamp each flush with the model that actually ran it."""
        with self._swap_lock:
            fn = self._fn if self._fn is not None else self
            return fn, self.swap_count

    def _warm_buckets(self, fn: Callable) -> None:
        """Drive every serving bucket through the NEW function before it is
        swapped in, still off to the side: each bucket's predict program
        compiles here, on the watcher thread, so the swap costs live
        traffic one pointer assignment instead of len(buckets) compiles
        (the near-zero-blackout property the serving drill asserts).
        Needs a bucketed loader result that advertises its input width
        (``load_serving(buckets=...)`` does); anything else warms nothing."""
        buckets = getattr(fn, "buckets", None)
        cols = getattr(fn, "input_cols", None)
        if not buckets or not cols:
            return
        for b in buckets:
            fn(np.zeros((int(b), int(cols)), np.int32),
               np.zeros((int(b), int(cols)), np.float32))
            self.prewarmed_buckets += 1

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sleep(self._poll_secs)
            if self._stop.is_set():
                return
            try:
                self.check_once()
            except Exception as e:  # never kill the serving thread
                self.watcher_errors += 1
                ulog.warning(f"LATEST poll failed ({e}); retrying")
                if self._on_error is not None:
                    try:
                        self._on_error(e)
                    except Exception:
                        pass

    def __call__(self, feat_ids: np.ndarray,
                 feat_vals: np.ndarray) -> np.ndarray:
        fn = self._fn
        if fn is None:
            raise RuntimeError(
                f"no artifact published under {self._publish_dir} yet")
        return fn(feat_ids, feat_vals)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def watch_latest(publish_dir: str, **kwargs) -> LatestWatcher:
    """``load_serving`` that follows the LATEST pointer: returns a callable
    that hot-swaps to each newly published artifact without dropping a
    request. See :class:`LatestWatcher` (kwargs forwarded)."""
    return LatestWatcher(publish_dir, **kwargs)
