"""Checkpoint/resume on shared storage via Orbax.

TPU-native replacement for TF-Estimator checkpointing (reference semantics:
shared-storage ``model_dir`` with auto-resume from the latest checkpoint,
``1-ps-cpu/...py:434-435`` + ``README-EN.md:62``; rank-0-only ``model_dir``
under Horovod, ``2-hvd-gpu/...py:365-368``). Orbax writes the sharded train
state distributedly (every process writes its shards — the multi-host
generalization of "rank 0 saves"), asynchronously (save overlaps the next
training steps), and keeps ``max_to_keep`` checkpoints. Preemption tolerance
== resume-from-latest, exactly the reference's spot-instance story.
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Callable, Optional

import jax
import orbax.checkpoint as ocp

from ..data import fileio
from . import logging as ulog
from . import retry as retry_lib


class AsyncSaveExecutor:
    """One background thread for artifact writes off the training hot path.

    Orbax drives its own async checkpoint writes; this executor serializes
    the *other* asynchronous writers — the online publisher's delta
    checkpoint + servable export jobs — so publish I/O never competes with
    itself and ``drain()`` gives the preemption path a single place to wait.
    The thread is created lazily on first submit and is a daemon, so an
    executor that is constructed but never used costs nothing and never
    blocks interpreter exit.
    """

    def __init__(self, name: str = "async-save"):
        self._name = name
        self._lock = threading.Lock()
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None

    def submit(self, fn: Callable, *args, **kwargs) -> concurrent.futures.Future:
        with self._lock:
            if self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix=self._name)
            return self._pool.submit(fn, *args, **kwargs)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for all submitted jobs; True iff everything finished in time.
        Submitting a no-op and waiting on it rides the FIFO guarantee of the
        single worker thread, so no job bookkeeping is needed."""
        with self._lock:
            pool = self._pool
        if pool is None:
            return True
        fence = pool.submit(lambda: None)
        try:
            fence.result(timeout=timeout)
            return True
        except concurrent.futures.TimeoutError:
            return False

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for the TrainState pytree."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 0, async_save: bool = True,
                 max_save_failures: int = 3,
                 retry_policy: Optional[retry_lib.RetryPolicy] = None):
        self._dir = fileio.normalize_dir(directory)
        fileio.makedirs(self._dir)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)
        self.save_interval_steps = save_interval_steps
        self._last_should_save_step: Optional[int] = None
        self._saved_steps: set = set()
        self._max_to_keep = max_to_keep
        # Save hardening: a transient interval-save failure logs and defers
        # to the next interval; only this many CONSECUTIVE failures abort.
        # (0 = abort on the first failure.) Forced saves always hard-fail.
        self.max_save_failures = max_save_failures
        self.save_failures = 0          # total failed save attempts
        self._consecutive_failures = 0
        # Read-side hardening: retry/backoff around latest_step/restore — a
        # transient storage error on restore would otherwise kill a resuming
        # job instantly (the save side has been hardened since PR 1).
        self._retry = retry_policy

    def _call_read(self, fn, *args, op_name: str):
        if self._retry is None:
            return fn(*args)
        return self._retry.call(fn, *args, op_name=op_name)

    @property
    def directory(self) -> str:
        return self._dir

    def latest_step(self) -> Optional[int]:
        return self._call_read(self._mgr.latest_step,
                               op_name=f"latest_step({self._dir})")

    def _do_save(self, step: int, state: Any, force: bool) -> bool:
        """The actual Orbax write. Seam for fault injection (FlakyFS
        patches this) — keep all failure handling in save() above it."""
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        # Dedup against steps saved THIS session too: async saves may not yet
        # appear in all_steps() when the final forced save lands on the same
        # step as an in-flight interval save.
        if step in self._saved_steps or step in self._mgr.all_steps():
            return False  # e.g. final forced save after an interval save hit it
        try:
            saved = self._do_save(step, state, force)
        except Exception as e:
            self.save_failures += 1
            self._consecutive_failures += 1
            if force:
                # The final save is the run's deliverable — losing it
                # silently would discard the training; let it kill the job.
                raise
            if self._consecutive_failures > self.max_save_failures:
                raise IOError(
                    f"checkpoint save failed {self._consecutive_failures} "
                    f"consecutive times (max_save_failures="
                    f"{self.max_save_failures}) at step {step}: {e}") from e
            ulog.warning(
                f"checkpoint save at step {step} failed "
                f"({self._consecutive_failures} consecutive, tolerating "
                f"{self.max_save_failures}); deferring to next interval: {e}")
            return False
        self._consecutive_failures = 0
        if saved:
            self._saved_steps.add(step)
            # Steps are monotonic and Orbax only retains max_to_keep
            # checkpoints, so the session dedup set needs just the most
            # recent entries — unpruned it leaks one int per save for the
            # whole run (weeks-long jobs).
            keep_n = max(self._max_to_keep, 8)
            if len(self._saved_steps) > keep_n:
                self._saved_steps = set(sorted(self._saved_steps)[-keep_n:])
            ulog.info(f"checkpoint saved at step {step} -> {self._dir}")
        return saved

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the template's shardings (pass a freshly-initialized
        state so restored arrays land row-sharded/replicated correctly)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self._dir}")
        abstract = jax.tree.map(_as_abstract, state_template)
        try:
            # Retry-wrapped: a transient read fault heals; a ValueError
            # (shape mismatch) is not retryable and falls through to the
            # guidance below unchanged.
            restored = self._call_read(
                lambda: self._mgr.restore(
                    step, args=ocp.args.StandardRestore(abstract)),
                op_name=f"restore(step {step}, {self._dir})")
        except ValueError as e:
            if "not compatible with the stored shape" in str(e):
                raise RuntimeError(
                    f"checkpoint at {self._dir} (step {step}) has parameter "
                    f"shapes that do not match this run's config/build: {e}. "
                    f"Common causes: changed model hyperparameters "
                    f"(feature_size/embedding_size/deep_layers) while "
                    f"reusing a model_dir, or a checkpoint saved before the "
                    f"mesh-independent vocab padding (ops/embedding.py). "
                    f"Match the original config, or start a fresh "
                    f"model_dir.") from e
            raise
        ulog.info(f"restored checkpoint step {step} from {self._dir}")
        return restored

    def should_save(self, step: int) -> bool:
        """True when ``step`` crosses a save-interval boundary since the last
        query — steps may advance by more than 1 per call (steps_per_loop).
        Seeded from the latest existing checkpoint so a resumed run does not
        save an off-schedule checkpoint on its first dispatch."""
        if not self.save_interval_steps:
            return False
        if self._last_should_save_step is None:
            self._last_should_save_step = self.latest_step() or 0
        crossed = (step // self.save_interval_steps
                   > self._last_should_save_step // self.save_interval_steps)
        self._last_should_save_step = step
        return crossed

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        if exc and exc[0] is not None:
            # Exiting on an exception (e.g. a preemption unwinding) with an
            # async save possibly in flight: drain it so the checkpoint
            # directory is never left half-written, but swallow secondary
            # close errors — the original exception must propagate.
            try:
                self.close()
            except Exception as close_exc:
                ulog.warning(
                    f"checkpoint close during exception unwind failed "
                    f"(original error propagates): {close_exc}")
            return
        self.close()


def _as_abstract(x: Any) -> Any:
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


def clear_model_dir(directory: str) -> None:
    """clear_existing_model semantics (reference 2-hvd-gpu/...py:60,334-340):
    wipe the checkpoint dir for a fresh run; chief only."""
    if jax.process_index() != 0:
        return
    if fileio.isdir(directory):
        fileio.rmtree(directory)
        ulog.info(f"cleared existing model dir {directory}")
