"""MFU (model FLOPs utilization) with an explicit basis label.

An MFU number is only meaningful relative to the peak it is divided by,
and that peak comes from different places depending on where the bench
runs:

- On a recognized TPU, the divisor is the chip's dense bf16 peak from the
  public spec sheet: basis ``measured-device-peak``.
- On the CPU backend there is no spec-sheet peak. Rather than silently
  emitting ``null`` (which readers mistook for "not applicable" instead
  of "unknown"), we divide by a labeled nominal host estimate: basis
  ``nominal-estimate``. The number is order-of-magnitude only — its job
  is to show the workload is nowhere near a FLOP wall, not to rank
  hosts.
- On an unrecognized accelerator the honest answer is no number at all:
  basis ``unavailable`` with a null MFU.

Every MFU a bench emits must carry its basis in-band (see BASELINE.md):
a consumer that averages a measured-device-peak MFU with a
nominal-estimate MFU gets garbage, and the label is what lets it refuse.
"""
from typing import Optional, Tuple

# Basis labels, stamped next to every emitted MFU value.
BASIS_MEASURED = "measured-device-peak"
BASIS_NOMINAL = "nominal-estimate"
BASIS_UNAVAILABLE = "unavailable"

# Dense bf16 peak FLOP/s per chip by device_kind (public spec sheets).
# Matched by substring against jax's device_kind; unknown kinds yield
# basis "unavailable" rather than a wrong number.
PEAK_FLOPS_BF16 = {
    "v6e": 918e12, "v6 lite": 918e12,
    "v5p": 459e12,
    "v5e": 197e12, "v5 lite": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

# Nominal single-host CPU peak for the labeled CPU estimate: a few AVX2
# cores' worth of fp32 FMA (~1e11 FLOP/s). Deliberately coarse — the
# basis label marks every number derived from it as an estimate.
NOMINAL_CPU_PEAK_FLOPS = 1e11


def device_peak_flops() -> Tuple[Optional[float], str, str]:
    """(peak_flops_or_None, device_kind, basis) for the first device.

    TPUs with a spec-sheet entry get (peak, kind, BASIS_MEASURED); the
    CPU backend gets the labeled nominal estimate; anything else gets
    (None, kind, BASIS_UNAVAILABLE).
    """
    import jax
    dev = jax.devices()[0]
    kind = dev.device_kind
    low = kind.lower()
    if "tpu" in low:
        for key, peak in PEAK_FLOPS_BF16.items():
            if key in low:
                return peak, kind, BASIS_MEASURED
        return None, kind, BASIS_UNAVAILABLE
    if dev.platform == "cpu":
        return NOMINAL_CPU_PEAK_FLOPS, kind, BASIS_NOMINAL
    return None, kind, BASIS_UNAVAILABLE


def mfu_pct(flops_per_example: float,
            examples_per_sec_per_chip: float) -> Tuple[Optional[float], str, str]:
    """(mfu_pct_or_None, basis, device_kind) for an achieved throughput.

    Returns a percentage against the first device's peak; None (with
    basis ``unavailable``) when no peak — measured or nominal — exists.
    """
    peak, kind, basis = device_peak_flops()
    if peak is None:
        return None, basis, kind
    pct = 100.0 * flops_per_example * examples_per_sec_per_chip / peak
    return round(pct, 4), basis, kind
