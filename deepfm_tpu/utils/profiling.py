"""Profiling / tracing subsystem (SURVEY.md §5 "tracing" equivalent).

The reference ships with profiling *disabled* (``debugger_hook_config=False,
disable_profiler=True``, ``deepfm-sagemaker-ps-cpu.ipynb:117-118``) and tunes
via MKL/OMP env + thread pools instead. The TPU-native replacement is real
tracing: ``jax.profiler`` XPlane traces viewable in TensorBoard/Perfetto,
plus a lightweight step-time/throughput meter for always-on observability.

Usage:
    with maybe_trace(cfg.profile_dir):
        ... training steps ...

    meter = ThroughputMeter()
    meter.update(n_examples)      # per step
    meter.summary()               # {examples_per_sec, mean/p50/p99 step ms}
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Optional

from ..obs import metrics as metrics_lib


@contextlib.contextmanager
def maybe_trace(profile_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler trace when ``profile_dir`` is set; no-op otherwise."""
    if not profile_dir:
        yield
        return
    import jax
    with jax.profiler.trace(profile_dir):
        yield


class StepWindowTracer:
    """Trace a bounded window of train steps.

    Tracing every step of a long run buffers an unloadably large XPlane
    file; the useful signal is a few steady-state steps. Starts after
    ``start_step`` (skipping compile) and stops after ``num_steps`` traced
    steps. ``on_step()`` is a fit-loop hook; ``close()`` stops an open
    trace (e.g. when the run ends inside the window). No-op when
    ``profile_dir`` is falsy.
    """

    def __init__(self, profile_dir: Optional[str], *, start_step: int = 2,
                 num_steps: int = 20):
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self._seen = 0
        self._active = False
        self._done = False

    def on_step(self, steps_done: int = 1) -> None:
        """Advance by ``steps_done`` optimizer steps (hooks fire once per
        dispatch, which covers steps_per_loop real steps)."""
        if not self.profile_dir or self._done:
            return
        import jax
        self._seen += steps_done
        if not self._active and self._seen >= self.start_step:
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
        elif self._active and self._seen >= self.start_step + self.num_steps:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    def close(self) -> None:
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
            self._done = True


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region in the profiler timeline (TraceAnnotation)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


class HostStageStats:
    """Per-stage wall-time accumulator for the host input path.

    The pipeline brackets each stage of its hot loop with ``stage(name)``
    (``read`` — stream bytes in; ``frame`` — split TFRecord frames;
    ``decode_assemble`` — proto decode scattered into the transfer-layout
    pool; ``emit`` — slice/stack batches off the pool) when a collector is
    attached via ``CtrPipeline.stage_stats``; detached (the default) every
    site is a no-op. All stages run on the pipeline generator's thread —
    even when the decode fans out to a reader pool, the bracket measures
    the generator's wall wait — so the numbers add up to (most of) the
    observed ns/record and the remainder is attributable Python glue.
    """

    def __init__(self) -> None:
        self.ns: Dict[str, int] = {}
        self.records = 0  # caller sets/accumulates the denominator
        # Unified registry (obs.metrics): per-stage ns/record is the
        # metric surface.
        metrics_lib.auto_register("host_stage", self)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.ns[name] = self.ns.get(name, 0) + (
                time.perf_counter_ns() - t0)

    def ns_per_record(self, records: Optional[int] = None
                      ) -> Dict[str, float]:
        """Per-stage ns/record; pass ``records`` or preset ``.records``."""
        n = records if records is not None else self.records
        n = max(int(n), 1)
        return {name: round(total / n, 1)
                for name, total in sorted(self.ns.items())}


class ThroughputMeter:
    """Step-time and examples/sec accumulator (host wall-clock).

    Per-step wall time includes host input handoff — by design: with JAX
    async dispatch the device step overlaps the next host batch, so the
    steady-state wall time *is* the pipeline-limited step time.
    """

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._step_times: List[float] = []  # per-step (interval / steps_done)
        self._total_time = 0.0
        self._examples = 0
        self._n_updates = 0
        self._n_steps = 0
        self._drain = 0.0
        self._last = time.perf_counter()

    def update(self, n_examples: int, steps_done: int = 1) -> None:
        """Record one dispatch covering ``steps_done`` optimizer steps."""
        now = time.perf_counter()
        self._n_updates += 1
        self._n_steps += steps_done
        if self._n_updates > self.warmup_steps:  # skip compile dispatches
            interval = now - self._last
            self._total_time += interval
            self._step_times.append(interval / max(steps_done, 1))
            self._examples += n_examples
        self._last = now

    def record_drain(self) -> None:
        """Fold time spent blocking on the final async-dispatched step into
        the throughput denominator (without polluting step percentiles) —
        call after jax.block_until_ready on the last step's outputs."""
        now = time.perf_counter()
        self._drain += now - self._last
        self._last = now

    def summary(self) -> Dict[str, float]:
        if not self._step_times:
            return {"steps": float(self._n_steps)}
        ts = sorted(self._step_times)
        n = len(ts)
        return {
            "steps": float(self._n_steps),
            "examples_per_sec": self._examples / max(
                self._total_time + self._drain, 1e-9),
            "step_ms_mean": 1000.0 * sum(ts) / n,
            "step_ms_p50": 1000.0 * ts[n // 2],
            # nearest-rank p99: ceil(0.99n)-1, not int(0.99n) (which would
            # report the max for any n <= 100)
            "step_ms_p99": 1000.0 * ts[max(0, -(-99 * n // 100) - 1)],
        }
