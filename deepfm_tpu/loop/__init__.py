"""Closed-loop feedback layer: serve -> log -> delayed labels -> train shards.

The reference's production loop (impression logging, label attribution,
periodic retrains) lived outside the repo, in the ad platform; here it is an
owned subsystem so the whole serve->log->train->publish cycle can be drilled
as one system (scripts/production_drill.py):

  * :class:`~deepfm_tpu.loop.impressions.ImpressionLogger` — served requests
    written back as TFRecord shards via atomic rename (the same
    write-then-``os.replace`` contract the online stream source expects of
    any producer).
  * :class:`~deepfm_tpu.loop.join.DelayedLabelJoiner` — impressions joined
    with labels arriving on a delay distribution, emitted as training shards
    bit-identical in schema to ``generate_synthetic_ctr`` output; duplicate
    impressions, late labels, and labels past the join window are counted,
    never silently dropped (:class:`~deepfm_tpu.loop.health.LoopHealth`).
  * :class:`~deepfm_tpu.loop.skew.SkewChecker` — the training decoder and
    the serving feature path must produce bit-identical features for the
    same logged record (training/serving skew is the classic silent killer
    of online CTR systems).
  * :class:`~deepfm_tpu.loop.traffic.DiurnalTrafficPlan` — a seeded,
    precomputed diurnal request plan with hidden-model ground-truth labels,
    so two drills with the same seed replay identical traffic.
  * :mod:`~deepfm_tpu.loop.metrics` — windowed online-vs-frozen AUC and
    staleness percentiles for the drill's metrics plane.

Everything here is numpy + the pure-Python codec: no jax import, so the
feedback layer can run in light processes (loggers, joiners) that never
touch a device.
"""

from .health import LoopHealth
from .impressions import ImpressionLogger, iter_impressions
from .join import DelayedLabelJoiner, SeededLabelFeed
from .metrics import arm_health, staleness_summary, windowed_auc
from .skew import SkewChecker
from .traffic import DiurnalTrafficPlan

__all__ = [
    "DelayedLabelJoiner",
    "DiurnalTrafficPlan",
    "ImpressionLogger",
    "LoopHealth",
    "SeededLabelFeed",
    "SkewChecker",
    "arm_health",
    "iter_impressions",
    "staleness_summary",
    "windowed_auc",
]
