"""Seeded diurnal traffic: a precomputed, bit-replayable request plan.

Production request rates are day-shaped; a drill that only ever sees a flat
rate never exercises the batcher's two regimes (deadline-bound at the
trough, max-batch-bound at the peak). The plan compresses one "day" into
``duration_s``: request arrivals follow a nonhomogeneous Poisson process
with rate ``base_qps + (peak_qps - base_qps) * sin^2(pi * t / duration)``
(trough at both ends, peak mid-run — the chaos schedule's 20-80% event
window lands its faults on the peak).

Everything — arrival times, request sizes, feature arrays, ground-truth
labels — is drawn up front from one seed, so two plans with equal seeds are
element-for-element identical and a drill replay serves byte-identical
traffic. Labels follow the same hidden-logistic model as
``libsvm.generate_synthetic_ctr`` (``hidden_seed`` fixes the ground truth
independently of the traffic seed), drawn per-impression from a
``(seed, impression_id)``-keyed rng — deterministic even if requests are
served out of order.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class PlannedRequest:
    t_s: float            # scheduled submit time, seconds from plan start
    first_id: int         # impression id of row 0 (rows are consecutive)
    ids: np.ndarray       # [n, F] int32 — exactly what serving scores
    vals: np.ndarray      # [n, F] float32
    labels: np.ndarray    # [n] float32 ground truth (known to the drill,
    #                       revealed to the joiner only after the delay)


class DiurnalTrafficPlan:
    """Precomputed request schedule + hidden-model ground truth."""

    def __init__(self, seed: int, *, duration_s: float, base_qps: float,
                 peak_qps: float, feature_size: int, field_size: int,
                 max_rows: int = 8, hidden_seed: int = 12345):
        if peak_qps < base_qps or base_qps <= 0:
            raise ValueError(
                f"need 0 < base_qps <= peak_qps, got {base_qps}/{peak_qps}")
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.hidden_w = np.random.default_rng(hidden_seed).normal(
            0, 1.0, size=feature_size).astype(np.float32)
        rng = np.random.default_rng(self.seed)
        requests: List[PlannedRequest] = []
        t, next_id = 0.0, 0
        while True:
            # Thinning (Lewis-Shedler): candidate arrivals at the peak
            # rate, accepted with probability rate(t)/peak — exact for a
            # nonhomogeneous Poisson process, and fully seeded.
            t += float(rng.exponential(1.0 / peak_qps))
            if t >= self.duration_s:
                break
            rate = base_qps + (peak_qps - base_qps) * (
                math.sin(math.pi * t / self.duration_s) ** 2)
            if float(rng.random()) >= rate / peak_qps:
                continue
            n = int(rng.integers(1, max_rows + 1))
            ids = rng.integers(0, feature_size,
                               (n, field_size)).astype(np.int32)
            vals = rng.normal(size=(n, field_size)).astype(np.float32)
            labels = np.empty((n,), np.float32)
            for r in range(n):
                labels[r] = self._draw_label(next_id + r, ids[r], vals[r])
            requests.append(PlannedRequest(
                t_s=round(t, 6), first_id=next_id,
                ids=ids, vals=vals, labels=labels))
            next_id += n
        self.requests: Tuple[PlannedRequest, ...] = tuple(requests)
        self.total_rows = next_id

    def _draw_label(self, impression_id: int, ids: np.ndarray,
                    vals: np.ndarray) -> float:
        logit = float(np.dot(self.hidden_w[ids], vals)) * 0.5
        p = 1.0 / (1.0 + math.exp(-logit))
        u = np.random.default_rng(
            (self.seed + 1) * 2_654_435_761 + int(impression_id)).random()
        return float(u < p)

    def fingerprint_data(self) -> Tuple:
        """Deterministic digestable view (times, ids, labels) for audit
        fingerprints."""
        return tuple((r.t_s, r.first_id, int(r.ids.shape[0]),
                      r.labels.tobytes()) for r in self.requests)


# --------------------------------------------------------------------------
# Flood traffic: a million-user Zipf population driven past saturation.
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FloodRequest:
    t_s: float            # scheduled submit time, seconds from plan start
    user_id: int          # population index (0 = most active); the sticky
    #                       affinity key and history owner
    value: str            # admission value class (serve/admission.py)
    first_id: int         # impression id of row 0
    ids: np.ndarray       # [n, F] int32
    vals: np.ndarray      # [n, F] float32
    hist_ids: np.ndarray  # [L] int32 — the user's click history BEFORE
    #                       this request (per-user continuity)
    hist_mask: np.ndarray  # [L] float32


class ZipfUserPopulation:
    """>= 1M synthetic users with Zipf-distributed activity and per-user
    click-history continuity.

    User activity follows ``rank^-zipf_q`` (user 0 is the hottest head
    user); item popularity follows its own Zipf over ``item_vocab`` ids, so
    DIN/BST and the twin-tower index see realistic skew: head users
    accumulate long histories across requests, tail users mostly arrive
    cold. Sampling is a vectorized inverse-CDF (``searchsorted`` over a
    precomputed float64 cumsum — ~8 MB per million users, built once);
    histories are LAZY per-user deques so a million-user population costs
    memory only for the users traffic actually touched.
    """

    def __init__(self, seed: int, *, users: int = 1_000_000,
                 zipf_q: float = 1.1, item_vocab: int = 10_000,
                 item_zipf_q: float = 1.05, hist_len: int = 8):
        if users < 1 or item_vocab < 1:
            raise ValueError(
                f"need users/item_vocab >= 1, got {users}/{item_vocab}")
        self.seed = int(seed)
        self.users = int(users)
        self.zipf_q = float(zipf_q)
        self.item_vocab = int(item_vocab)
        self.hist_len = int(hist_len)
        w = np.arange(1, self.users + 1, dtype=np.float64) ** -zipf_q
        self._user_cum = np.cumsum(w)
        self._user_cum /= self._user_cum[-1]
        wi = np.arange(1, self.item_vocab + 1,
                       dtype=np.float64) ** -float(item_zipf_q)
        self._item_cum = np.cumsum(wi)
        self._item_cum /= self._item_cum[-1]
        self._hist: dict = {}     # user_id -> List[int], most recent last

    def sample_users(self, rng: np.random.Generator,
                     count: int) -> np.ndarray:
        """``count`` user ids by inverse CDF (0 = most active)."""
        return np.searchsorted(self._user_cum, rng.random(count),
                               side="right").astype(np.int64)

    def sample_items(self, rng: np.random.Generator,
                     count: int) -> np.ndarray:
        return np.searchsorted(self._item_cum, rng.random(count),
                               side="right").astype(np.int64)

    @property
    def touched_users(self) -> int:
        """How many distinct users have any history (lazy-store size)."""
        return len(self._hist)

    def history(self, user_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """(hist_ids [L], hist_mask [L]) — most recent clicks, zero-padded
        at the tail like the cascade's ``_fit_history`` convention."""
        clicks = self._hist.get(int(user_id), ())
        out_ids = np.zeros((self.hist_len,), np.int32)
        out_mask = np.zeros((self.hist_len,), np.float32)
        n = min(len(clicks), self.hist_len)
        if n:
            out_ids[:n] = clicks[-n:]
            out_mask[:n] = 1.0
        return out_ids, out_mask

    def click(self, user_id: int, item_id: int) -> None:
        """Append one click to the user's history (bounded at hist_len)."""
        hist = self._hist.setdefault(int(user_id), [])
        hist.append(int(item_id))
        if len(hist) > self.hist_len:
            del hist[:len(hist) - self.hist_len]


class FloodTrafficPlan:
    """Open-loop flood schedule: a FIXED offered rate (Poisson arrivals at
    ``offered_qps``), each request drawn from a shared
    :class:`ZipfUserPopulation` with a seeded value class — the load shape
    for driving a fleet PAST saturation, where a closed-loop driver would
    self-throttle and hide the knee.

    The population is shared (and mutated: every planned request appends
    its item to the user's history), so sweeping multiple plans over one
    population carries history continuity across offered-load points.
    Construction order is the determinism contract: building the same
    plans in the same order from a fresh same-seed population reproduces
    identical traffic (``fingerprint_data``).

    ``repeat_p`` > 0 makes each returning user REPLAY their previous
    request byte-identically with that probability (same ids/vals/history
    arrays, no history mutation) — the workload shape the serving result
    cache and in-flight coalescing monetize. Fresh randoms never produce
    byte-identical requests, so without this knob a flood cannot exercise
    the fast path at all. ``repeat_p=0`` (the default) draws NOTHING extra
    from the rng stream: existing plans reproduce bit-identically.
    """

    #: seeded value-class mix (lowest value first; must sum to 1)
    VALUE_MIX: Tuple[Tuple[str, float], ...] = (
        ("bulk", 0.3), ("normal", 0.6), ("critical", 0.1))

    def __init__(self, seed: int, *, offered_qps: float, duration_s: float,
                 population: ZipfUserPopulation,
                 field_size: int, feature_size: int, max_rows: int = 1,
                 repeat_p: float = 0.0):
        if offered_qps <= 0 or duration_s <= 0:
            raise ValueError(
                f"need positive offered_qps/duration_s, got "
                f"{offered_qps}/{duration_s}")
        if not 0.0 <= repeat_p < 1.0:
            raise ValueError(
                f"repeat_p must be in [0, 1), got {repeat_p}")
        self.seed = int(seed)
        self.offered_qps = float(offered_qps)
        self.duration_s = float(duration_s)
        self.repeat_p = float(repeat_p)
        self.population = population
        rng = np.random.default_rng(self.seed)
        classes = [c for c, _ in self.VALUE_MIX]
        probs = np.asarray([p for _, p in self.VALUE_MIX])
        requests: List[FloodRequest] = []
        last: dict = {}   # user -> (ids, vals, hist_ids, hist_mask)
        repeats = 0
        t, next_id = 0.0, 0
        while True:
            t += float(rng.exponential(1.0 / self.offered_qps))
            if t >= self.duration_s:
                break
            user = int(population.sample_users(rng, 1)[0])
            value = classes[int(rng.choice(len(classes), p=probs))]
            prev = last.get(user) if self.repeat_p > 0 else None
            if prev is not None and float(rng.random()) < self.repeat_p:
                # Byte-identical replay of this user's previous request:
                # same arrays, same history, NO click (the replay is the
                # same impression, not a new one).
                ids, vals, hist_ids, hist_mask = prev
                requests.append(FloodRequest(
                    t_s=round(t, 6), user_id=user, value=value,
                    first_id=next_id, ids=ids, vals=vals,
                    hist_ids=hist_ids, hist_mask=hist_mask))
                next_id += int(ids.shape[0])
                repeats += 1
                continue
            item = int(population.sample_items(rng, 1)[0]) \
                % max(1, feature_size)
            n = int(rng.integers(1, max_rows + 1)) if max_rows > 1 else 1
            ids = rng.integers(0, feature_size,
                               (n, field_size)).astype(np.int32)
            ids[:, 0] = item
            vals = rng.normal(size=(n, field_size)).astype(np.float32)
            hist_ids, hist_mask = population.history(user)
            requests.append(FloodRequest(
                t_s=round(t, 6), user_id=user, value=value,
                first_id=next_id, ids=ids, vals=vals,
                hist_ids=hist_ids, hist_mask=hist_mask))
            population.click(user, item)
            next_id += n
            if self.repeat_p > 0:
                last[user] = (ids, vals, hist_ids, hist_mask)
        self.requests: Tuple[FloodRequest, ...] = tuple(requests)
        self.total_rows = next_id
        self.repeat_requests = repeats

    def fingerprint_data(self) -> Tuple:
        """Deterministic digestable view for audit fingerprints."""
        return tuple(
            (r.t_s, r.user_id, r.value, r.first_id, r.ids.tobytes(),
             r.hist_ids.tobytes()) for r in self.requests)
