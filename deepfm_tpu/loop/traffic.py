"""Seeded diurnal traffic: a precomputed, bit-replayable request plan.

Production request rates are day-shaped; a drill that only ever sees a flat
rate never exercises the batcher's two regimes (deadline-bound at the
trough, max-batch-bound at the peak). The plan compresses one "day" into
``duration_s``: request arrivals follow a nonhomogeneous Poisson process
with rate ``base_qps + (peak_qps - base_qps) * sin^2(pi * t / duration)``
(trough at both ends, peak mid-run — the chaos schedule's 20-80% event
window lands its faults on the peak).

Everything — arrival times, request sizes, feature arrays, ground-truth
labels — is drawn up front from one seed, so two plans with equal seeds are
element-for-element identical and a drill replay serves byte-identical
traffic. Labels follow the same hidden-logistic model as
``libsvm.generate_synthetic_ctr`` (``hidden_seed`` fixes the ground truth
independently of the traffic seed), drawn per-impression from a
``(seed, impression_id)``-keyed rng — deterministic even if requests are
served out of order.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class PlannedRequest:
    t_s: float            # scheduled submit time, seconds from plan start
    first_id: int         # impression id of row 0 (rows are consecutive)
    ids: np.ndarray       # [n, F] int32 — exactly what serving scores
    vals: np.ndarray      # [n, F] float32
    labels: np.ndarray    # [n] float32 ground truth (known to the drill,
    #                       revealed to the joiner only after the delay)


class DiurnalTrafficPlan:
    """Precomputed request schedule + hidden-model ground truth."""

    def __init__(self, seed: int, *, duration_s: float, base_qps: float,
                 peak_qps: float, feature_size: int, field_size: int,
                 max_rows: int = 8, hidden_seed: int = 12345):
        if peak_qps < base_qps or base_qps <= 0:
            raise ValueError(
                f"need 0 < base_qps <= peak_qps, got {base_qps}/{peak_qps}")
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.hidden_w = np.random.default_rng(hidden_seed).normal(
            0, 1.0, size=feature_size).astype(np.float32)
        rng = np.random.default_rng(self.seed)
        requests: List[PlannedRequest] = []
        t, next_id = 0.0, 0
        while True:
            # Thinning (Lewis-Shedler): candidate arrivals at the peak
            # rate, accepted with probability rate(t)/peak — exact for a
            # nonhomogeneous Poisson process, and fully seeded.
            t += float(rng.exponential(1.0 / peak_qps))
            if t >= self.duration_s:
                break
            rate = base_qps + (peak_qps - base_qps) * (
                math.sin(math.pi * t / self.duration_s) ** 2)
            if float(rng.random()) >= rate / peak_qps:
                continue
            n = int(rng.integers(1, max_rows + 1))
            ids = rng.integers(0, feature_size,
                               (n, field_size)).astype(np.int32)
            vals = rng.normal(size=(n, field_size)).astype(np.float32)
            labels = np.empty((n,), np.float32)
            for r in range(n):
                labels[r] = self._draw_label(next_id + r, ids[r], vals[r])
            requests.append(PlannedRequest(
                t_s=round(t, 6), first_id=next_id,
                ids=ids, vals=vals, labels=labels))
            next_id += n
        self.requests: Tuple[PlannedRequest, ...] = tuple(requests)
        self.total_rows = next_id

    def _draw_label(self, impression_id: int, ids: np.ndarray,
                    vals: np.ndarray) -> float:
        logit = float(np.dot(self.hidden_w[ids], vals)) * 0.5
        p = 1.0 / (1.0 + math.exp(-logit))
        u = np.random.default_rng(
            (self.seed + 1) * 2_654_435_761 + int(impression_id)).random()
        return float(u < p)

    def fingerprint_data(self) -> Tuple:
        """Deterministic digestable view (times, ids, labels) for audit
        fingerprints."""
        return tuple((r.t_s, r.first_id, int(r.ids.shape[0]),
                      r.labels.tobytes()) for r in self.requests)
