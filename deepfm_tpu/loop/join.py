"""Delayed-label joining: impressions + late labels -> training shards.

The label side of the feedback loop. Clicks (labels) arrive seconds-to-
minutes after the impression was served; the joiner holds each impression
open for ``join_window_s`` of *logical* time, then emits it exactly once:

  * label arrives with delay <= window  -> joined (``labels_joined``);
  * window closes first                 -> emitted with the no-label default
    0.0 — the standard delayed-feedback negative assumption
    (``impressions_expired``);
  * label arrives with delay > window   -> the label is dropped and counted
    (``labels_past_window``), never retroactively applied;
  * duplicate impression id             -> the later copy is dropped
    (``duplicate_impressions``); duplicate or orphan labels count
    ``labels_late``.

All decisions are pure functions of (impression served_at, label arrival,
window) — the caller's pump cadence cannot change a single counter, which
is what makes a chaos drill's audit bit-reproducible across runs.

Emission is transactional and ordered: impression shard ``imp-NNNNN`` maps
to training shard ``<prefix>-NNNNN`` (same index), shards emit strictly in
index order (so the online stream admits them in the order they were
served), and each emission is manifest-sidecar-then-atomic-rename. The
existence of the output shard IS the joiner's durable state: a restarted
joiner skips any shard whose output exists — re-running it would produce
byte-identical output, so crash-between-manifest-and-shard heals by redo —
giving exactly-once emission across supervised restarts with no extra
journal. Torn impression shards (injected faults, torn tails) are healed
mid-join: the intact prefix is processed, the tail discarded and counted.
"""

from __future__ import annotations

import heapq
import json
import os
import random
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..data import example_codec, tfrecord
from .health import LoopHealth
from .impressions import iter_impressions


class SeededLabelFeed:
    """Deterministic delayed-label source.

    Each impression's delay is a pure function of ``(seed, impression_id)``
    — NOT of push order or wall time — so the same seed replays the same
    arrival schedule bit-exactly. ``push()`` registers the ground-truth
    label at serve time; ``poll(now)`` delivers every label whose arrival
    time has passed.
    """

    def __init__(self, seed: int, *, delay_min_s: float, delay_max_s: float):
        if delay_max_s < delay_min_s:
            raise ValueError(f"delay_max_s {delay_max_s} < delay_min_s "
                             f"{delay_min_s}")
        self.seed = int(seed)
        self.delay_min_s = float(delay_min_s)
        self.delay_max_s = float(delay_max_s)
        self._heap: List[Tuple[float, int, float]] = []  # (arrival, iid, y)

    def delay_for(self, impression_id: int) -> float:
        rng = random.Random(self.seed * 1_000_003 + int(impression_id))
        return rng.uniform(self.delay_min_s, self.delay_max_s)

    def push(self, impression_id: int, label: float,
             served_at_s: float) -> float:
        """Register a label; returns its (deterministic) arrival time."""
        arrival = float(served_at_s) + self.delay_for(impression_id)
        heapq.heappush(self._heap,
                       (arrival, int(impression_id), float(label)))
        return arrival

    def poll(self, now_s: float) -> List[Tuple[int, float, float]]:
        """-> [(impression_id, label, arrival_s)] for every label whose
        arrival is at or before ``now_s``, in arrival order."""
        out = []
        while self._heap and self._heap[0][0] <= now_s:
            arrival, iid, label = heapq.heappop(self._heap)
            out.append((iid, label, arrival))
        return out

    @property
    def pending(self) -> int:
        return len(self._heap)


class _Record:
    __slots__ = ("iid", "served_at", "ids", "vals", "label", "resolved")

    def __init__(self, iid: int, served_at: float,
                 ids: np.ndarray, vals: np.ndarray):
        self.iid = iid
        self.served_at = served_at
        self.ids = ids
        self.vals = vals
        self.label: Optional[float] = None
        self.resolved = False


class _Shard:
    __slots__ = ("index", "source", "records", "emitted")

    def __init__(self, index: int, source: str):
        self.index = index
        self.source = source
        self.records: List[_Record] = []
        self.emitted = False


_IMP_NAME = re.compile(r"^(?P<prefix>.+)-(?P<index>\d{5})\.tfrecords$")


class DelayedLabelJoiner:
    """Pump-driven joiner: call :meth:`pump` with a monotonically
    non-decreasing logical clock; emitted training-shard paths return."""

    DEFAULT_LABEL = 0.0

    def __init__(self, impression_dir: str, out_dir: str,
                 feed: SeededLabelFeed, *, join_window_s: float,
                 prefix: str = "tr", health: Optional[LoopHealth] = None,
                 verify_crc: bool = True):
        if join_window_s <= 0:
            raise ValueError(f"join_window_s must be > 0, got {join_window_s}")
        self._imp_dir = impression_dir
        self._out_dir = out_dir
        self._feed = feed
        self.join_window_s = float(join_window_s)
        self._prefix = prefix
        self.health = health if health is not None else LoopHealth()
        self._verify_crc = bool(verify_crc)
        os.makedirs(out_dir, exist_ok=True)
        self._ingested: set = set()            # impression shard basenames
        self._shards: Dict[int, _Shard] = {}   # index -> shard
        self._open: Dict[int, _Record] = {}    # iid -> unresolved record
        self._seen: set = set()                # every iid ever ingested
        self._served_at: Dict[int, float] = {}  # iid -> serve time (for the
        #                                         late-label classification)
        self.manifests: Dict[str, List[int]] = {}  # out path -> iid order
        self._next_emit = 0                    # in-order emission cursor

    # -- paths ----------------------------------------------------------
    def _out_path(self, index: int) -> str:
        return os.path.join(self._out_dir,
                            f"{self._prefix}-{index:05d}.tfrecords")

    def _manifest_path(self, index: int) -> str:
        return os.path.join(self._out_dir,
                            f".{self._prefix}-{index:05d}.manifest.json")

    # -- the pump -------------------------------------------------------
    def pump(self, now_s: float) -> List[str]:
        """Ingest new impression shards, apply due labels, expire closed
        windows, emit every fully-resolved shard (in index order).
        Returns the training-shard paths emitted by this call."""
        self._ingest()
        for iid, label, arrival in self._feed.poll(now_s):
            self._apply_label(iid, label, arrival)
        for rec in list(self._open.values()):
            if now_s - rec.served_at > self.join_window_s:
                self._resolve(rec)
        return self._emit_ready()

    def finalize(self, now_s: float) -> List[str]:
        """End of the run: one last pump, then force-expire everything
        still open (their windows would close with no label) and emit."""
        emitted = self.pump(now_s)
        for rec in list(self._open.values()):
            self._resolve(rec)
        return emitted + self._emit_ready()

    # -- internals ------------------------------------------------------
    def _ingest(self) -> None:
        try:
            names = sorted(os.listdir(self._imp_dir))
        except FileNotFoundError:
            return
        for name in names:
            if name.startswith(".") or name in self._ingested:
                continue
            m = _IMP_NAME.match(name)
            if m is None:
                continue
            index = int(m.group("index"))
            self._ingested.add(name)
            shard = _Shard(index, name)
            self._shards[index] = shard
            already_emitted = os.path.exists(self._out_path(index))
            for iid, served_at, ids, vals in iter_impressions(
                    os.path.join(self._imp_dir, name),
                    verify_crc=self._verify_crc, health=self.health):
                if iid in self._seen:
                    self.health.record("duplicate_impressions")
                    continue
                self._seen.add(iid)
                self._served_at[iid] = served_at
                rec = _Record(iid, served_at, ids, vals)
                shard.records.append(rec)
                if already_emitted:
                    rec.resolved = True     # durable state: output exists
                else:
                    self._open[iid] = rec
            if already_emitted:
                # Restart recovery: the emission already happened; reload
                # its manifest so audits keep working across the restart.
                shard.emitted = True
                try:
                    with open(self._manifest_path(index),
                              encoding="utf-8") as f:
                        manifest = json.load(f)
                    self.manifests[self._out_path(index)] = [
                        int(i) for i in manifest["impressions"]]
                except (OSError, ValueError, KeyError):
                    pass
                self._next_emit = max(self._next_emit, index + 1)

    def _apply_label(self, iid: int, label: float, arrival: float) -> None:
        rec = self._open.get(iid)
        if rec is not None:
            delay = arrival - rec.served_at
            if delay <= self.join_window_s:
                rec.label = float(label)
                rec.resolved = True
                del self._open[iid]
                self.health.record("labels_joined")
            else:
                self._resolve(rec)
                self.health.record("labels_past_window")
            return
        served = self._served_at.get(iid)
        if served is not None and arrival - served > self.join_window_s:
            # The record was already expired-and-emitted; the label is past
            # the window either way — same counter as the unexpired case,
            # so pump cadence never changes the audit.
            self.health.record("labels_past_window")
        else:
            self.health.record("labels_late")

    def _resolve(self, rec: _Record) -> None:
        """Close a record with the no-label default (delayed-feedback
        negative)."""
        rec.resolved = True
        self._open.pop(rec.iid, None)
        self.health.record("impressions_expired")

    def _emit_ready(self) -> List[str]:
        emitted = []
        while True:
            shard = self._shards.get(self._next_emit)
            if shard is None or shard.emitted \
                    or not all(r.resolved for r in shard.records):
                break
            emitted.append(self._emit(shard))
            self._next_emit += 1
        return emitted

    def _emit(self, shard: _Shard) -> str:
        out_path = self._out_path(shard.index)
        manifest = {
            "source": shard.source,
            "impressions": [r.iid for r in shard.records],
            "labels": [float(r.label if r.label is not None
                             else self.DEFAULT_LABEL)
                       for r in shard.records],
        }
        # Manifest first, shard second; both atomic. A crash between the
        # two redoes this emission from scratch (byte-identical), so the
        # pair is consistent once the shard exists.
        mpath = self._manifest_path(shard.index)
        tmp = mpath + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, mpath)
        tmp_shard = os.path.join(
            self._out_dir, f".{self._prefix}-{shard.index:05d}.part")
        with tfrecord.TFRecordWriter(tmp_shard) as w:
            for rec in shard.records:
                label = (rec.label if rec.label is not None
                         else self.DEFAULT_LABEL)
                w.write(example_codec.encode_ctr_example(
                    label, rec.ids, rec.vals))
        with open(tmp_shard, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp_shard, out_path)
        shard.emitted = True
        self.manifests[out_path] = [r.iid for r in shard.records]
        self.health.record("joined_shards")
        self.health.record("records_emitted", len(shard.records))
        return out_path

    # -- introspection --------------------------------------------------
    @property
    def open_impressions(self) -> int:
        return len(self._open)

    @property
    def emitted_shards(self) -> List[str]:
        return [self._out_path(i) for i, s in sorted(self._shards.items())
                if s.emitted]
