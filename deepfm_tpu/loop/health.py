"""Feedback-loop health accounting, mirroring ``data.health.DataHealth``.

One thread-safe object the impression logger and the delayed-label joiner
both stamp into; ``snapshot()`` is what the production drill writes into
``PRODUCTION_r0N.json``. Counters are typed (one name per failure mode) so
a drill can assert *exactly* how many duplicates/late/past-window events
occurred — "some labels were dropped" is not an auditable statement.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..obs import metrics as metrics_lib

SCALAR_COUNTERS = (
    "impressions_logged",       # rows written to impression shards
    "impression_shards",        # impression shards atomically published
    "duplicate_impressions",    # same impression id logged again (dropped)
    "labels_joined",            # label arrived within the join window
    "labels_past_window",       # label arrived after the window (dropped,
                                # impression already emitted as unlabeled)
    "labels_late",              # label for an unknown or already-labeled
                                # impression (duplicate/orphan label)
    "impressions_expired",      # emitted with the no-label default after
                                # the window closed (delayed-feedback
                                # negatives; late positives land in
                                # labels_past_window)
    "torn_impression_shards",   # truncated shard healed mid-join (intact
                                # prefix processed, tail discarded)
    "joined_shards",            # training shards atomically emitted
    "records_emitted",          # rows in emitted training shards
)


class LoopHealth:
    """Thread-safe counters for the serve->log->join->train feedback loop."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        for name in SCALAR_COUNTERS:
            setattr(self, name, 0)
        metrics_lib.auto_register("loop_health", self)

    def record(self, counter: str, n: int = 1) -> None:
        if counter not in SCALAR_COUNTERS:
            raise ValueError(f"unknown loop counter {counter!r}")
        with self._lock:
            setattr(self, counter, getattr(self, counter) + int(n))

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: int(getattr(self, k)) for k in SCALAR_COUNTERS}

    def merge_into(self, totals: Dict[str, int]) -> None:
        snap = self.snapshot()
        for key in SCALAR_COUNTERS:
            totals[key] = totals.get(key, 0) + snap[key]

    def summary(self) -> str:
        snap = self.snapshot()
        return " ".join(f"{k}={v}" for k, v in snap.items() if v)
