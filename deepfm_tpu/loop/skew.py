"""Training/serving skew check: one logged record, two decode paths, zero
byte drift.

The classic failure of online CTR systems is silent: the serving path and
the training path disagree about what a feature vector *was* (different
casts, different default fills, different key aliases), AUC decays, and
nothing raises. Here the check is executable: for every audited record, the
feature arrays the serving engine actually scored (kept by the drill) must
be bit-identical to what the TRAINING decoder reads back from the emitted
training shard (``example_codec.decode_ctr_example`` — the golden-pinned
bit-exact mirror of the native decoder the pipeline runs).

"Bit-identical" means: ids equal as integers (serving submits int32, the
on-disk schema is int64 — a value drift, not a width drift, is what skew
is), and vals equal as raw float32 bytes (no tolerance: a single ULP of
drift means the paths diverged).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..data import example_codec, tfrecord


class SkewChecker:
    """Audit emitted training shards against the served feature arrays.

    ``served`` maps impression id -> (ids, vals) exactly as submitted to
    the serving engine. Shard record order comes from the joiner's
    manifest sidecar (record k of the shard is impression ``manifest[k]``).
    """

    def __init__(self, served: Dict[int, Tuple[np.ndarray, np.ndarray]]):
        self._served = served
        self.records_audited = 0
        self.mismatches: List[str] = []

    def audit_shard(self, shard_path: str,
                    impression_order: Optional[List[int]] = None) -> int:
        """Audit every record of one emitted shard; returns the number
        audited. Mismatches accumulate in ``self.mismatches`` (empty ==
        bit-identical)."""
        if impression_order is None:
            manifest_path = os.path.join(
                os.path.dirname(shard_path),
                "." + os.path.basename(shard_path).replace(
                    ".tfrecords", ".manifest.json"))
            with open(manifest_path, encoding="utf-8") as f:
                impression_order = [int(i)
                                    for i in json.load(f)["impressions"]]
        k = 0
        for rec in tfrecord.iter_records(shard_path):
            if k >= len(impression_order):
                self.mismatches.append(
                    f"{shard_path}: record {k} beyond manifest "
                    f"({len(impression_order)} entries)")
                break
            iid = impression_order[k]
            served = self._served.get(iid)
            if served is None:
                self.mismatches.append(
                    f"{shard_path}[{k}]: impression {iid} never served")
                k += 1
                continue
            s_ids, s_vals = served
            feats = example_codec.decode_example(rec)
            _, t_label = feats[example_codec.LABEL_KEY]
            t_ids = np.asarray(feats[example_codec.IDS_KEY][1], np.int64)
            t_vals = np.asarray(feats[example_codec.VALS_KEY][1], np.float32)
            if not np.array_equal(np.asarray(s_ids, np.int64), t_ids):
                self.mismatches.append(
                    f"{shard_path}[{k}] impression {iid}: ids drifted "
                    f"(served {np.asarray(s_ids).tolist()}, "
                    f"decoded {t_ids.tolist()})")
            elif np.asarray(s_vals, np.float32).tobytes() != t_vals.tobytes():
                self.mismatches.append(
                    f"{shard_path}[{k}] impression {iid}: vals drifted "
                    f"(float32 bytes differ)")
            else:
                # Also cross-check the fixed-schema fast path the pipeline
                # actually calls — the two training decoders must agree
                # with each other AND with serving.
                label2, ids2, vals2 = example_codec.decode_ctr_example(
                    rec, int(t_ids.shape[0]))
                if (not np.array_equal(ids2, t_ids)
                        or vals2.tobytes() != t_vals.tobytes()
                        or label2 != float(np.asarray(t_label)[0])):
                    self.mismatches.append(
                        f"{shard_path}[{k}]: generic and fixed-schema "
                        "decoders disagree")
            self.records_audited += 1
            k += 1
        if k < len(impression_order):
            self.mismatches.append(
                f"{shard_path}: {len(impression_order) - k} manifest "
                "entries have no record")
        return k

    @property
    def ok(self) -> bool:
        return not self.mismatches and self.records_audited > 0
