"""Impression logging: served requests written back as TFRecord shards.

The serving side of the feedback loop. Every served row becomes one
impression record — the *exact* feature arrays the model scored (the joiner
re-encodes them unchanged into training shards, which is what makes the
training/serving skew check meaningful: one byte path, end to end).

Shards are produced the only way the online stream source accepts: full
write to a dot-prefixed temp name in the target directory, fsync, then
``os.replace`` — a reader never sees a half-written shard, and shard names
ascend (``imp-00000.tfrecords``, ...) so downstream join order is the log
order.

Record schema = the CTR training schema (``label``/``ids``/``values``) plus
two loop-only keys the joiner strips: ``impression_id`` (int64, unique per
row) and ``served_at_us`` (int64 microseconds on the caller's clock —
logical drill time or wall time, the logger does not care). The placeholder
label is 0.0 until the joiner attaches the real one.

Correlation (obs.trace): callers may additionally stamp ``trace_id`` (the
request's correlation id) and ``model_version`` (the publish version that
scored the row). Both are optional int64 keys — ``decode_impression`` reads
only the required keys, and the joiner re-encodes just label/ids/values, so
stamped shards stay byte-compatible downstream.

Experimentation (serve.experiment): two more optional keys ride the same
pattern — ``arm`` (int64: 0 control / 1 challenger, the traffic-split arm
that produced the row; shadow-lane challenger responses are logged under
their own impression ids with arm=1) and ``pred`` (float32: the probability
the arm's model served). ``pred`` is what makes per-arm health replayable
from the log alone: offline recomputation of AUC/calibration from
(arm, pred, joined label) must match the online accumulation bit-exactly,
no model re-run required.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..data import example_codec, tfrecord
from .health import LoopHealth

IMPRESSION_ID_KEY = "impression_id"
SERVED_AT_KEY = "served_at_us"
TRACE_ID_KEY = "trace_id"
MODEL_VERSION_KEY = "model_version"
ARM_KEY = "arm"
PRED_KEY = "pred"


def encode_impression(impression_id: int, served_at_s: float,
                      ids: np.ndarray, vals: np.ndarray, *,
                      trace_id: Optional[int] = None,
                      model_version: Optional[int] = None,
                      arm: Optional[int] = None,
                      pred: Optional[float] = None) -> bytes:
    features = {
        example_codec.LABEL_KEY: (np.asarray([0.0], np.float32), "float"),
        example_codec.IDS_KEY: (np.asarray(ids, np.int64), "int64"),
        example_codec.VALS_KEY: (np.asarray(vals, np.float32), "float"),
        IMPRESSION_ID_KEY: (
            np.asarray([int(impression_id)], np.int64), "int64"),
        SERVED_AT_KEY: (
            np.asarray([int(round(served_at_s * 1e6))], np.int64), "int64"),
    }
    if trace_id is not None:
        features[TRACE_ID_KEY] = (
            np.asarray([int(trace_id)], np.int64), "int64")
    if model_version is not None:
        features[MODEL_VERSION_KEY] = (
            np.asarray([int(model_version)], np.int64), "int64")
    if arm is not None:
        features[ARM_KEY] = (np.asarray([int(arm)], np.int64), "int64")
    if pred is not None:
        features[PRED_KEY] = (np.asarray([pred], np.float32), "float")
    return example_codec.encode_example(features)


def read_correlation(buf: bytes) -> Tuple[Optional[int], Optional[int]]:
    """-> (trace_id, model_version) of one impression record (None when the
    writer did not stamp them)."""
    feats = example_codec.decode_example(buf)
    out = []
    for key in (TRACE_ID_KEY, MODEL_VERSION_KEY):
        entry = feats.get(key)
        out.append(None if entry is None else int(np.asarray(entry[1])[0]))
    return out[0], out[1]


def read_experiment(buf: bytes) -> Tuple[Optional[int], Optional[float]]:
    """-> (arm, pred) of one impression record (None when the writer did
    not stamp them). ``pred`` comes back as the float32 the arm served —
    the exact value per-arm health recomputation must use."""
    feats = example_codec.decode_example(buf)
    arm_entry = feats.get(ARM_KEY)
    pred_entry = feats.get(PRED_KEY)
    arm = None if arm_entry is None else int(np.asarray(arm_entry[1])[0])
    pred = (None if pred_entry is None
            else float(np.asarray(pred_entry[1], np.float32)[0]))
    return arm, pred


def decode_impression(buf: bytes) -> Tuple[int, float, np.ndarray, np.ndarray]:
    """-> (impression_id, served_at_s, ids int64[F], vals float32[F])."""
    feats = example_codec.decode_example(buf)
    try:
        _, iid = feats[IMPRESSION_ID_KEY]
        _, at_us = feats[SERVED_AT_KEY]
        _, ids = feats[example_codec.IDS_KEY]
        _, vals = feats[example_codec.VALS_KEY]
    except KeyError:
        raise ValueError(
            f"not an impression record: found keys {sorted(feats)}") from None
    return (int(np.asarray(iid)[0]), float(np.asarray(at_us)[0]) / 1e6,
            np.asarray(ids, np.int64), np.asarray(vals, np.float32))


def iter_impressions(path: str, *, verify_crc: bool = True,
                     health: Optional[LoopHealth] = None
                     ) -> Iterator[Tuple[int, float, np.ndarray, np.ndarray]]:
    """Decode one impression shard; a torn tail is healed (intact prefix
    yielded, tail discarded, ``torn_impression_shards`` counted)."""
    from ..data import health as health_lib
    policy = health_lib.BadRecordPolicy("skip")
    for rec in tfrecord.iter_records(path, verify_crc=verify_crc,
                                     policy=policy):
        yield decode_impression(rec)
    if policy.skips and health is not None:
        health.record("torn_impression_shards")


class ImpressionLogger:
    """Append impressions; publish a shard via atomic rename every
    ``shard_records`` rows (and on :meth:`flush`/:meth:`close`)."""

    def __init__(self, out_dir: str, *, shard_records: int = 64,
                 prefix: str = "imp", health: Optional[LoopHealth] = None):
        if shard_records < 1:
            raise ValueError(f"shard_records must be >= 1, got {shard_records}")
        os.makedirs(out_dir, exist_ok=True)
        self._dir = out_dir
        self._shard_records = int(shard_records)
        self._prefix = prefix
        self.health = health if health is not None else LoopHealth()
        self._index = self._next_free_index()
        self._writer: Optional[tfrecord.TFRecordWriter] = None
        self._tmp_path: Optional[str] = None
        self._in_shard = 0
        self.shards: List[str] = []     # final paths, publish order

    def _next_free_index(self) -> int:
        idx = 0
        while os.path.exists(self._final_path(idx)):
            idx += 1
        return idx

    def _final_path(self, idx: int) -> str:
        return os.path.join(self._dir, f"{self._prefix}-{idx:05d}.tfrecords")

    def log(self, impression_id: int, ids: np.ndarray, vals: np.ndarray,
            served_at_s: float, *, trace_id: Optional[int] = None,
            model_version: Optional[int] = None,
            arm: Optional[int] = None,
            pred: Optional[float] = None) -> None:
        """Log one served row. ``ids``/``vals`` are the arrays the engine
        scored ([F], any integer/float32 dtype)."""
        if self._writer is None:
            self._tmp_path = os.path.join(
                self._dir, f".{self._prefix}-{self._index:05d}.part")
            self._writer = tfrecord.TFRecordWriter(self._tmp_path)
            self._in_shard = 0
        self._writer.write(
            encode_impression(impression_id, served_at_s, ids, vals,
                              trace_id=trace_id,
                              model_version=model_version,
                              arm=arm, pred=pred))
        self._in_shard += 1
        self.health.record("impressions_logged")
        if self._in_shard >= self._shard_records:
            self.flush()

    def log_request(self, first_id: int, ids: np.ndarray, vals: np.ndarray,
                    served_at_s: float, *,
                    trace_id: Optional[int] = None,
                    model_version: Optional[int] = None,
                    arm: Optional[int] = None,
                    preds: Optional[np.ndarray] = None) -> List[int]:
        """Log every row of one request ``(ids[n,F], vals[n,F])`` with
        consecutive impression ids starting at ``first_id``; returns them.
        ``trace_id``/``model_version``/``arm`` stamp every row of the
        request (the engine resolves one model version per flush; the
        router one arm per request); ``preds`` ([n] probabilities) stamps
        each row with the probability its arm served."""
        out = []
        for r in range(int(ids.shape[0])):
            iid = int(first_id) + r
            self.log(iid, ids[r], vals[r], served_at_s,
                     trace_id=trace_id, model_version=model_version,
                     arm=arm,
                     pred=None if preds is None else float(preds[r]))
            out.append(iid)
        return out

    def flush(self) -> Optional[str]:
        """Seal the open shard: fsync, atomic rename, return the final path
        (None when nothing is buffered)."""
        if self._writer is None:
            return None
        self._writer.flush()
        with open(self._tmp_path, "rb") as f:
            os.fsync(f.fileno())
        self._writer.close()
        final = self._final_path(self._index)
        os.replace(self._tmp_path, final)
        self._writer, self._tmp_path = None, None
        self._index += 1
        self.shards.append(final)
        self.health.record("impression_shards")
        return final

    def close(self) -> Optional[str]:
        return self.flush()

    def __enter__(self) -> "ImpressionLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
