"""The drill's metrics plane: windowed online AUC and staleness summaries.

Numpy-only (no jax) so the feedback layer stays importable in light
processes. The AUC here is the exact Mann-Whitney statistic with midrank
tie handling — same semantics as ``train.metrics.auc_numpy_reference``,
reimplemented without the jax-importing module so the loop layer stays
device-free.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def exact_auc(probs: Sequence[float], labels: Sequence[float]) -> float:
    """Exact ROC AUC (midranks for ties); NaN when one class is absent."""
    p = np.asarray(probs, np.float64)
    y = np.asarray(labels, np.float64) > 0.5
    n_pos = int(y.sum())
    n_neg = int(y.size - n_pos)
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(p, kind="mergesort")
    ranks = np.empty(p.size, np.float64)
    sorted_p = p[order]
    i = 0
    while i < p.size:
        j = i
        while j + 1 < p.size and sorted_p[j + 1] == sorted_p[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0  # midrank, 1-based
        i = j + 1
    rank_sum_pos = float(ranks[y].sum())
    return (rank_sum_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)


def windowed_auc(samples: List[Tuple[float, float, float, float]],
                 n_windows: int, duration_s: float) -> List[Dict[str, Any]]:
    """Windowed online-vs-frozen AUC over ``(t_s, label, online_prob,
    baseline_prob)`` samples: the metric production watches to see the
    online model pull away from (or regress against) the frozen baseline.
    Windows split logical time evenly over ``[0, duration_s]``."""
    out = []
    for w in range(int(n_windows)):
        lo = duration_s * w / n_windows
        hi = duration_s * (w + 1) / n_windows
        in_w = [s for s in samples if lo <= s[0] < hi]
        labels = [s[1] for s in in_w]
        entry = {
            "window": w,
            "t_range_s": [round(lo, 3), round(hi, 3)],
            "n": len(in_w),
            "positives": int(sum(1 for y in labels if y > 0.5)),
            "auc_online": None,
            "auc_frozen_baseline": None,
        }
        if in_w:
            a_on = exact_auc([s[2] for s in in_w], labels)
            a_base = exact_auc([s[3] for s in in_w], labels)
            entry["auc_online"] = (round(a_on, 4)
                                   if a_on == a_on else None)
            entry["auc_frozen_baseline"] = (round(a_base, 4)
                                            if a_base == a_base else None)
        out.append(entry)
    return out


def arm_health(samples: Sequence[Tuple[int, float, float, float]]
               ) -> Dict[int, Dict[str, Any]]:
    """Per-arm guardrail metrics over ``(arm, label, prob, latency_ms)``
    samples — the health window the promotion controller judges
    (``train.promote.evaluate_gates``).

    Per arm: ``n``, ``auc`` (exact, None on a one-class window),
    ``p99_latency_ms``, ``nonfinite`` (count of NaN/Inf probs — those rows
    are EXCLUDED from auc/calibration so one poisoned prediction cannot
    also poison the other gates), ``mean_pred`` / ``observed_ctr`` /
    ``calibration_err`` (|mean predicted − observed CTR|).

    Deterministic and representation-stable: inputs are cast to float64
    from whatever the caller logged (the impression log stamps float32
    preds), sums run in sorted-sample order as given, and every reported
    float is rounded — so the online accumulation and a pure offline
    recomputation from the impression log produce bit-identical dicts.
    """
    by_arm: Dict[int, List[Tuple[float, float, float]]] = {}
    for arm, label, prob, latency_ms in samples:
        by_arm.setdefault(int(arm), []).append(
            (float(label), float(prob), float(latency_ms)))
    out: Dict[int, Dict[str, Any]] = {}
    for arm in sorted(by_arm):
        rows = by_arm[arm]
        probs = np.asarray([r[1] for r in rows], np.float64)
        labels = np.asarray([r[0] for r in rows], np.float64)
        lats = [r[2] for r in rows]
        finite = np.isfinite(probs)
        nonfinite = int(probs.size - int(finite.sum()))
        fp, fl = probs[finite], labels[finite]
        auc = exact_auc(fp, fl) if fp.size else float("nan")
        mean_pred = float(fp.mean()) if fp.size else None
        ctr = float(fl.mean()) if fl.size else None
        p99 = percentile(lats, 99)
        out[arm] = {
            "arm": arm,
            "n": len(rows),
            "auc": round(auc, 4) if auc == auc else None,
            "p99_latency_ms": round(p99, 3) if p99 is not None else None,
            "nonfinite": nonfinite,
            "mean_pred": (round(mean_pred, 6)
                          if mean_pred is not None else None),
            "observed_ctr": round(ctr, 6) if ctr is not None else None,
            "calibration_err": (round(abs(mean_pred - ctr), 6)
                                if mean_pred is not None and ctr is not None
                                else None),
        }
    return out


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    if not len(values):
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


def staleness_summary(staleness_s: Sequence[float]) -> Dict[str, Any]:
    """p50/p95/max of end-to-end staleness samples (impression served ->
    first servable model that trained on it), in seconds."""
    return {
        "n": int(len(staleness_s)),
        "staleness_p50_s": (round(percentile(staleness_s, 50), 3)
                            if len(staleness_s) else None),
        "staleness_p95_s": (round(percentile(staleness_s, 95), 3)
                            if len(staleness_s) else None),
        "staleness_max_s": (round(float(max(staleness_s)), 3)
                            if len(staleness_s) else None),
    }
