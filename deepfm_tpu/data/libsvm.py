"""LibSVM text <-> TFRecord conversion (component H of the reference).

Reference behavior (``tools/libsvm_to_tfrecord.py:5-37``): each input line
``"label id:val id:val ..."`` becomes one ``Example{label: float,
ids: int64[F], values: float[F]}``. This implementation adds what the
reference's converter lacks: sharded output, field-size validation, a reverse
(TFRecord->LibSVM) path for round-trip testing, and a synthetic-data
generator for tests/benchmarks.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import example_codec, tfrecord


def parse_libsvm_line(line: str) -> Tuple[float, np.ndarray, np.ndarray]:
    parts = line.strip().split()
    if not parts:
        raise ValueError("empty LibSVM line")
    label = float(parts[0])
    ids = np.empty(len(parts) - 1, dtype=np.int64)
    vals = np.empty(len(parts) - 1, dtype=np.float32)
    for i, tok in enumerate(parts[1:]):
        k, _, v = tok.partition(":")
        ids[i] = int(k)
        vals[i] = float(v)
    return label, ids, vals


def format_libsvm_line(label: float, ids: np.ndarray, vals: np.ndarray) -> str:
    toks = [f"{label:g}"] + [f"{int(i)}:{float(v):g}" for i, v in zip(ids, vals)]
    return " ".join(toks)


def convert_libsvm_file(
    in_path: str,
    out_path: str,
    *,
    field_size: Optional[int] = None,
    num_shards: int = 1,
) -> int:
    """Convert a LibSVM text file to TFRecord file(s). Returns record count.

    With ``num_shards > 1``, writes ``{out_path}-00000-of-0000N`` shards
    round-robin (the layout `ShardedByS3Key` distribution expects).
    """
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    if num_shards == 1:
        writers = [tfrecord.TFRecordWriter(out_path)]
    else:
        writers = [
            tfrecord.TFRecordWriter(f"{out_path}-{s:05d}-of-{num_shards:05d}")
            for s in range(num_shards)
        ]
    n = 0
    try:
        with open(in_path, "r") as f:
            for line in f:
                if not line.strip():
                    continue
                label, ids, vals = parse_libsvm_line(line)
                if field_size is not None and ids.shape[0] != field_size:
                    raise ValueError(
                        f"line {n}: expected {field_size} features, got {ids.shape[0]}")
                writers[n % num_shards].write(
                    example_codec.encode_ctr_example(label, ids, vals))
                n += 1
    finally:
        for w in writers:
            w.close()
    return n


def tfrecord_to_libsvm(in_path: str, out_path: str, field_size: int) -> int:
    """Reverse conversion, for round-trip tests."""
    n = 0
    with open(out_path, "w") as out:
        for rec in tfrecord.iter_records(in_path):
            label, ids, vals = example_codec.decode_ctr_example(rec, field_size)
            out.write(format_libsvm_line(label, ids, vals) + "\n")
            n += 1
    return n


def generate_synthetic_ctr(
    out_dir: str,
    *,
    num_files: int,
    examples_per_file: int,
    feature_size: int,
    field_size: int,
    prefix: str = "tr",
    seed: int = 0,
    hidden_seed: int = 12345,
    num_labels: int = 1,
    history: int = 0,
) -> List[str]:
    """Write synthetic Criteo-shaped TFRecords with a learnable signal.

    Labels follow a logistic model over a hidden random weight vector so AUC
    above 0.5 is achievable — used by integration tests and the benchmark
    harness (reference trained on real Criteo; shape/hparams from
    ``deepfm-sagemaker-ps-cpu.ipynb:82-90``). ``hidden_seed`` fixes the
    label-generating model independently of ``seed`` (the example sampler),
    so train/eval/test splits generated with different seeds share the same
    ground-truth mapping.

    With ``num_labels=2`` each Example additionally carries a ``label2``
    (conversion) key generated from a SECOND hidden vector and gated on the
    click (label2 can be 1 only when label is 1 — the ESMM entire-space
    setup), so both tasks are learnable and realistically correlated. With
    the default ``num_labels=1`` no extra rng draws happen and the output
    is byte-identical to previous versions.

    With ``history > 0`` each Example additionally carries a ragged
    click-gated ``hist_ids``/``hist_vals`` pair: the history is sampled from
    the ids of PREVIOUSLY CLICKED examples in the stream (a rolling pool, so
    early records naturally have empty histories), its length is uniform in
    ``[0, history]``, and the click logit gains an affinity term between the
    history and the candidate through the same hidden vector — target
    attention over the history is therefore genuinely learnable. With the
    default ``history=0`` no extra rng draws happen and the output is
    byte-identical.
    """
    if num_labels not in (1, 2):
        raise ValueError(f"num_labels must be 1 or 2, got {num_labels}")
    if history < 0:
        raise ValueError(f"history must be >= 0, got {history}")
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    hidden_w = np.random.default_rng(hidden_seed).normal(
        0, 1.0, size=feature_size).astype(np.float32)
    hidden_w2 = np.random.default_rng(hidden_seed + 1).normal(
        0, 1.0, size=feature_size).astype(np.float32)
    clicked_pool: List[int] = []  # rolling pool of clicked ids (click-gated)
    paths = []
    for fi in range(num_files):
        path = os.path.join(out_dir, f"{prefix}_{fi:04d}.tfrecords")
        paths.append(path)
        with tfrecord.TFRecordWriter(path) as w:
            for _ in range(examples_per_file):
                ids = rng.integers(0, feature_size, size=field_size, dtype=np.int64)
                vals = rng.normal(0, 1, size=field_size).astype(np.float32)
                logit = float(np.dot(hidden_w[ids], vals)) * 0.5
                hist_ids = None
                if history > 0:
                    hist_n = min(int(rng.integers(0, history + 1)),
                                 len(clicked_pool))
                    if hist_n > 0:
                        pick = rng.integers(0, len(clicked_pool), size=hist_n)
                        hist_ids = np.asarray(
                            [clicked_pool[j] for j in pick], np.int64)
                        # history/candidate affinity through the hidden model
                        logit += float(np.mean(hidden_w[hist_ids])) \
                            * float(np.mean(hidden_w[ids])) * 2.0
                label = float(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
                if history > 0 and label > 0:
                    clicked_pool.extend(int(i) for i in ids)
                    if len(clicked_pool) > 4096:
                        del clicked_pool[:-4096]
                label2 = None
                if num_labels == 2:
                    label2 = 0.0
                    if label > 0:
                        logit2 = float(np.dot(hidden_w2[ids], vals)) * 0.5
                        label2 = float(
                            rng.random() < 1.0 / (1.0 + np.exp(-logit2)))
                w.write(example_codec.encode_ctr_example(
                    label, ids, vals, label2=label2, hist_ids=hist_ids))
    return paths
