from . import example_codec, libsvm, pipeline, sharding, tfrecord  # noqa: F401
from .pipeline import Batch, CtrPipeline, StreamingCtrPipeline  # noqa: F401
from .sharding import ShardSpec, shard_files  # noqa: F401
