"""Data-plane health accounting: bad-record policy + per-file fault stats.

The reference delegated corrupt-shard handling to TF (silently fatal) and
transient-read handling to SageMaker job restarts. Here both are explicit:
:class:`BadRecordPolicy` decides raise-vs-skip for corrupt/truncated frames
(with a skip budget), and :class:`DataHealth` aggregates per-file skip and
retry counters so the training loop can log them every ``log_steps`` and at
epoch end. Thread-safe — the pooled decode path and the prefetch thread both
report into the same object.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..obs import metrics as metrics_lib

SCALAR_COUNTERS = ("read_retries", "bad_records", "truncated_tails",
                   "bytes_discarded", "late_files", "duplicate_files",
                   "torn_files")


class DataHealth:
    """Thread-safe counters for I/O faults survived by the pipeline."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.read_retries = 0        # transient read errors healed by retry
        self.bad_records = 0         # corrupt records skipped
        self.truncated_tails = 0     # files whose tail was discarded
        self.bytes_discarded = 0     # payload bytes dropped with bad frames
        # Unbounded-stream-source counters (data/stream.py): shards that
        # arrived sorting before already-consumed ones (admitted anyway),
        # shards whose name was already consumed (skipped), and shards that
        # vanished or shrank mid-read (partial tail discarded, stream heals).
        self.late_files = 0
        self.duplicate_files = 0
        self.torn_files = 0
        self.per_file: Dict[str, Dict[str, int]] = {}
        self._dirty = False
        # Unified registry (obs.metrics): snapshot() is the metric surface.
        metrics_lib.auto_register("data_health", self)

    def _file(self, path: str) -> Dict[str, int]:
        entry = self.per_file.get(path)
        if entry is None:
            entry = {"retries": 0, "skipped": 0}
            self.per_file[path] = entry
        return entry

    def record_retry(self, path: str) -> None:
        with self._lock:
            self.read_retries += 1
            self._file(path)["retries"] += 1
            self._dirty = True

    def record_bad_record(self, path: str, nbytes: int = 0, *,
                          truncated: bool = False) -> None:
        with self._lock:
            self.bad_records += 1
            self.bytes_discarded += int(nbytes)
            if truncated:
                self.truncated_tails += 1
            self._file(path)["skipped"] += 1
            self._dirty = True

    def record_late_file(self, path: str) -> None:
        with self._lock:
            self.late_files += 1
            self._dirty = True

    def record_duplicate_file(self, path: str) -> None:
        with self._lock:
            self.duplicate_files += 1
            self._dirty = True

    def record_torn_file(self, path: str, nbytes: int = 0) -> None:
        with self._lock:
            self.torn_files += 1
            self.bytes_discarded += int(nbytes)
            self._file(path)["skipped"] += 1
            self._dirty = True

    @property
    def total_events(self) -> int:
        with self._lock:
            return self.read_retries + self.bad_records

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {k: getattr(self, k)
                                      for k in SCALAR_COUNTERS}
            out["per_file"] = {k: dict(v) for k, v in self.per_file.items()}
            return out

    def apply_delta(self, delta: Dict[str, object]) -> None:
        """Add a snapshot-shaped increment into these counters — the
        cross-process merge used by the input service (workers send
        cumulative snapshots; the parent applies successive differences,
        so restransmission-free aggregation stays exact)."""
        with self._lock:
            changed = False
            for key in SCALAR_COUNTERS:
                inc = int(delta.get(key, 0))  # type: ignore[arg-type]
                if inc:
                    setattr(self, key, getattr(self, key) + inc)
                    changed = True
            for path, c in delta.get("per_file", {}).items():  # type: ignore[union-attr]
                entry = self._file(path)
                for k in ("retries", "skipped"):
                    entry[k] += int(c.get(k, 0))
                changed = changed or any(c.values())
            self._dirty = self._dirty or changed

    def merge_into(self, totals: Dict[str, int]) -> None:
        """Accumulate scalar counters into ``totals`` (for cross-epoch sums)."""
        snap = self.snapshot()
        for key in SCALAR_COUNTERS:
            totals[key] = totals.get(key, 0) + int(snap[key])  # type: ignore[arg-type]

    def summary(self) -> str:
        snap = self.snapshot()
        worst = sorted(
            snap["per_file"].items(),  # type: ignore[union-attr]
            key=lambda kv: -(kv[1]["retries"] + kv[1]["skipped"]))[:3]
        files = ", ".join(
            f"{p}(retries={c['retries']},skipped={c['skipped']})"
            for p, c in worst)
        scalars = " ".join(f"{k}={snap[k]}" for k in SCALAR_COUNTERS
                           if k in ("read_retries", "bad_records",
                                    "truncated_tails", "bytes_discarded")
                           or snap[k])
        return scalars + (f" [{files}]" if files else "")

    def consume_dirty(self) -> bool:
        """True once per batch of new events — drives log_steps-cadence logs."""
        with self._lock:
            dirty, self._dirty = self._dirty, False
            return dirty


class BadRecordPolicy:
    """raise|skip decision for corrupt or truncated TFRecord frames.

    ``skip`` mode drops the offending record (or file tail, when framing can
    no longer resync) and counts it in :class:`DataHealth`; ``max_bad`` > 0
    bounds the total skips (budget exceeded → raise so a systemically
    corrupt dataset cannot silently train on a fraction of the data).
    ``max_bad == 0`` means unlimited.
    """

    def __init__(self, on_bad: str = "raise", max_bad: int = 0,
                 health: Optional[DataHealth] = None):
        if on_bad not in ("raise", "skip"):
            raise ValueError(
                f"on_bad_record must be 'raise' or 'skip', got {on_bad!r}")
        self.on_bad = on_bad
        self.max_bad = int(max_bad)
        self.health = health if health is not None else DataHealth()
        self._lock = threading.Lock()
        self._skipped = 0

    @property
    def skips(self) -> int:
        return self._skipped

    def bad_record(self, path: str, offset: int, reason: str, *,
                   nbytes: int = 0, truncated: bool = False) -> None:
        """Handle one bad frame at absolute byte ``offset`` of ``path``.

        Returns normally iff policy is skip and the budget allows; the
        caller then drops the frame and continues.
        """
        label = path or "<stream>"
        if self.on_bad != "skip":
            raise IOError(
                f"corrupt TFRecord: {reason} in {label} at byte {offset}")
        with self._lock:
            self._skipped += 1
            over_budget = self.max_bad > 0 and self._skipped > self.max_bad
        if over_budget:
            raise IOError(
                f"bad-record budget exceeded ({self._skipped} > "
                f"max_bad_records={self.max_bad}); last: {reason} in "
                f"{label} at byte {offset}")
        self.health.record_bad_record(label, nbytes, truncated=truncated)
