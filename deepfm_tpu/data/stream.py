"""Unbounded stream source: tail an ever-growing set of TFRecord shards.

The reference's Pipe-mode FIFO replays a *fixed* channel; ``--online_mode``
instead trains continuously from a directory (or manifest file) that keeps
receiving new shards.  :class:`UnboundedFileStream` presents the same
bounded-``read(n)`` byte-stream contract as ``ChainedFileStream``, so the
whole streaming decode path (``StreamingCtrPipeline`` → framer → bad-record
policy) consumes it unchanged; only the producer side knows the input never
ends.

Admission protocol (directory mode): every ``poll_secs`` the source is
globbed; a new file is *admitted* once its size is stable across two
consecutive polls (writers must write-once — create under a temp name and
rename, or finish writing before the second poll).  Manifest mode (``source``
is a text file of shard paths, one per line, appended over time) declares
files complete, so lines are admitted as soon as the named file exists.

Replay-exactness: every admission is appended — *before any of its bytes are
served* — to a high-water-mark sidecar (atomic via ``fileio.write_atomic``).
On restart the sidecar is replayed verbatim: same files, same order, same
per-file byte counts (each file is read exactly up to its admitted size, so
late growth never shifts record positions).  Combined with the consumer-side
``skip_batches`` trim this makes online resume consume each record exactly
once.  The watcher then resumes polling where the sidecar left off.

Anomalies are healed or skipped and counted in :class:`DataHealth`:

- **late** — a new file sorting before an already-admitted name (out-of-order
  delivery).  Admitted anyway; counted so operators can spot slow writers.
- **duplicate** — a new path whose basename was already admitted (the same
  shard re-delivered elsewhere).  Skipped; counted.
- **torn** — an admitted file that vanished or shrank before/while being
  read.  The remaining bytes are discarded and the stream moves on (the
  framer's carried tail then resyncs under the bad-record policy); counted,
  and the discarded bytes land in ``bytes_discarded``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Callable, List, Optional, Sequence, Tuple

from . import fileio
from .health import DataHealth

_SIDECAR_VERSION = 1


class UnboundedFileStream:
    """Bounded-``read(n)`` view over a growing shard set, replayed exactly.

    ``read(n)`` returns up to ``n`` bytes; it returns *fewer* as soon as the
    currently-admitted files are drained (the framer treats any non-empty
    read as progress, so small fresh shards reach the trainer without
    waiting to fill a 64MB chunk) and returns ``b""`` — true EOF — only when
    :meth:`request_stop` was called or no new data arrived for
    ``idle_timeout_secs`` (0 = wait forever, i.e. run until signalled).
    """

    def __init__(self, source: str, *,
                 pattern: str = "*",
                 sidecar_path: str = "",
                 poll_secs: float = 2.0,
                 idle_timeout_secs: float = 0.0,
                 retry_policy=None,
                 health: Optional[DataHealth] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Optional[Callable[[float], None]] = None):
        self._source = source
        self._pattern = pattern
        self._sidecar_path = sidecar_path
        self._poll_secs = float(poll_secs)
        self._idle_timeout_secs = float(idle_timeout_secs)
        self._retry_policy = retry_policy
        self.health = health if health is not None else DataHealth()
        self._clock = clock
        self._stop = threading.Event()
        # Default sleep rides the stop event so request_stop() interrupts a
        # poll wait immediately; tests inject a no-op for sleep-free polling.
        self._sleep = sleep if sleep is not None else self._stop.wait
        self._manifest_mode = bool(source) and not fileio.isdir(source)

        # Admission state. ``admitted`` is the full high-water-mark history
        # (mirrored in the sidecar); ``_queue``/``_qidx`` is the unread
        # suffix being served.
        self.admitted: List[Tuple[str, int]] = []
        self._queue: List[Tuple[str, int]] = []
        self._qidx = 0
        self._seen_paths: set = set()
        self._seen_names: set = set()
        self._max_name = ""
        self._pending: dict = {}  # path -> last observed size (settling)
        self._fh = None
        self._fh_path = ""
        self._fh_remaining = 0
        self._last_progress = self._clock()
        self._load_sidecar()

    # ---------------------------------------------------------------- sidecar

    def _load_sidecar(self) -> None:
        if not self._sidecar_path or not fileio.exists(self._sidecar_path):
            return
        try:
            with fileio.open_stream(self._sidecar_path, "rb") as f:
                meta = json.loads(f.read().decode("utf-8"))
            if meta.get("version") != _SIDECAR_VERSION:
                raise ValueError(f"sidecar version {meta.get('version')}")
            entries = [(str(p), int(s)) for p, s in meta["admitted"]]
        except Exception as e:  # corrupt sidecar: replay-exact resume is
            # impossible; start a fresh manifest rather than crash-loop.
            warnings.warn(
                f"stream sidecar {self._sidecar_path} unreadable ({e}); "
                "starting a fresh manifest — resume will not be replay-exact",
                RuntimeWarning, stacklevel=2)
            return
        if meta.get("source") not in (None, self._source):
            warnings.warn(
                f"stream sidecar {self._sidecar_path} was written for source "
                f"{meta.get('source')!r}, not {self._source!r}; ignoring it",
                RuntimeWarning, stacklevel=2)
            return
        for path, size in entries:
            self._note_admitted(path, size, count_late=False)

    def _write_sidecar(self) -> None:
        if not self._sidecar_path:
            return
        fileio.write_atomic(self._sidecar_path, json.dumps({
            "version": _SIDECAR_VERSION,
            "source": self._source,
            "pattern": self._pattern,
            "admitted": [[p, s] for p, s in self.admitted],
        }))

    # -------------------------------------------------------------- admission

    def _note_admitted(self, path: str, size: int, *,
                       count_late: bool = True) -> None:
        name = os.path.basename(path)
        if count_late and self._max_name and name < self._max_name:
            self.health.record_late_file(path)
        if name > self._max_name:
            self._max_name = name
        self._seen_paths.add(path)
        self._seen_names.add(name)
        entry = (path, int(size))
        self.admitted.append(entry)
        self._queue.append(entry)

    def _list_candidates(self) -> Sequence[Tuple[str, Optional[int]]]:
        """(path, declared_complete_size_or_None) for every current source
        entry. Directory mode returns None sizes (settling decides); manifest
        mode stats the named file (a listed-but-absent file stays pending)."""
        if self._manifest_mode:
            try:
                with fileio.open_stream(self._source, "rb") as f:
                    lines = f.read().decode("utf-8").splitlines()
            except OSError:
                return []
            out = []
            for line in lines:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                out.append((line, None))
            return out
        return [(p, None)
                for p in fileio.glob(fileio.join(self._source, self._pattern))]

    def _poll_once(self) -> bool:
        """One watcher pass; returns True iff new files were admitted.

        New files settle for one poll (size must be stable) in directory
        mode; manifest-declared files admit as soon as they exist non-empty.
        The sidecar is flushed BEFORE returning, so no byte of a new file is
        ever served ahead of its high-water-mark record.
        """
        admitted_any = False
        for path, _ in self._list_candidates():
            if path in self._seen_paths:
                continue
            name = os.path.basename(path)
            if name in self._seen_names:
                # Same shard re-delivered under another path: train on it
                # once, not twice.
                self.health.record_duplicate_file(path)
                self._seen_paths.add(path)
                continue
            try:
                if not fileio.exists(path):
                    continue
                size = fileio.size(path)
            except OSError:
                continue  # raced a writer; retry next poll
            if size <= 0:
                continue  # empty or still being created
            if self._manifest_mode or self._pending.get(path) == size:
                self._pending.pop(path, None)
                self._note_admitted(path, size)
                admitted_any = True
            else:
                self._pending[path] = size  # settle one more poll
        if admitted_any:
            self._write_sidecar()
            self._mark_progress()
        return admitted_any

    def poll_now(self) -> bool:
        """Force a watcher pass outside the read loop (tests, feeders)."""
        return self._poll_once()

    # ------------------------------------------------------------------ read

    def _mark_progress(self) -> None:
        self._last_progress = self._clock()

    def _open_current(self, path: str):
        on_retry = None
        health = self.health
        if health is not None:
            on_retry = lambda exc, n, p=path: health.record_retry(p)  # noqa: E731
        return fileio.open_resilient(path, policy=self._retry_policy,
                                     on_retry=on_retry)

    def _advance(self) -> bool:
        """Open the next admitted file; False when the queue is drained."""
        while self._qidx < len(self._queue):
            path, size = self._queue[self._qidx]
            self._qidx += 1
            try:
                if not fileio.exists(path):
                    # Admitted then vanished: the records it held cannot be
                    # replayed — count the tear and keep streaming.
                    self.health.record_torn_file(path, nbytes=size)
                    continue
            except OSError:
                self.health.record_torn_file(path, nbytes=size)
                continue
            self._fh = self._open_current(path)
            self._fh_path = path
            self._fh_remaining = size
            return True
        return False

    def _close_current(self) -> None:
        fh, self._fh = self._fh, None
        self._fh_path = ""
        self._fh_remaining = 0
        if fh is not None:
            try:
                fh.close()
            except Exception:
                pass

    def _wait_for_data(self) -> bool:
        """Block until new files are admitted (True) or the stream ends
        (False: stop requested, or idle past ``idle_timeout_secs``)."""
        while True:
            if self._stop.is_set():
                return False
            if self._poll_once():
                return True
            if self._stop.is_set():
                return False
            if (self._idle_timeout_secs > 0
                    and self._clock() - self._last_progress
                    >= self._idle_timeout_secs):
                return False
            self._sleep(self._poll_secs)

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            raise ValueError(
                "UnboundedFileStream only supports bounded reads")
        out = []
        got = 0
        while got < n:
            if self._fh is None:
                if not self._advance():
                    if got:
                        break  # serve what we have; caller reads again
                    if not self._wait_for_data():
                        break  # true EOF: stopped or idle-timed-out
                    continue
            want = min(n - got, self._fh_remaining)
            if want == 0:
                # Admitted size fully delivered. Bytes appended after
                # admission are deliberately ignored (write-once contract):
                # replay must see the same per-file byte count.
                self._close_current()
                continue
            try:
                chunk = self._fh.read(want)
            except OSError:
                # Mid-read tear survived retries: discard the rest of this
                # file and let the framer resync under the bad-record policy.
                self.health.record_torn_file(
                    self._fh_path, nbytes=self._fh_remaining)
                self._close_current()
                continue
            if not chunk:
                # File shrank below its admitted size.
                self.health.record_torn_file(
                    self._fh_path, nbytes=self._fh_remaining)
                self._close_current()
                continue
            self._fh_remaining -= len(chunk)
            got += len(chunk)
            out.append(chunk)
            self._mark_progress()
        if len(out) == 1:
            return out[0]
        return b"".join(out)

    # ----------------------------------------------------------------- misc

    def request_stop(self) -> None:
        """Finish the current read promptly and report EOF thereafter.
        Called from the preemption path so a blocked poll wait wakes up."""
        self._stop.set()

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def files_admitted(self) -> List[str]:
        return [p for p, _ in self.admitted]

    def close(self) -> None:
        self.request_stop()
        self._close_current()
