"""Multi-process input service: decode workers + shared-memory transport.

BENCH r5 showed the staged pipeline is GIL-bound: the C decoder releases the
GIL but the shuffle/scatter/batch-assembly Python around it cannot scale past
one core's interpreter time, so ``reader_threads`` stops helping once decode
stops being the bottleneck. This module moves the whole frame+decode stage
into worker *processes* (the TPU-native analog of the reference's
PipeModeDataset C++ reader fleet): each worker runs the existing
framed-chunk reader (``pipeline._iter_framed_chunks`` — same chunking, CRC
policy, retry healing, and bad-record accounting as in-process) and decodes
straight into :mod:`shm_ring` slabs; the trainer process consumes zero-copy
``np.frombuffer`` views and feeds them to the unchanged shuffle-pool drain.

Determinism contract (the bit-identical parity the bench asserts):

  * File ``i`` of the epoch-shuffled list goes to worker ``i % W`` (static
    round-robin — no dynamic work stealing, so the assignment is a pure
    function of the file list).
  * The consumer iterates files in the SAME epoch-shuffled global order the
    in-process path uses, pulling each file's chunks from its owner's ring.
    Chunks within a file arrive in file order (SPSC ring, ordered queue),
    so the reassembled chunk stream is exactly the in-process
    ``_iter_framed_chunks`` stream — same records, same order, same chunk
    boundaries (fragments are reassembled before yielding).
  * Every data/control message consumes one monotonically increasing
    sequence number per worker. A respawned worker replays its full file
    list but only *emits* messages with ``seq >= start_seq``, which makes
    crash recovery replay-exact.

Worker death: detected via queue-timeout + ``Process.is_alive``. Policy
``raise`` (default) fails the epoch; ``respawn`` restarts the worker on a
FRESH ring at the first sequence number of the incomplete chunk (bounded by
``max_respawns``). Health caveats of respawn: the replacement re-reads the
dead worker's files from the start, so ``DataHealth`` retry/bad-record
counters for already-delivered chunks can be counted twice; the bad-record
skip budget is enforced per worker, not globally.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue as _queue
import sys
import traceback
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as trace_lib
from . import shm_ring
from .health import BadRecordPolicy, DataHealth

# Spawn, not fork: the trainer process owns a JAX runtime (threads, device
# handles) that must not leak into decode workers; spawned children import
# only the numpy-level ``deepfm_tpu.data`` stack.
_MP_CTX = "spawn"

# Default slab sizing: one slab should hold a full reader chunk (64MB of
# on-disk bytes is < ~210k Criteo-shaped records) so the common case is one
# zero-copy fragment per chunk; fragmentation beyond that is correct, just
# one concatenate-copy slower.
_DEFAULT_SLAB_BYTES = 64 << 20
_DEFAULT_CAPACITY = 4


def default_slab_records(field_size: int) -> int:
    row_bytes = 4 + 8 * field_size  # f32 label + (i32 + f32) * field
    return max(1, _DEFAULT_SLAB_BYTES // row_bytes)


def _policy_scalars(policy) -> Optional[Dict[str, Any]]:
    """Picklable retry knobs for spawn args (callables stay behind)."""
    if policy is None:
        return None
    return dict(max_attempts=policy.max_attempts,
                base_delay=policy.base_delay,
                max_delay=policy.max_delay,
                deadline=policy.deadline,
                jitter_seed=policy.jitter_seed)


def _snapshot_delta(prev: Dict[str, Any], cur: Dict[str, Any]
                    ) -> Dict[str, Any]:
    """cur - prev over cumulative DataHealth snapshots."""
    delta: Dict[str, Any] = {
        key: int(cur[key]) - int(prev.get(key, 0))
        for key in ("read_retries", "bad_records", "truncated_tails",
                    "bytes_discarded")}
    per_file: Dict[str, Dict[str, int]] = {}
    for path, c in cur.get("per_file", {}).items():
        p = prev.get("per_file", {}).get(path, {})
        d = {k: int(c[k]) - int(p.get(k, 0)) for k in ("retries", "skipped")}
        if any(d.values()):
            per_file[path] = d
    delta["per_file"] = per_file
    return delta


def worker_main(worker_id: int, handle: shm_ring.RingHandle,
                files: Sequence[Tuple[int, str]], opts: Dict[str, Any]
                ) -> None:
    """Decode worker entry point (module-level: spawn pickles by reference).

    Streams each assigned ``(global_file_idx, path)`` through the shared
    framed-chunk reader, splits every chunk into <= slab_records fragments,
    decodes each fragment straight into a ring slab, and publishes
    ``("chunk", seq, slot, file_idx, n_records, last_fragment)``. File
    boundaries publish ``("eof", seq, file_idx, health_snapshot)``; normal
    completion ``("done", seq, worker_id, health_snapshot)``; any failure
    ``("error", seq, worker_id, exc_type, detail, health_snapshot)``.
    """
    ring = shm_ring.ShmRing.attach(handle)
    trace_lib.configure_from_env()  # inherit the parent's --trace settings
    seq = 0
    start_seq = int(opts.get("start_seq", 0))
    die_after = opts.get("fault_die_after")
    emitted = 0
    health = DataHealth()
    try:
        policy = BadRecordPolicy(opts["on_bad_record"],
                                 opts["max_bad_records"], health)
        retry_policy = None
        if opts.get("retry") is not None:
            from ..utils.retry import RetryPolicy  # noqa: PLC0415
            retry_policy = RetryPolicy(**opts["retry"])
        from . import pipeline as pipe_mod  # noqa: PLC0415
        loader = pipe_mod._native_loader()
        if loader is None:
            raise RuntimeError("native decoder unavailable in input worker")
        S = handle.slab_records
        F = handle.field_size
        for fidx, path in files:
            for buf, offsets, lengths in pipe_mod._iter_framed_chunks(
                    path, loader, opts["verify_crc"], policy=policy,
                    retry_policy=retry_policy):
                total = len(offsets)
                if total == 0:
                    continue
                for s in range(0, total, S):
                    e = min(s + S, total)
                    if seq >= start_seq:
                        with trace_lib.span("input.slab_wait", worker=worker_id):
                            slot = ring.acquire()  # blocks = backpressure
                        n = e - s
                        labels, ids, vals = ring.arrays(slot, n)
                        with trace_lib.span("input.decode", worker=worker_id,
                                            records=n):
                            loader.decode_spans_scatter(
                                buf, offsets[s:e], lengths[s:e], F,
                                np.arange(n, dtype=np.int64), labels, ids, vals)
                        del labels, ids, vals
                        ring.send(("chunk", seq, slot, fidx, n, e == total))
                        emitted += 1
                        if die_after is not None \
                                and emitted >= int(die_after):
                            os._exit(13)  # test hook: simulated hard crash
                    seq += 1
            if seq >= start_seq:
                ring.send(("eof", seq, fidx, health.snapshot()))
            seq += 1
        ring.send(("done", seq, worker_id, health.snapshot()))
    except BaseException as exc:  # noqa: BLE001 — forwarded to the trainer
        try:
            ring.send(("error", seq, worker_id, type(exc).__name__,
                       f"{exc}\n{traceback.format_exc()}", health.snapshot()))
        except Exception:
            pass
        trace_lib.export()
        ring.close()
        sys.exit(1)
    trace_lib.export()  # one trace-<pid>.json per worker; parent merges
    ring.close()


class _WorkerDied(Exception):
    """Internal: worker process exited without a protocol farewell."""


class InputStallError(RuntimeError):
    """An input worker is alive but produced nothing for stall_timeout_s.

    Distinct from ``_WorkerDied`` (process gone) — this is the wedged-but-
    breathing case: a hung filesystem mount, a deadlocked decoder, a worker
    blocked on a ring slot the consumer will never free. Raising (instead of
    polling forever) surfaces the stall with diagnostics so a supervisor can
    restart the job rather than letting it burn accelerator reservations
    silently."""


class ShmInputService:
    """Parent-side fleet manager + globally-ordered chunk iterator.

    Context manager: ``__enter__`` spawns the fleet, ``__exit__`` tears it
    down (terminate + join + unlink every segment), safe on abandonment
    mid-epoch (GeneratorExit in the consumer lands in ``__exit__``).
    """

    def __init__(self, files: Sequence[str], *, field_size: int,
                 num_workers: int, slab_records: Optional[int] = None,
                 capacity: int = _DEFAULT_CAPACITY, verify_crc: bool = False,
                 on_bad_record: str = "raise", max_bad_records: int = 0,
                 retry_policy=None, health: Optional[DataHealth] = None,
                 on_worker_death: str = "raise", max_respawns: int = 2,
                 poll_secs: float = 0.2, fault_die_after: Optional[int] = None,
                 stall_timeout_s: float = 0.0):
        if on_worker_death not in ("raise", "respawn"):
            raise ValueError(
                f"on_worker_death must be 'raise' or 'respawn', "
                f"got {on_worker_death!r}")
        self._files: Tuple[str, ...] = tuple(files)
        self.field_size = field_size
        self.num_workers = max(1, min(int(num_workers), len(self._files))) \
            if self._files else 0
        self.slab_records = int(slab_records if slab_records is not None
                                else default_slab_records(field_size))
        self.capacity = int(capacity)
        self._opts: Dict[str, Any] = dict(
            verify_crc=verify_crc, on_bad_record=on_bad_record,
            max_bad_records=max_bad_records,
            retry=_policy_scalars(retry_policy),
            fault_die_after=fault_die_after)
        self.health = health if health is not None else DataHealth()
        self.on_worker_death = on_worker_death
        self.max_respawns = int(max_respawns)
        self._poll_secs = poll_secs
        self._stall_timeout_s = float(stall_timeout_s)
        self._ctx = mp.get_context(_MP_CTX)
        self._rings: List[shm_ring.ShmRing] = []
        self._procs: List[Optional[mp.process.BaseProcess]] = []
        self._expected: List[int] = []       # next seq per worker
        self._chunk_start: List[int] = []    # restart seq of the open chunk
        self._held: List[List[Tuple[shm_ring.ShmRing, int]]] = []
        self._last_snap: List[Dict[str, Any]] = []
        self._retired: List[shm_ring.ShmRing] = []
        self._respawns = 0
        self._started = False
        self._closed = False

    # -- lifecycle ------------------------------------------------------
    def _assignment(self, w: int) -> List[Tuple[int, str]]:
        return [(i, path) for i, path in enumerate(self._files)
                if i % self.num_workers == w]

    def _spawn(self, w: int, start_seq: int) -> None:
        spec = shm_ring.SlabSpec(self.slab_records, self.field_size)
        ring = shm_ring.ShmRing.create(spec, self.capacity, self._ctx)
        try:
            opts = dict(self._opts, start_seq=start_seq)
            proc = self._ctx.Process(
                target=worker_main, name=f"dfm-input-{w}",
                args=(w, ring.handle, self._assignment(w), opts), daemon=True)
            proc.start()
        except BaseException:
            ring.close()  # owner: unlinks the segment
            raise
        self._rings[w] = ring
        self._procs[w] = proc
        self._expected[w] = start_seq
        self._chunk_start[w] = start_seq
        self._last_snap[w] = {}

    def start(self) -> "ShmInputService":
        if self._started:
            return self
        self._started = True
        W = self.num_workers
        self._rings = [None] * W  # type: ignore[list-item]
        self._procs = [None] * W
        self._expected = [0] * W
        self._chunk_start = [0] * W
        self._held = [[] for _ in range(W)]
        self._last_snap = [{} for _ in range(W)]
        try:
            for w in range(W):
                self._spawn(w, start_seq=0)
        except BaseException:
            self.close()
            raise
        return self

    def __enter__(self) -> "ShmInputService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for proc in self._procs:
            if proc is not None and proc.is_alive():
                proc.terminate()
        for proc in self._procs:
            if proc is not None:
                proc.join(timeout=10)
        for ring in list(self._rings) + self._retired:
            if ring is not None:
                ring.close()

    # -- health ---------------------------------------------------------
    def _merge_health(self, w: int, snap: Dict[str, Any]) -> None:
        self.health.apply_delta(_snapshot_delta(self._last_snap[w], snap))
        self._last_snap[w] = snap

    # -- message pump ---------------------------------------------------
    def _pop(self, w: int) -> Tuple:
        ring = self._rings[w]
        waited = 0.0
        # Async span opened lazily on the first empty poll: the common
        # message-ready case never allocates a trace event.
        sp = None
        try:
            while True:
                try:
                    return ring.pop(timeout=self._poll_secs)
                except _queue.Empty:
                    if sp is None:
                        sp = trace_lib.begin("input.ring_wait", worker=w)
                proc = self._procs[w]
                if proc is None or not proc.is_alive():
                    try:  # messages flushed just before death are still valid
                        return ring.pop(timeout=0)
                    except _queue.Empty:
                        raise _WorkerDied(w) from None
                waited += self._poll_secs
                if self._stall_timeout_s > 0 \
                        and waited >= self._stall_timeout_s:
                    raise InputStallError(
                        f"input worker {w} is alive but produced no message "
                        f"for {waited:.1f}s (stall_timeout_s="
                        f"{self._stall_timeout_s:g}); data health: "
                        f"{self.health.summary()}")
        finally:
            trace_lib.end(sp)

    def _next_msg(self, w: int) -> Tuple:
        msg = self._pop(w)
        if msg[0] == "error":
            _, seq, _, exc_type, detail, snap = msg
            self._merge_health(w, snap)
            text = f"input worker {w} failed: {detail}"
            if exc_type in ("IOError", "OSError"):
                raise IOError(text)  # keeps bad-record-budget parity
            if exc_type == "ValueError":
                raise ValueError(text)
            raise RuntimeError(text)
        if msg[1] != self._expected[w]:
            raise RuntimeError(
                f"input worker {w} protocol violation: message seq "
                f"{msg[1]}, expected {self._expected[w]}")
        self._expected[w] += 1
        return msg

    def _on_death(self, w: int) -> None:
        proc = self._procs[w]
        code = proc.exitcode if proc is not None else None
        if self.on_worker_death != "respawn" \
                or self._respawns >= self.max_respawns:
            raise RuntimeError(
                f"input worker {w} died (exit code {code}); "
                f"on_worker_death={self.on_worker_death!r}, "
                f"respawns used {self._respawns}/{self.max_respawns}")
        self._respawns += 1
        # The crash knob injects ONE fault: replacements spawn healthy.
        # (os._exit can kill the queue feeder before anything flushed, so
        # the replacement may replay from seq 0 — were the knob still
        # armed it would re-crash at the same spot every incarnation.)
        self._opts["fault_die_after"] = None
        # Fresh ring: slots lost in the dead worker's hands (acquired but
        # never committed, or queued messages that never flushed) cannot be
        # recovered from the old segment's bookkeeping. Views the consumer
        # still holds keep referencing the retired segment until
        # release_consumed(); it is unlinked at service close.
        self._retired.append(self._rings[w])
        self._spawn(w, start_seq=self._chunk_start[w])

    # -- the consumer API ----------------------------------------------
    def chunks(self, *, copy: bool = False
               ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Decoded ``(labels, ids, vals)`` chunks in GLOBAL file order —
        the exact stream ``CtrPipeline._iter_decoded_chunks`` would
        produce in-process. With ``copy=False`` single-fragment chunks are
        zero-copy slab views, held until :meth:`release_consumed`; to stay
        deadlock-free the hold is bounded at ``capacity - 2`` slabs per
        worker, past which chunks are copied and their slots released
        immediately (a consumer pooling more rows than the rings hold must
        not starve the producers)."""
        if not self._started:
            raise RuntimeError("service not started (use 'with service:')")
        got_any = False
        for fidx in range(len(self._files)):
            w = fidx % self.num_workers
            frags: List[Tuple[int, Tuple[np.ndarray, ...]]] = []
            while True:
                try:
                    msg = self._next_msg(w)
                except _WorkerDied:
                    self._on_death(w)  # raises unless respawn allowed
                    frags = []  # partial chunk replays from _chunk_start
                    continue
                kind = msg[0]
                if kind == "chunk":
                    _, _, slot, m_fidx, n, last = msg
                    if m_fidx != fidx:
                        raise RuntimeError(
                            f"input worker {w} protocol violation: chunk "
                            f"for file {m_fidx}, expected {fidx}")
                    frags.append((slot, self._rings[w].arrays(slot, n)))
                    if not last:
                        continue
                    got_any = True
                    yield self._assemble(w, frags, copy)
                    frags = []
                    self._chunk_start[w] = self._expected[w]
                elif kind == "eof":
                    _, _, m_fidx, snap = msg
                    if frags or m_fidx != fidx:
                        raise RuntimeError(
                            f"input worker {w} protocol violation: eof of "
                            f"file {m_fidx} with open chunk for {fidx}")
                    self._merge_health(w, snap)
                    self._chunk_start[w] = self._expected[w]
                    break
                else:
                    raise RuntimeError(
                        f"input worker {w} protocol violation: unexpected "
                        f"{kind!r} message before eof of file {fidx}")
        for w in range(self.num_workers):
            try:
                while True:
                    msg = self._next_msg(w)
                    if msg[0] == "done":
                        self._merge_health(w, msg[3])
                        break
                    raise RuntimeError(
                        f"input worker {w} protocol violation: expected "
                        f"'done', got {msg[0]!r}")
            except _WorkerDied:
                pass  # every file already delivered; the farewell is lost
        if not got_any and self._files:
            raise IOError(f"no records found in {len(self._files)} files")

    def _assemble(self, w: int, frags, copy: bool
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        ring = self._rings[w]
        if (not copy and len(frags) == 1
                and len(self._held[w]) < self.capacity - 2):
            slot, arrays = frags[0]
            self._held[w].append((ring, slot))
            return arrays
        if len(frags) == 1:
            slot, (labels, ids, vals) = frags[0]
            out = (labels.copy(), ids.copy(), vals.copy())
            ring.release(slot)
            return out
        labels = np.concatenate([f[1][0] for f in frags])
        ids = np.concatenate([f[1][1] for f in frags])
        vals = np.concatenate([f[1][2] for f in frags])
        for slot, _ in frags:
            ring.release(slot)
        return labels, ids, vals

    def release_consumed(self) -> None:
        """Return every held slab to its producer. The pipeline calls this
        right after the shuffle-pool drain scatters the held views into
        fresh pool arrays — from that point the slab memory is dead weight
        and the worker may overwrite it."""
        for w in range(self.num_workers):
            for ring, slot in self._held[w]:
                if ring is self._rings[w]:  # retired rings have no reader
                    ring.release(slot)
            self._held[w] = []
