"""Filesystem seam: local paths plus remote object stores (``gs://`` etc.).

The reference reads training data from S3 — either downloaded by SageMaker
File mode or streamed through the Pipe-mode FIFO (X3). The TPU-native
equivalent streams from GCS: every byte-level reader in this package opens
files through :func:`open_stream` and lists them through :func:`glob`, which
dispatch to ``tf.io.gfile`` for URL-style paths (``gs://``, ``s3://``,
``hdfs://`` — whatever the installed TF build supports) and to plain POSIX
I/O otherwise. TensorFlow is imported lazily and only for remote paths, so
local training never pays the import.

Fault tolerance: every metadata op (glob/exists/size/isdir) and every open
runs under the module :class:`~deepfm_tpu.utils.retry.RetryPolicy`, and
:class:`ResilientStream` heals transient *mid-read* failures by reopening
and repositioning to the last good byte offset. A process-wide fault
injector seam (:func:`set_fault_injector`) lets tests and
``scripts/fault_drill.py`` script deterministic failures INSIDE the retry
loop, so the healing path itself is what gets exercised.
"""

from __future__ import annotations

import glob as _glob
import io
import os
from typing import BinaryIO, Callable, List, Optional

from ..utils import retry as _retry

_gfile_mod = None

# Module retry policy for filesystem ops. Replaceable (set_retry_policy) so
# tasks.py can apply Config knobs and tests can zero out sleeps.
_retry_policy = _retry.RetryPolicy()

# Process-wide fault injector (see utils/faults.py). None in production.
_injector = None


def set_retry_policy(policy: _retry.RetryPolicy) -> _retry.RetryPolicy:
    """Install the retry policy for all fileio ops; returns the previous."""
    global _retry_policy
    prev, _retry_policy = _retry_policy, policy
    return prev


def get_retry_policy() -> _retry.RetryPolicy:
    return _retry_policy


def set_fault_injector(inj) -> None:
    """Install (or with None, remove) the process-wide fault injector.

    The injector duck-type is two methods: ``on_op(op_name, path)`` called
    inside the retry loop before each metadata/open op (raise to inject),
    and ``wrap_stream(path, stream)`` called on freshly opened read streams
    (return a wrapper to inject read faults).
    """
    global _injector
    _injector = inj


def is_remote(path: str) -> bool:
    return "://" in path


def _gfile():
    global _gfile_mod
    if _gfile_mod is None:
        try:
            from tensorflow.io import gfile  # noqa: PLC0415 (lazy, heavy)
        except ImportError as e:  # pragma: no cover - env without TF
            raise RuntimeError(
                "remote paths (gs:// etc.) require tensorflow's tf.io.gfile; "
                "download the data locally or install tensorflow") from e
        _gfile_mod = gfile
    return _gfile_mod


def open_stream(path: str, mode: str = "rb") -> BinaryIO:
    """Open a (possibly remote) path, retrying transient open failures."""
    def _open() -> BinaryIO:
        if _injector is not None:
            _injector.on_op("open", path)
        if is_remote(path):
            f: BinaryIO = _gfile().GFile(path, mode)
        else:
            f = open(path, mode)
        if _injector is not None and "r" in mode and "+" not in mode:
            f = _injector.wrap_stream(path, f)
        return f
    return _retry_policy.call(_open, op_name=f"open({path})")


def glob(pattern: str) -> List[str]:
    def _glob_op() -> List[str]:
        if _injector is not None:
            _injector.on_op("glob", pattern)
        if is_remote(pattern):
            return sorted(_gfile().glob(pattern))
        return sorted(_glob.glob(pattern))
    return _retry_policy.call(_glob_op, op_name=f"glob({pattern})")


def isdir(path: str) -> bool:
    def _isdir_op() -> bool:
        if _injector is not None:
            _injector.on_op("isdir", path)
        if is_remote(path):
            return _gfile().isdir(path)
        return os.path.isdir(path)
    return _retry_policy.call(_isdir_op, op_name=f"isdir({path})")


def exists(path: str) -> bool:
    def _exists_op() -> bool:
        if _injector is not None:
            _injector.on_op("exists", path)
        if is_remote(path):
            return _gfile().exists(path)
        return os.path.exists(path)
    return _retry_policy.call(_exists_op, op_name=f"exists({path})")


def size(path: str) -> int:
    """Byte length of a (possibly remote) file."""
    def _size_op() -> int:
        if _injector is not None:
            _injector.on_op("size", path)
        if is_remote(path):
            return int(_gfile().stat(path).length)
        return os.path.getsize(path)
    return _retry_policy.call(_size_op, op_name=f"size({path})")


def makedirs(path: str) -> None:
    if is_remote(path):
        _gfile().makedirs(path)
        return
    os.makedirs(path, exist_ok=True)


def rmtree(path: str) -> None:
    if is_remote(path):
        _gfile().rmtree(path)
        return
    import shutil
    shutil.rmtree(path)


def replace(src: str, dst: str) -> None:
    """Atomically move ``src`` over ``dst`` (file or directory).

    Local paths use ``os.replace`` — atomic on POSIX, so readers only ever
    see the old artifact or the complete new one, never a partial state.
    Remote stores rename with overwrite; object-store renames are not
    guaranteed atomic, which is why the publish path requires a local
    staging filesystem (see train/publish.py)."""
    if is_remote(src) or is_remote(dst):
        _gfile().rename(src, dst, overwrite=True)
        return
    os.replace(src, dst)


def fsync_dir(path: str) -> None:
    """fsync a local directory so a just-completed rename survives a crash.
    No-op for remote stores (durability is the store's contract)."""
    if is_remote(path):
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_atomic(path: str, data) -> None:
    """Write ``data`` (str or bytes) so readers see the old content or the
    new content, never a torn intermediate: write a same-directory temp
    file, flush+fsync, then rename over the destination. The pattern behind
    every pointer/sidecar file the online-publishing path maintains
    (``LATEST``, the stream high-water-mark manifest)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    if is_remote(path):
        # Remote stores: single-shot object write is already all-or-nothing.
        with open_stream(path, "wb") as f:
            f.write(data)
        return
    d = os.path.dirname(path) or "."
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(d)


def join(base: str, *parts: str) -> str:
    """Path join that keeps URL-style separators for remote bases.

    ``os.path.join`` is correct for POSIX paths but on remote URLs it must
    not be trusted with platform separators; object stores always use '/'.
    """
    if is_remote(base):
        out = base.rstrip("/")
        for p in parts:
            out += "/" + str(p).strip("/")
        return out
    return os.path.join(base, *parts)


def normalize_dir(path: str) -> str:
    """Absolute form for local paths; remote URIs pass through untouched.

    Orbax and friends require absolute local paths but take ``gs://`` URIs
    verbatim — ``os.path.abspath`` would mangle them into
    ``/cwd/gs:/bucket/...`` (the VERDICT r2 storage-seam bug)."""
    if is_remote(path):
        return path.rstrip("/")
    return os.path.abspath(path)


class ResilientStream(io.RawIOBase):
    """Sequential read stream that survives transient mid-file failures.

    Tracks the absolute byte offset of delivered data; when a read raises a
    transient error the broken stream is dropped and — under the retry
    policy's backoff — a fresh one is opened and repositioned to the last
    good offset (``seek`` when the underlying stream supports it, otherwise
    read-and-discard, matching object-store streams that only resume by
    re-reading). ``read(n)`` always returns exactly ``n`` bytes except at
    EOF, so the strictly sequential framers (``pipeline._iter_framed_stream``
    and ``tfrecord.iter_records_from_stream``) get mid-file fault survival
    without any changes of their own.
    """

    _DISCARD_CHUNK = 1 << 20

    def __init__(self, path: str = "", *,
                 opener: Optional[Callable[[], BinaryIO]] = None,
                 policy: Optional[_retry.RetryPolicy] = None,
                 on_retry: Optional[Callable[[BaseException, int], None]] = None):
        super().__init__()
        if opener is None:
            if not path:
                raise ValueError("ResilientStream needs a path or an opener")
            opener = lambda: open_stream(path, "rb")  # noqa: E731
        self._opener = opener
        self._path = path or "<stream>"
        self._policy = policy or _retry_policy
        self._on_retry = on_retry
        self._stream: Optional[BinaryIO] = None
        self._offset = 0  # absolute offset of the next byte owed the caller
        self.reopen_count = 0

    @property
    def path(self) -> str:
        return self._path

    def tell(self) -> int:
        return self._offset

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return False

    def _drop(self) -> None:
        s, self._stream = self._stream, None
        if s is not None:
            try:
                s.close()
            except Exception:
                pass  # a broken remote stream may refuse even close()

    def _reposition(self, stream: BinaryIO) -> None:
        try:
            can_seek = bool(stream.seekable())
        except Exception:
            can_seek = hasattr(stream, "seek")
        if can_seek and hasattr(stream, "seek"):
            stream.seek(self._offset)
            return
        remaining = self._offset
        while remaining > 0:
            chunk = stream.read(min(remaining, self._DISCARD_CHUNK))
            if not chunk:
                raise IOError(
                    f"reopen of {self._path} hit EOF at byte "
                    f"{self._offset - remaining} before reaching the last "
                    f"good offset {self._offset}")
            remaining -= len(chunk)

    def _read_some(self, want: int) -> bytes:
        def attempt() -> bytes:
            if self._stream is None:
                stream = self._opener()
                if self._offset:
                    self._reposition(stream)
                self._stream = stream
            return self._stream.read(want)

        def on_retry(exc: BaseException, n: int) -> None:
            self._drop()
            self.reopen_count += 1
            if self._on_retry is not None:
                self._on_retry(exc, n)

        try:
            return self._policy.call(
                attempt, op_name=f"read({self._path}@{self._offset})",
                on_retry=on_retry)
        except BaseException:
            self._drop()
            raise

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            chunks = []
            while True:
                c = self.read(self._DISCARD_CHUNK)
                if not c:
                    return b"".join(chunks)
                chunks.append(c)
        if n == 0:
            return b""
        # Accumulate the underlying reads and join ONCE — the common case
        # (one underlying read satisfies the request, or hits EOF) returns
        # that chunk as-is. The previous bytearray accumulation + bytes()
        # conversion copied every chunk-sized read twice; at the input
        # pipeline's 64MB chunk size that was ~2x the file's bytes in pure
        # memcpy per epoch, the single largest host-path overhead found by
        # the r6 per-stage breakdown.
        chunks = []
        got = 0
        while got < n:
            chunk = self._read_some(n - got)
            if not chunk:
                break  # EOF
            self._offset += len(chunk)
            got += len(chunk)
            chunks.append(chunk)
        if len(chunks) == 1:
            return chunks[0]
        return b"".join(chunks)

    def close(self) -> None:
        self._drop()
        super().close()


def open_resilient(path: str, *,
                   policy: Optional[_retry.RetryPolicy] = None,
                   on_retry: Optional[Callable[[BaseException, int], None]] = None,
                   ) -> ResilientStream:
    """Open ``path`` for reading behind transparent reopen-and-seek retry."""
    return ResilientStream(path, policy=policy, on_retry=on_retry)
