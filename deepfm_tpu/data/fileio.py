"""Filesystem seam: local paths plus remote object stores (``gs://`` etc.).

The reference reads training data from S3 — either downloaded by SageMaker
File mode or streamed through the Pipe-mode FIFO (X3). The TPU-native
equivalent streams from GCS: every byte-level reader in this package opens
files through :func:`open_stream` and lists them through :func:`glob`, which
dispatch to ``tf.io.gfile`` for URL-style paths (``gs://``, ``s3://``,
``hdfs://`` — whatever the installed TF build supports) and to plain POSIX
I/O otherwise. TensorFlow is imported lazily and only for remote paths, so
local training never pays the import.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import BinaryIO, List

_gfile_mod = None


def is_remote(path: str) -> bool:
    return "://" in path


def _gfile():
    global _gfile_mod
    if _gfile_mod is None:
        try:
            from tensorflow.io import gfile  # noqa: PLC0415 (lazy, heavy)
        except ImportError as e:  # pragma: no cover - env without TF
            raise RuntimeError(
                "remote paths (gs:// etc.) require tensorflow's tf.io.gfile; "
                "download the data locally or install tensorflow") from e
        _gfile_mod = gfile
    return _gfile_mod


def open_stream(path: str, mode: str = "rb") -> BinaryIO:
    """Open a (possibly remote) path for sequential reading."""
    if is_remote(path):
        return _gfile().GFile(path, mode)
    return open(path, mode)


def glob(pattern: str) -> List[str]:
    if is_remote(pattern):
        return sorted(_gfile().glob(pattern))
    return sorted(_glob.glob(pattern))


def isdir(path: str) -> bool:
    if is_remote(path):
        return _gfile().isdir(path)
    return os.path.isdir(path)


def exists(path: str) -> bool:
    if is_remote(path):
        return _gfile().exists(path)
    return os.path.exists(path)


def size(path: str) -> int:
    """Byte length of a (possibly remote) file."""
    if is_remote(path):
        return int(_gfile().stat(path).length)
    return os.path.getsize(path)


def makedirs(path: str) -> None:
    if is_remote(path):
        _gfile().makedirs(path)
        return
    os.makedirs(path, exist_ok=True)


def rmtree(path: str) -> None:
    if is_remote(path):
        _gfile().rmtree(path)
        return
    import shutil
    shutil.rmtree(path)


def join(base: str, *parts: str) -> str:
    """Path join that keeps URL-style separators for remote bases.

    ``os.path.join`` is correct for POSIX paths but on remote URLs it must
    not be trusted with platform separators; object stores always use '/'.
    """
    if is_remote(base):
        out = base.rstrip("/")
        for p in parts:
            out += "/" + str(p).strip("/")
        return out
    return os.path.join(base, *parts)


def normalize_dir(path: str) -> str:
    """Absolute form for local paths; remote URIs pass through untouched.

    Orbax and friends require absolute local paths but take ``gs://`` URIs
    verbatim — ``os.path.abspath`` would mangle them into
    ``/cwd/gs:/bucket/...`` (the VERDICT r2 storage-seam bug)."""
    if is_remote(path):
        return path.rstrip("/")
    return os.path.abspath(path)
