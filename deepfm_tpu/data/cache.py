"""Decoded-epoch cache: frame+decode the dataset once, serve every later
epoch from contiguous ``(label, feat_ids, feat_vals)`` column slabs.

The staged pipeline pays frame+decode (~half its ns/record) again every
epoch for bytes that never changed — the reference repo's Pipe-mode
streaming shape. This module persists the decoded columns after the first
pass and lets later epochs skip straight to the shuffle pool:

* ``disk`` mode writes one ``.npy`` slab per column under
  ``<cache_dir>/<fingerprint>/`` and re-opens them memory-mapped, so a
  warm epoch costs page-cache reads instead of proto decode.
* ``ram`` mode keeps the concatenated columns in a small process-global
  registry (the training driver recreates its pipeline every epoch, so
  the cache must outlive any one pipeline instance).

Entries are keyed by a fingerprint over the file list (absolute paths,
sizes, mtimes), the decoder/codec version, the CRC setting, the
bad-record policy, and the field width — anything that changes the
decoded rows forces a rebuild rather than serving stale columns. A slab
that fails validation (bad magic, shape mismatch, unreadable) is counted
into :class:`~deepfm_tpu.data.health.DataHealth`, purged, and rebuilt
from the source stream — corruption degrades to one extra decode pass,
never to wrong data or a crash.

Columns are stored in CANONICAL file order (the pipeline's ``files``
list) with per-file record counts, so any epoch's arrival order — the
per-epoch seeded file shuffle — is a cheap reordering of per-file
segments, and the device-resident fit path can upload the whole epoch
as-is and gather batches by index on device.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import warnings
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from .health import DataHealth

# Bump when the slab layout or fingerprint recipe changes: old entries
# then miss cleanly and rebuild instead of misparsing.
CACHE_FORMAT_VERSION = 1

MODES = ("off", "ram", "disk")

_META_NAME = "meta.json"
_SLABS = ("label", "feat_ids", "feat_vals")


class CacheColumns(NamedTuple):
    """One decoded epoch as contiguous columns (canonical file order)."""

    labels: np.ndarray   # [n] float32
    ids: np.ndarray      # [n, field_size] int32
    vals: np.ndarray     # [n, field_size] float32
    counts: np.ndarray   # [num_files] int64, records per canonical file

    @property
    def num_records(self) -> int:
        return int(self.labels.shape[0])

    def nbytes(self) -> int:
        return int(self.labels.nbytes + self.ids.nbytes + self.vals.nbytes)


def decoder_version() -> str:
    """Identity of the decode implementation baked into cached rows."""
    try:
        from ..native import loader  # noqa: PLC0415

        if loader.available():
            return "native-1"
    except Exception:
        pass
    return "python-1"


def compute_fingerprint(files: List[str], *, field_size: int,
                        verify_crc: bool, on_bad_record: str,
                        max_bad_records: int) -> str:
    """Hash of everything that determines the decoded rows."""
    ident: List[object] = [CACHE_FORMAT_VERSION, decoder_version(),
                           int(field_size), bool(verify_crc),
                           str(on_bad_record), int(max_bad_records)]
    for path in files:
        ap = os.path.abspath(path)
        try:
            st = os.stat(ap)
            ident.append([ap, st.st_size, st.st_mtime_ns])
        except OSError:
            # Unstattable (gs:// or vanished): identity falls back to the
            # path alone; remote inputs get no staleness detection.
            ident.append([ap, -1, -1])
    digest = hashlib.sha256(
        json.dumps(ident, separators=(",", ":")).encode()).hexdigest()
    return digest[:32]


# ---------------------------------------------------------------------------
# RAM registry: process-global, bounded. Keyed by fingerprint so a changed
# dataset (or policy) naturally misses; a tiny LRU cap keeps a long-lived
# process that walks many datasets from accumulating epochs forever.
# ---------------------------------------------------------------------------
_RAM_LOCK = threading.Lock()
_RAM_REGISTRY: Dict[str, CacheColumns] = {}
_RAM_MAX_ENTRIES = 2


def _ram_get(fp: str) -> Optional[CacheColumns]:
    with _RAM_LOCK:
        cols = _RAM_REGISTRY.pop(fp, None)
        if cols is not None:
            _RAM_REGISTRY[fp] = cols  # re-insert: LRU order
        return cols


def _ram_put(fp: str, cols: CacheColumns) -> None:
    with _RAM_LOCK:
        _RAM_REGISTRY.pop(fp, None)
        _RAM_REGISTRY[fp] = cols
        while len(_RAM_REGISTRY) > _RAM_MAX_ENTRIES:
            _RAM_REGISTRY.pop(next(iter(_RAM_REGISTRY)))


def clear_ram_cache() -> None:
    """Testing hook: drop every RAM-cached epoch."""
    with _RAM_LOCK:
        _RAM_REGISTRY.clear()


class DecodedEpochCache:
    """Lookup/store façade over one dataset's cache entry.

    ``mode`` is one of :data:`MODES`. The cache never decodes anything
    itself — the pipeline passes a builder callable to
    :meth:`get_or_build`, keeping frame/CRC/bad-record semantics in one
    place (the pipeline) and persistence in another (here).
    """

    def __init__(self, mode: str, cache_dir: str, files: List[str], *,
                 field_size: int, verify_crc: bool, on_bad_record: str,
                 max_bad_records: int,
                 health: Optional[DataHealth] = None) -> None:
        if mode not in MODES:
            raise ValueError(f"decoded_cache must be one of {MODES}, "
                             f"got {mode!r}")
        if mode == "disk" and not cache_dir:
            raise ValueError("decoded_cache='disk' requires a cache dir")
        self.mode = mode
        self.cache_dir = cache_dir
        self.files = list(files)
        self.field_size = int(field_size)
        self.health = health
        self._fp = compute_fingerprint(
            self.files, field_size=field_size, verify_crc=verify_crc,
            on_bad_record=on_bad_record, max_bad_records=max_bad_records)

    # -- identity -----------------------------------------------------
    @property
    def fingerprint(self) -> str:
        return self._fp

    @property
    def entry_dir(self) -> str:
        return os.path.join(self.cache_dir, self._fp)

    # -- lookup -------------------------------------------------------
    def load(self) -> Optional[CacheColumns]:
        """The cached columns, or None on miss. A present-but-invalid
        entry counts into DataHealth, is purged, and reads as a miss."""
        if self.mode == "off":
            return None
        if self.mode == "ram":
            return _ram_get(self._fp)
        entry = self.entry_dir
        if not os.path.isdir(entry):
            return None
        try:
            return self._load_disk(entry)
        except Exception as exc:
            self._note_corrupt(entry, exc)
            shutil.rmtree(entry, ignore_errors=True)
            return None

    def _load_disk(self, entry: str) -> CacheColumns:
        with open(os.path.join(entry, _META_NAME)) as f:
            meta = json.load(f)
        if (meta.get("format") != CACHE_FORMAT_VERSION
                or meta.get("fingerprint") != self._fp):
            raise ValueError(f"stale cache meta: {meta}")
        n = int(meta["num_records"])
        counts = np.asarray(meta["counts"], np.int64)
        if int(counts.sum()) != n or len(counts) != len(self.files):
            raise ValueError("cache meta counts inconsistent")
        arrs = {}
        for name, dtype, shape in (
                ("label", np.float32, (n,)),
                ("feat_ids", np.int32, (n, self.field_size)),
                ("feat_vals", np.float32, (n, self.field_size))):
            a = np.load(os.path.join(entry, name + ".npy"), mmap_mode="r")
            if a.dtype != dtype or a.shape != shape:
                raise ValueError(
                    f"cache slab {name}: dtype/shape {a.dtype}{a.shape} != "
                    f"{np.dtype(dtype)}{shape}")
            arrs[name] = a
        return CacheColumns(arrs["label"], arrs["feat_ids"],
                            arrs["feat_vals"], counts)

    def _note_corrupt(self, entry: str, exc: Exception) -> None:
        if self.health is not None:
            self.health.record_bad_record(entry)
        warnings.warn(
            f"decoded-epoch cache entry {entry} invalid ({exc}); "
            f"rebuilding from source stream", RuntimeWarning, stacklevel=3)

    # -- store --------------------------------------------------------
    def store(self, cols: CacheColumns) -> CacheColumns:
        """Persist freshly decoded columns; returns the (possibly
        memory-mapped) columns future readers will see."""
        if self.mode == "ram":
            _ram_put(self._fp, cols)
            return cols
        if self.mode != "disk":
            return cols
        os.makedirs(self.cache_dir, exist_ok=True)
        # Stage into a temp dir and rename: readers only ever see a
        # complete entry (same discipline as checkpoint save hardening).
        tmp = tempfile.mkdtemp(prefix=f".{self._fp}.", dir=self.cache_dir)
        try:
            np.save(os.path.join(tmp, "label.npy"),
                    np.ascontiguousarray(cols.labels, np.float32))
            np.save(os.path.join(tmp, "feat_ids.npy"),
                    np.ascontiguousarray(cols.ids, np.int32))
            np.save(os.path.join(tmp, "feat_vals.npy"),
                    np.ascontiguousarray(cols.vals, np.float32))
            meta = {"format": CACHE_FORMAT_VERSION, "fingerprint": self._fp,
                    "num_records": cols.num_records,
                    "field_size": self.field_size,
                    "counts": [int(c) for c in cols.counts],
                    "decoder": decoder_version()}
            with open(os.path.join(tmp, _META_NAME), "w") as f:
                json.dump(meta, f)
            entry = self.entry_dir
            shutil.rmtree(entry, ignore_errors=True)
            os.replace(tmp, entry)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        loaded = self.load()
        return loaded if loaded is not None else cols

    def get_or_build(self, builder: Callable[[], CacheColumns]
                     ) -> CacheColumns:
        cols = self.load()
        if cols is not None:
            return cols
        return self.store(builder())


def epoch_chunks(cols: CacheColumns, file_order: List[int],
                 chunk_records: int = 1 << 16
                 ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Slice cached columns into per-file (label, ids, vals) chunk views
    following ``file_order`` — the arrival stream one epoch's shuffle pool
    consumes, without touching the source bytes. Views are zero-copy into
    the slab (or memmap); the pool scatter copies rows out at drain time."""
    starts = np.zeros(len(cols.counts) + 1, np.int64)
    np.cumsum(cols.counts, out=starts[1:])
    out: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    for fi in file_order:
        lo, hi = int(starts[fi]), int(starts[fi + 1])
        for s in range(lo, hi, chunk_records):
            e = min(s + chunk_records, hi)
            if e > s:
                out.append((cols.labels[s:e], cols.ids[s:e],
                            cols.vals[s:e]))
    return out
