"""Minimal ``tf.train.Example`` wire-format codec (no TensorFlow dependency).

The reference keeps all data as TFRecord files of ``tf.train.Example`` protos
with **on-disk** schema ``{label: float, ids: int64[F], values: float[F]}``
(written by ``tools/libsvm_to_tfrecord.py:25-33``, parsed with exactly those
keys at ``1-ps-cpu/DeepFM-dist-ps-for-multipleCPU-multiInstance.py:81-86``;
the parsed tensors are then *renamed* to ``feat_ids``/``feat_vals`` for the
in-memory model_fn contract at ``:92``). We keep TFRecord as the on-disk
format for drop-in compatibility, write the reference key set, and accept
both key sets on read (``ids``/``values`` and the legacy repo aliases
``feat_ids``/``feat_vals`` from pre-r3 files). This module is the pure-Python
reference implementation; the C++ fast path lives in ``deepfm_tpu/native/``.

Wire format facts used (protobuf encoding spec):
  Example        { Features features = 1; }
  Features       { map<string, Feature> feature = 1; }   // map entry: key=1, value=2
  Feature        { oneof { BytesList bytes_list = 1; FloatList float_list = 2;
                           Int64List int64_list = 3; } }
  BytesList      { repeated bytes value = 1; }
  FloatList      { repeated float value = 1 [packed]; }
  Int64List      { repeated int64 value = 1 [packed]; }
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

FeatureValue = Union[np.ndarray, List[float], List[int], List[bytes]]

# ---------------------------------------------------------------------------
# varint / tag helpers
# ---------------------------------------------------------------------------


def write_varint(n: int, out: bytearray) -> None:
    if n < 0:
        n &= (1 << 64) - 1  # two's complement, 64-bit
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _tag(field_number: int, wire_type: int) -> int:
    return (field_number << 3) | wire_type


def _write_len_delimited(field_number: int, payload: bytes, out: bytearray) -> None:
    write_varint(_tag(field_number, 2), out)
    write_varint(len(payload), out)
    out += payload


# ---------------------------------------------------------------------------
# Feature encode
# ---------------------------------------------------------------------------


def _encode_float_list(values: np.ndarray) -> bytes:
    packed = np.asarray(values, dtype="<f4").tobytes()
    inner = bytearray()
    _write_len_delimited(1, packed, inner)  # FloatList.value packed
    return bytes(inner)


def _encode_int64_list(values: np.ndarray) -> bytes:
    inner = bytearray()
    payload = bytearray()
    for v in np.asarray(values, dtype=np.int64).tolist():
        write_varint(v, payload)
    _write_len_delimited(1, bytes(payload), inner)  # Int64List.value packed
    return bytes(inner)


def _encode_bytes_list(values: List[bytes]) -> bytes:
    inner = bytearray()
    for v in values:
        _write_len_delimited(1, v, inner)
    return bytes(inner)


def encode_feature(value: FeatureValue, kind: str) -> bytes:
    """Encode one Feature message. kind in {'float','int64','bytes'}."""
    out = bytearray()
    if kind == "float":
        _write_len_delimited(2, _encode_float_list(np.asarray(value)), out)
    elif kind == "int64":
        _write_len_delimited(3, _encode_int64_list(np.asarray(value)), out)
    elif kind == "bytes":
        _write_len_delimited(1, _encode_bytes_list(list(value)), out)
    else:
        raise ValueError(f"unknown feature kind {kind!r}")
    return bytes(out)


def encode_example(features: Dict[str, Tuple[FeatureValue, str]]) -> bytes:
    """Serialize an Example. ``features`` maps name -> (value, kind)."""
    feat_map = bytearray()
    for name, (value, kind) in features.items():
        entry = bytearray()
        _write_len_delimited(1, name.encode("utf-8"), entry)      # key
        _write_len_delimited(2, encode_feature(value, kind), entry)  # value
        _write_len_delimited(1, bytes(entry), feat_map)           # map entry
    out = bytearray()
    _write_len_delimited(1, bytes(feat_map), out)  # Example.features
    return bytes(out)


# ---------------------------------------------------------------------------
# Feature decode
# ---------------------------------------------------------------------------


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = read_varint(buf, pos)
    elif wire_type == 1:
        pos += 8
    elif wire_type == 2:
        n, pos = read_varint(buf, pos)
        pos += n
    elif wire_type == 5:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    return pos


def _decode_float_list(buf: bytes) -> np.ndarray:
    pos, end = 0, len(buf)
    chunks: List[np.ndarray] = []
    while pos < end:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 2:  # packed
            n, pos = read_varint(buf, pos)
            chunks.append(np.frombuffer(buf, dtype="<f4", count=n // 4, offset=pos))
            pos += n
        elif field == 1 and wt == 5:  # unpacked fixed32
            chunks.append(np.frombuffer(buf, dtype="<f4", count=1, offset=pos))
            pos += 4
        else:
            pos = _skip_field(buf, pos, wt)
    if not chunks:
        return np.zeros((0,), np.float32)
    return np.concatenate(chunks) if len(chunks) > 1 else chunks[0].copy()


def _decode_int64_list(buf: bytes) -> np.ndarray:
    pos, end = 0, len(buf)
    vals: List[int] = []
    while pos < end:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 2:  # packed
            n, pos = read_varint(buf, pos)
            stop = pos + n
            while pos < stop:
                v, pos = read_varint(buf, pos)
                if v >= 1 << 63:
                    v -= 1 << 64
                vals.append(v)
        elif field == 1 and wt == 0:
            v, pos = read_varint(buf, pos)
            if v >= 1 << 63:
                v -= 1 << 64
            vals.append(v)
        else:
            pos = _skip_field(buf, pos, wt)
    return np.asarray(vals, dtype=np.int64)


def _decode_bytes_list(buf: bytes) -> List[bytes]:
    pos, end = 0, len(buf)
    vals: List[bytes] = []
    while pos < end:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 2:
            n, pos = read_varint(buf, pos)
            vals.append(buf[pos:pos + n])
            pos += n
        else:
            pos = _skip_field(buf, pos, wt)
    return vals


def decode_feature(buf: bytes) -> Tuple[str, FeatureValue]:
    """Decode one Feature message -> (kind, value)."""
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt != 2:
            pos = _skip_field(buf, pos, wt)
            continue
        n, pos = read_varint(buf, pos)
        payload = buf[pos:pos + n]
        pos += n
        if field == 1:
            return "bytes", _decode_bytes_list(payload)
        if field == 2:
            return "float", _decode_float_list(payload)
        if field == 3:
            return "int64", _decode_int64_list(payload)
    return "bytes", []


def decode_example(buf: bytes) -> Dict[str, Tuple[str, FeatureValue]]:
    """Parse a serialized Example -> {name: (kind, value)}."""
    out: Dict[str, Tuple[str, FeatureValue]] = {}
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 2:  # Example.features
            n, pos = read_varint(buf, pos)
            fpos, fend = pos, pos + n
            pos = fend
            while fpos < fend:
                ftag, fpos = read_varint(buf, fpos)
                ffield, fwt = ftag >> 3, ftag & 7
                if ffield == 1 and fwt == 2:  # map entry
                    en, fpos = read_varint(buf, fpos)
                    epos, eend = fpos, fpos + en
                    fpos = eend
                    key = b""
                    feat = b""
                    while epos < eend:
                        etag, epos = read_varint(buf, epos)
                        efield, ewt = etag >> 3, etag & 7
                        if ewt != 2:
                            epos = _skip_field(buf, epos, ewt)
                            continue
                        vn, epos = read_varint(buf, epos)
                        if efield == 1:
                            key = buf[epos:epos + vn]
                        elif efield == 2:
                            feat = buf[epos:epos + vn]
                        epos += vn
                    out[key.decode("utf-8")] = decode_feature(feat)
                else:
                    fpos = _skip_field(buf, fpos, fwt)
        else:
            pos = _skip_field(buf, pos, wt)
    return out


# ---------------------------------------------------------------------------
# Fixed-schema fast path used by the input pipeline
# ---------------------------------------------------------------------------

LABEL_KEY = "label"
# Optional second task label (e.g. conversion for --tasks ctr,cvr). Absent
# from single-task files; decode defaults it to 0.0.
LABEL2_KEY = "label2"
# On-disk keys as written by the reference converter
# (tools/libsvm_to_tfrecord.py:25-33).
IDS_KEY = "ids"
VALS_KEY = "values"
# Pre-r3 files from this repo used the in-memory feature names on disk;
# still accepted on read.
LEGACY_IDS_KEY = "feat_ids"
LEGACY_VALS_KEY = "feat_vals"
# Optional ragged user-history pair (variable length, may be absent or
# empty). Decoded into fixed [max_len] id/mask columns by
# decode_ctr_example_hist / the native dfm_decode_ctr_hist entry.
HIST_IDS_KEY = "hist_ids"
HIST_VALS_KEY = "hist_vals"


def encode_ctr_example(label: float, ids: np.ndarray, vals: np.ndarray,
                       label2: Optional[float] = None,
                       hist_ids: Optional[np.ndarray] = None,
                       hist_vals: Optional[np.ndarray] = None) -> bytes:
    """Encode the reference CTR schema (tools/libsvm_to_tfrecord.py:25-33).

    ``label2`` (second-task label) is appended as an extra ``label2`` float
    key when given; ``hist_ids``/``hist_vals`` (ragged user history, any
    length including zero) are appended as an extra int64/float pair when
    given. With all optionals ``None`` the output is byte-identical to the
    historical single-label encoding, so existing files and golden bytes are
    unaffected.
    """
    features = {
        LABEL_KEY: (np.asarray([label], np.float32), "float"),
        IDS_KEY: (np.asarray(ids, np.int64), "int64"),
        VALS_KEY: (np.asarray(vals, np.float32), "float"),
    }
    if label2 is not None:
        features[LABEL2_KEY] = (np.asarray([label2], np.float32), "float")
    if hist_ids is not None:
        features[HIST_IDS_KEY] = (np.asarray(hist_ids, np.int64), "int64")
        hv = hist_vals if hist_vals is not None else np.ones(
            len(np.asarray(hist_ids)), np.float32)
        features[HIST_VALS_KEY] = (np.asarray(hv, np.float32), "float")
    return encode_example(features)


def decode_ctr_example(buf: bytes, field_size: int) -> Tuple[float, np.ndarray, np.ndarray]:
    """Decode one CTR Example; validates fixed field_size (parse_example analog).

    Accepts both the reference's on-disk keys (``ids``/``values``) and this
    repo's legacy aliases (``feat_ids``/``feat_vals``).
    """
    feats = decode_example(buf)
    try:
        _, label = feats[LABEL_KEY]
        if IDS_KEY in feats:
            _, ids = feats[IDS_KEY]
        else:
            _, ids = feats[LEGACY_IDS_KEY]
        if VALS_KEY in feats:
            _, vals = feats[VALS_KEY]
        else:
            _, vals = feats[LEGACY_VALS_KEY]
    except KeyError:
        raise ValueError(
            "Example is missing CTR schema keys: found "
            f"{sorted(feats)}, need 'label' plus 'ids'/'values' "
            "(reference schema) or 'feat_ids'/'feat_vals' (legacy)") from None
    ids = np.asarray(ids, np.int64)
    vals = np.asarray(vals, np.float32)
    if ids.shape[0] != field_size or vals.shape[0] != field_size:
        raise ValueError(
            f"expected field_size={field_size}, got ids={ids.shape[0]} vals={vals.shape[0]}")
    return float(np.asarray(label, np.float32)[0]), ids, vals


def decode_ctr_example2(
        buf: bytes, field_size: int
) -> Tuple[float, float, np.ndarray, np.ndarray]:
    """Two-label variant of :func:`decode_ctr_example` for multi-task data.

    Returns ``(label, label2, ids, vals)``; ``label2`` defaults to 0.0 when
    the key is absent (single-task files remain readable as multi-task input
    with an all-negative second task). This is the bit-identical Python
    mirror of the native ``dfm_decode_ctr2_ex`` entry.
    """
    feats = decode_example(buf)
    label, ids, vals = decode_ctr_example(buf, field_size)
    label2 = 0.0
    if LABEL2_KEY in feats:
        _, l2 = feats[LABEL2_KEY]
        l2 = np.asarray(l2, np.float32)
        if l2.shape[0] != 1:
            raise ValueError(
                f"'label2' must be a single float, got {l2.shape[0]} values")
        label2 = float(l2[0])
    return label, label2, ids, vals


def decode_ctr_example_hist(
        buf: bytes, field_size: int, max_len: int
) -> Tuple[float, np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """History variant of :func:`decode_ctr_example` for sequence models.

    Returns ``(label, ids, vals, hist_ids [max_len] int32,
    hist_vals [max_len] float32, hist_len)``. The ragged history pair is
    zero-padded to ``max_len`` and silently truncated past it
    (``hist_len = min(actual, max_len)``); records with neither history key
    decode with ``hist_len = 0`` and all-zero columns, so single-task files
    without history remain readable. A record carrying only one of the pair,
    or the pair with differing lengths, is a schema error. This is the
    bit-identical Python mirror of the native ``dfm_decode_ctr_hist`` entry.
    """
    feats = decode_example(buf)
    label, ids, vals = decode_ctr_example(buf, field_size)
    h_ids = np.asarray(feats[HIST_IDS_KEY][1], np.int64) \
        if HIST_IDS_KEY in feats else np.zeros((0,), np.int64)
    h_vals = np.asarray(feats[HIST_VALS_KEY][1], np.float32) \
        if HIST_VALS_KEY in feats else np.zeros((0,), np.float32)
    if h_ids.shape[0] != h_vals.shape[0]:
        raise ValueError(
            f"history length mismatch: {h_ids.shape[0]} hist_ids vs "
            f"{h_vals.shape[0]} hist_vals")
    n = min(h_ids.shape[0], int(max_len))
    out_ids = np.zeros((max_len,), np.int32)
    out_vals = np.zeros((max_len,), np.float32)
    out_ids[:n] = h_ids[:n].astype(np.int32)
    out_vals[:n] = h_vals[:n]
    return label, ids, vals, out_ids, out_vals, n
