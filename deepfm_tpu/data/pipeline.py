"""Host-side input pipeline: TFRecord -> fixed-shape numpy batches for TPU.

TPU-native re-design of the reference's two ``input_fn`` flavors
(``1-ps-cpu/...py:76-133`` file/pipe, ``2-hvd-gpu/...py:74-133`` horovod):

  * File mode: per-epoch file-list shuffle, shard policy (``sharding.py``),
    record shuffle buffer, batch -> *vectorized* decode (the reference decodes
    with ``tf.parse_example`` after ``.batch()`` — here the batched decode is
    the native C++ decoder or the pure-Python codec), drop_remainder, repeat.
  * Streaming mode (Pipe analog): sequential non-seekable stream, one pass,
    no re-open per epoch (the FIFO pitfall at ``2-hvd-gpu/...py:396``).
  * Prefetch: a background thread keeps ``prefetch_batches`` ready, the host
    analog of ``dataset.prefetch`` — with TPU async dispatch this overlaps
    host decode with device step time.

Outputs fixed-shape batches ``{"feat_ids": int32[B,F], "feat_vals": f32[B,F],
"label": f32[B,1]}`` — static shapes so every step hits the same XLA program.
With ``num_labels=2`` (multi-task training, ``--tasks ctr,cvr``) batches gain
a ``"label2"`` f32[B,1] column decoded from the optional on-disk key.
"""

from __future__ import annotations

import collections
import contextlib
import os
import queue
import threading
import time
from typing import BinaryIO, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import example_codec, fileio, sharding, tfrecord
from .health import BadRecordPolicy, DataHealth

Batch = Dict[str, np.ndarray]


def decode_batch_python(records: Sequence[bytes], field_size: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized-decode fallback: parse each Example with the Python codec."""
    n = len(records)
    labels = np.empty((n,), np.float32)
    ids = np.empty((n, field_size), np.int32)
    vals = np.empty((n, field_size), np.float32)
    for i, rec in enumerate(records):
        lab, rid, rval = example_codec.decode_ctr_example(rec, field_size)
        labels[i] = lab
        ids[i] = rid.astype(np.int32)
        vals[i] = rval
    return labels, ids, vals


def decode_batch2_python(records: Sequence[bytes], field_size: int
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray]:
    """Two-label decode fallback (multi-task input): ``label2`` defaults to
    0.0 for single-label records. Mirrors ``native.loader.decode_batch2``."""
    n = len(records)
    labels = np.empty((n,), np.float32)
    labels2 = np.empty((n,), np.float32)
    ids = np.empty((n, field_size), np.int32)
    vals = np.empty((n, field_size), np.float32)
    for i, rec in enumerate(records):
        lab, lab2, rid, rval = example_codec.decode_ctr_example2(
            rec, field_size)
        labels[i] = lab
        labels2[i] = lab2
        ids[i] = rid.astype(np.int32)
        vals[i] = rval
    return labels, labels2, ids, vals


def _get_decoder(use_native: bool):
    if use_native:
        try:
            from ..native import loader  # noqa: PLC0415 (lazy: builds .so on first use)
            if loader.available():
                return loader.decode_batch
        except Exception:
            pass
    return decode_batch_python


def _get_decoder2(use_native: bool):
    """Two-label sibling of ``_get_decoder`` (same fallback discipline)."""
    if use_native:
        try:
            from ..native import loader  # noqa: PLC0415
            if loader.available():
                return loader.decode_batch2
        except Exception:
            pass
    return decode_batch2_python


def decode_batch_hist_python(records: Sequence[bytes], field_size: int,
                             max_len: int
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                        np.ndarray, np.ndarray, np.ndarray]:
    """History decode fallback (sequence-model input): the ragged
    ``hist_ids``/``hist_vals`` pair zero-padded/truncated to ``max_len`` per
    record. Mirrors ``native.loader.decode_batch_hist``."""
    n = len(records)
    labels = np.empty((n,), np.float32)
    ids = np.empty((n, field_size), np.int32)
    vals = np.empty((n, field_size), np.float32)
    hist_ids = np.zeros((n, max_len), np.int32)
    hist_vals = np.zeros((n, max_len), np.float32)
    hist_len = np.zeros((n,), np.int32)
    for i, rec in enumerate(records):
        lab, rid, rval, hid, hval, hn = example_codec.decode_ctr_example_hist(
            rec, field_size, max_len)
        labels[i] = lab
        ids[i] = rid.astype(np.int32)
        vals[i] = rval
        hist_ids[i] = hid
        hist_vals[i] = hval
        hist_len[i] = hn
    return labels, ids, vals, hist_ids, hist_vals, hist_len


def _get_decoder_hist(use_native: bool):
    """History sibling of ``_get_decoder``. The native entry internally
    falls back per-record to the Python codec mirror on a stale .so, so
    either return emits identical values."""
    if use_native:
        try:
            from ..native import loader  # noqa: PLC0415
            if loader.available():
                return loader.decode_batch_hist
        except Exception:
            pass
    return decode_batch_hist_python


# Chunk size for the native streaming reader: big enough to amortize the
# per-call framing cost, small enough to keep RSS constant on huge shards.
_NATIVE_CHUNK_BYTES = 64 << 20

# Minimum records per sub-span when the fused drain decode splits one big
# chunk across reader threads (below this the spawn overhead beats the win).
# Module-level so tests can lower it to exercise the split arithmetic.
_SCATTER_SPLIT_MIN = 4096

# Read size used past a file's stat()ed length: files can grow between the
# stat and the read, so probe for extra bytes — but with a bounded request,
# not a full chunk (BufferedReader pre-allocates the entire requested size,
# so a 64MB request that returns 0 bytes at EOF still costs a 64MB alloc).
_EOF_PROBE_BYTES = 64 << 10

# Env knob for scripts/bench_multiprocess.py: inflate the host emission cost
# by N synthetic ns/record (a GIL-releasing sleep in the drain), making the
# host path the bottleneck even on a 1-core box so the transfer-ahead
# overlap A/B has something to overlap. Never set in production.
_SYNTH_STALL_ENV = "DEEPFM_TPU_SYNTH_HOST_NS_PER_RECORD"


def _timed(stats, name: str):
    """Stage-timing context: records wall ns into ``stats`` (a
    ``profiling.HostStageStats``), or free when no collector is attached."""
    if stats is None:
        return contextlib.nullcontext()
    return stats.stage(name)


def _native_loader():
    """The native decoder module, or None when toolchain/build unavailable."""
    try:
        from ..native import loader  # noqa: PLC0415
        if loader.available():
            return loader
    except ImportError:
        pass
    return None


def _iter_framed_stream(stream: BinaryIO, loader, verify_crc: bool = True,
                        *, path: str = "", policy: Optional[BadRecordPolicy] = None,
                        size_hint: Optional[int] = None, stats=None
                        ) -> Iterator[Tuple[bytes, np.ndarray, np.ndarray]]:
    """Chunked read() + C-speed framing with a carried partial tail: yields
    (buf, offsets, lengths) per chunk from any sequential byte source.
    Constant memory on multi-GB inputs, and plain I/O errors stay catchable
    Python exceptions (an mmap would turn them into SIGBUS). The single
    framing state machine shared by the record iterator, the vectorized
    file path, and the streaming (Pipe-mode) path.

    Bad frames: the native framer rejects a corrupt chunk wholesale; the
    chunk is then re-scanned by the pure-Python framer, which locates the
    exact absolute byte offset (for the path+offset error message) and
    applies the same raise/skip ``policy`` as the pure-Python decode path —
    so both decoder paths surface identical locations and skip-policy
    behavior. Clean data never takes the re-scan, keeping the fast path
    byte-identical (TestPooledEmissionGolden).

    ``size_hint`` (the stat()ed file length, when the caller has one) caps
    each read request at the bytes actually remaining: BufferedReader
    pre-allocates the full requested size per call, so an unhinted 64MB
    request against a 5MB file costs a 64MB alloc + trim every chunk — the
    second-largest host-path overhead in the r6 per-stage breakdown. Past
    the hint the loop keeps reading in ``_EOF_PROBE_BYTES`` requests (files
    may grow after the stat), so the emitted spans are identical with or
    without the hint."""
    carry = b""
    carry_base = 0  # absolute stream offset of carry[0]
    read_size = _NATIVE_CHUNK_BYTES
    pos = 0  # bytes read from the stream so far
    while True:
        if size_hint is not None and size_hint > pos:
            want = min(read_size, size_hint - pos)
        elif size_hint is not None:
            want = _EOF_PROBE_BYTES
        else:
            want = read_size
        with _timed(stats, "read"):
            chunk = stream.read(want)
        if not chunk:
            if carry:
                # Strict parse of the leftover: surfaces truncated-input
                # as an error (or a counted skip under the policy).
                with _timed(stats, "frame"):
                    try:
                        offsets, lengths = loader.split_frames(
                            carry, verify_crc=verify_crc)
                    except IOError:
                        offsets, lengths, _, _ = tfrecord.scan_frames_partial(
                            carry, verify_crc=verify_crc, final=True,
                            base_offset=carry_base, path=path, policy=policy)
                yield carry, offsets, lengths
            return
        pos += len(chunk)
        buf = carry + chunk if carry else chunk
        buf_base = carry_base
        abort = False
        with _timed(stats, "frame"):
            try:
                offsets, lengths, consumed = loader.split_frames_partial(
                    buf, verify_crc=verify_crc)
            except IOError:
                offsets, lengths, consumed, abort = \
                    tfrecord.scan_frames_partial(
                        buf, verify_crc=verify_crc, final=False,
                        base_offset=buf_base, path=path, policy=policy)
        yield buf, offsets, lengths
        if abort:  # framing cannot resync past the corruption
            return
        carry = buf[consumed:]
        carry_base = buf_base + consumed
        # A record larger than the read size frames nothing (consumed=0);
        # double the next read so it completes in O(n) total copying
        # rather than O(n^2) re-copies of the growing carry.
        read_size = (_NATIVE_CHUNK_BYTES if consumed
                     else max(read_size * 2, _NATIVE_CHUNK_BYTES))


def _health_retry_cb(policy: Optional[BadRecordPolicy], path: str):
    """on_retry hook recording healed transient reads into DataHealth."""
    if policy is None:
        return None
    health = policy.health
    return lambda exc, n: health.record_retry(path)


def _iter_framed_chunks(path: str, loader, verify_crc: bool = True, *,
                        policy: Optional[BadRecordPolicy] = None,
                        retry_policy=None, stats=None
                        ) -> Iterator[Tuple[bytes, np.ndarray, np.ndarray]]:
    """File-path front-end of ``_iter_framed_stream`` (local or gs://),
    reading through a ResilientStream so transient mid-file errors heal.
    The stat()ed length becomes the framer's ``size_hint`` (right-sized
    read buffers); a failed stat degrades to unhinted reads, not an error."""
    try:
        size_hint: Optional[int] = fileio.size(path)
    except Exception:
        size_hint = None
    with fileio.open_resilient(path, policy=retry_policy,
                               on_retry=_health_retry_cb(policy, path)) as f:
        yield from _iter_framed_stream(f, loader, verify_crc,
                                       path=path, policy=policy,
                                       size_hint=size_hint, stats=stats)


def _iter_file_records(path: str, use_native: bool, verify_crc: bool = True,
                       *, policy: Optional[BadRecordPolicy] = None,
                       retry_policy=None) -> Iterator[bytes]:
    """Per-file record iterator with the same CRC policy on both paths
    (same integrity guarantee regardless of toolchain)."""
    loader = _native_loader() if use_native else None
    if loader is not None:
        for buf, offsets, lengths in _iter_framed_chunks(
                path, loader, verify_crc, policy=policy,
                retry_policy=retry_policy):
            for off, ln in zip(offsets.tolist(), lengths.tolist()):
                yield buf[off:off + ln]
        return
    yield from tfrecord.iter_records(
        path, verify_crc=verify_crc, policy=policy, resilient=True,
        retry_policy=retry_policy, on_retry=_health_retry_cb(policy, path))


def _available_cores() -> int:
    try:
        return len(os.sched_getaffinity(0))  # respects cgroup/affinity limits
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


def _trim_skip(src: Iterator[Tuple[Batch, int, int]], skip: int, bs: int
               ) -> Iterator[Tuple[Batch, int, int]]:
    """Drop the first ``skip`` batches from a grouped ``(rows, m, n_ex)``
    stream — whole emissions dropped, a partially-covered group sliced (the
    surviving rows stay one contiguous block)."""
    for rows, m, n_ex in src:
        if skip:
            if m <= skip:
                skip -= m
                continue
            rows = {key: v[skip * bs:] for key, v in rows.items()}
            m -= skip
            n_ex -= skip * bs
            skip = 0
        yield rows, m, n_ex


def _group_plain_batches(batches: Iterator[Batch], k: int, bs: int
                         ) -> Iterator[Tuple[Batch, int, int]]:
    """Fallback superbatch grouping over a per-batch stream (stack copy):
    full groups of k, short tails flushed as singles."""
    group: List[Batch] = []
    for b in batches:
        if b["label"].shape[0] == bs:
            group.append(b)
            if len(group) == k:
                yield ({key: np.concatenate([g[key] for g in group])
                        for key in group[0]}, k, k * bs)
                group = []
        else:  # short tail: flush pending then emit single
            for g in group:
                yield g, 1, bs
            group = []
            yield b, 1, b["label"].shape[0]
    for g in group:
        yield g, 1, bs


class _DrainPool:
    """Lazily-created drain-decode thread pool, owned by ONE iterator.

    Persistent across every pool drain of that iterator (spawn/join per
    drain would recur every shuffle_buffer records), but private to it: a
    pipeline-shared executor let one iterator's epoch-end release kill a
    concurrent iterator's in-flight drain (advisor r5).
    """

    def __init__(self, n_threads: int):
        self._n = n_threads
        self._ex = None

    def get(self):
        if self._ex is None:
            from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415
            self._ex = ThreadPoolExecutor(self._n)
        return self._ex

    def shutdown(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False)
            self._ex = None


class CtrPipeline:
    """TFRecord CTR input pipeline producing fixed-shape numpy batches."""

    def __init__(
        self,
        files: Sequence[str],
        *,
        field_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = True,
        shuffle_files: bool = True,
        shuffle_buffer: int = 10000,
        drop_remainder: bool = True,
        seed: int = 42,
        shard: Optional[sharding.ShardSpec] = None,
        prefetch_batches: int = 4,
        use_native_decoder: bool = True,
        native_assembly: bool = True,
        reader_threads: int = 4,
        verify_crc: bool = False,  # speed-over-parity default (see Config); codec fns keep True
        epoch_offset: int = 0,
        skip_batches: int = 0,
        on_bad_record: str = "raise",
        max_bad_records: int = 0,
        retry_policy=None,
        input_workers: int = 0,
        input_worker_slab_records: Optional[int] = None,
        input_worker_death: str = "raise",
        stall_timeout_s: float = 0.0,
        decoded_cache: str = "off",
        decoded_cache_dir: str = "",
        num_labels: int = 1,
        history: bool = False,
        history_max_len: int = 20,
    ):
        if shard is not None:
            self._files: Tuple[str, ...] = shard.files
            self._record_shard = shard.record_shard
        else:
            self._files = tuple(files)
            self._record_shard = None
        self.field_size = field_size
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.shuffle_files = shuffle_files
        self.shuffle_buffer = shuffle_buffer
        self.drop_remainder = drop_remainder
        self.seed = seed
        self.prefetch_batches = prefetch_batches
        # Clamp to AVAILABLE cores: on a 1-core host a 4-thread decode pool
        # only adds contention (~6% measured); extra threads help only when
        # the GIL-released C decoder can actually run in parallel. Use the
        # scheduler affinity mask where exposed (cgroup/CI-quota accurate),
        # not os.cpu_count() (physical cores).
        self.reader_threads = max(1, min(reader_threads, _available_cores()))
        self._use_native = use_native_decoder
        # Fused decode->assemble (one C call per drain writing straight into
        # the transfer-layout pool). Off = per-chunk scatter-decode, which
        # emits bit-identical bytes — the flag exists as a kill switch and
        # for the bench/tests to measure and pin that parity. Ignored when
        # the built .so predates the entry point (loader.has_assemble()).
        self.native_assembly = bool(native_assembly)
        self.verify_crc = verify_crc
        # Optional per-stage wall-time collector (profiling.HostStageStats).
        # None outside the bench: every timing site no-ops through _timed.
        self.stage_stats = None
        self._synth_stall_ns = float(os.environ.get(_SYNTH_STALL_ENV) or 0.0)
        # Shifts the internal epoch index used for shuffle seeding. The task
        # driver recreates the pipeline per epoch with num_epochs=1 (the
        # reference's file-mode shape, 2-hvd-gpu/...py:390-394); without the
        # offset every driver epoch would replay epoch-0's byte-identical
        # shuffle order (VERDICT r2 weak #2).
        self.epoch_offset = epoch_offset
        # Step-accurate resume: drop the first N emitted batches (the
        # already-trained prefix of an interrupted epoch). Applied INSIDE
        # each emission path so the skipped stream is identical to the one
        # the interrupted run trained on — an external wrapper would both
        # hide iter_superbatches (killing the zero-copy feed) and, worse,
        # skip along the k=1 pooled stream while training had consumed the
        # k-pooled stream, whose batch order differs past the first drain.
        self.skip_batches = skip_batches
        # Multi-label emission (--tasks ctr,cvr): batches gain a "label2"
        # [B, 1] column decoded from the optional on-disk key. The
        # multi-label stream takes the eager decode path only — the fused
        # drain entry, the shm worker slabs, and the decoded cache are
        # single-label layouts by design, so they are forced off here
        # rather than silently dropping the second column.
        self.num_labels = max(1, int(num_labels))
        if self.num_labels > 2:
            raise ValueError(
                f"num_labels must be 1 or 2, got {num_labels} (the on-disk "
                "schema carries at most one extra 'label2' column)")
        if self.num_labels > 1:
            input_workers = 0
            native_assembly = False
            self.native_assembly = False
            decoded_cache = "off"
        # History emission (sequence models): batches gain fixed "hist_ids"
        # int32[B, L] / "hist_mask" f32[B, L] columns decoded from the
        # optional ragged on-disk pair, padded/truncated to history_max_len
        # (the mask is the decoded hist_vals column — zero past each
        # record's actual length, so it doubles as attention weights). Like
        # num_labels>1, the history stream takes the eager decode path only:
        # the fused drain entry, the shm worker slabs, and the decoded
        # cache are fixed-arity single-label layouts by design.
        self.history = bool(history)
        self.history_max_len = int(history_max_len)
        if self.history and self.num_labels > 1:
            raise ValueError(
                "history=True is incompatible with num_labels>1 (one "
                "optional schema extension per stream)")
        if self.history and self.history_max_len < 1:
            raise ValueError(
                f"history_max_len must be >= 1 when history=True, got "
                f"{history_max_len}")
        if self.history:
            input_workers = 0
            native_assembly = False
            self.native_assembly = False
            decoded_cache = "off"
        # Pool/chunk column width: history rides the existing (labels, ids,
        # vals) chunk tuples as extra packed columns (ids -> [n, F+L] int32
        # feat||hist ids, vals -> [n, F+L] f32 feat vals||hist mask), split
        # back out at batch-assembly time.
        self._pool_cols = self.field_size + (
            self.history_max_len if self.history else 0)
        self._decode = _get_decoder(use_native_decoder)
        self._decode2 = _get_decoder2(use_native_decoder)
        self._decode_hist = _get_decoder_hist(use_native_decoder)
        # Multi-process input service (opt-in, see workers.py): decode
        # worker processes feed shared-memory slabs; 0 = in-process decode
        # (the default path, byte-for-byte unchanged). Engaged only where
        # its determinism contract holds: native decoder present and no
        # record-level shard (workers see per-file streams, not global
        # record indices).
        self.input_workers = max(0, int(input_workers))
        self.input_worker_slab_records = input_worker_slab_records
        self.input_worker_death = input_worker_death
        # Stall watchdog on ring reads: a wedged-but-alive worker (hung
        # mount, deadlocked decoder) raises InputStallError instead of
        # polling forever. 0 = wait indefinitely (the pre-watchdog behavior).
        self.stall_timeout_s = float(stall_timeout_s)
        # Fault tolerance: one DataHealth/BadRecordPolicy pair per pipeline
        # (skip budget spans every epoch of this pipeline's life); the
        # retry policy governs opens + mid-file reopen-and-seek healing.
        self.health = DataHealth()
        self._bad_policy = BadRecordPolicy(
            on_bad_record, max_bad_records, self.health)
        self._retry_policy = retry_policy
        # Decoded-epoch cache (opt-in, see cache.py): frame+decode once,
        # serve later epochs from contiguous column slabs through the same
        # shuffle pool. Disabled under record-sharding — the 1/world filter
        # keys off the global record index of the per-epoch file order, so
        # the kept-row set is epoch-dependent and uncacheable.
        self._on_bad_record = on_bad_record
        self._max_bad_records = max_bad_records
        if decoded_cache != "off" and self._record_shard is not None:
            import warnings  # noqa: PLC0415
            warnings.warn(
                "decoded_cache disabled: record-level sharding keeps rows "
                "by per-epoch global index, which a cache cannot reproduce",
                RuntimeWarning, stacklevel=2)
            decoded_cache = "off"
        self.decoded_cache = decoded_cache
        self.decoded_cache_dir = decoded_cache_dir
        self._cache_cols = None  # built/loaded lazily, reused across epochs

    # ------------------------------------------------------------------
    # Vectorized fast path (native decode straight to arrays).
    # ------------------------------------------------------------------
    def _iter_decoded_chunks(self, epoch: int, loader
                             ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Per ~64MB chunk: frame + eager decode -> (labels, ids, vals)
        arrays. Framing, file order, CRC, and shard selection all come from
        ``_iter_framed_span_chunks`` (the single source shared with the
        fused path); the record-shard filter is applied to the SPAN arrays
        before decode, so sharded ranks decode only their own rows. Decode
        runs on a thread pool (the C decoder releases the GIL, so this
        scales on real cores) while framing/IO stays on the producer;
        bounded in-flight depth keeps memory ~threads x chunk; FIFO
        consumption preserves deterministic chunk order."""
        def decode(job: Tuple[bytes, np.ndarray, np.ndarray]):
            buf, offsets, lengths = job
            if self.num_labels > 1:
                labels, labels2, ids, vals = loader.decode_spans2(
                    buf, offsets, lengths, self.field_size)
                return np.stack([labels, labels2], axis=1), ids, vals
            if self.history:
                labels, ids, vals, hid, hmask, _ = loader.decode_spans_hist(
                    buf, offsets, lengths, self.field_size,
                    self.history_max_len)
                # Packed-column chunk layout (see __init__): feat||hist.
                return (labels, np.hstack([ids, hid]),
                        np.hstack([vals, hmask]))
            return loader.decode_spans(buf, offsets, lengths, self.field_size)

        jobs = self._iter_framed_span_chunks(epoch, loader)
        n_threads = self.reader_threads
        if n_threads <= 1:
            for job in jobs:
                yield decode(job)
        else:
            import collections  # noqa: PLC0415
            from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415
            with ThreadPoolExecutor(n_threads) as ex:
                inflight: "collections.deque" = collections.deque()
                for job in jobs:
                    inflight.append(ex.submit(decode, job))
                    while len(inflight) >= n_threads + 1:
                        yield inflight.popleft().result()
                while inflight:
                    yield inflight.popleft().result()

    def _iter_batches_vectorized(self, loader) -> Iterator[Batch]:
        """Pool decoded chunks to >= max(shuffle_buffer, chunk) rows, permute
        the pool, then slice batches — at least the record path's shuffle
        quality (the pool is the whole epoch on small data, a >= 64MB window
        on large), with zero per-record Python."""
        for rows, _, _ in self._iter_pooled(loader, 1):
            yield rows

    def _epoch_files(self, epoch: int) -> List[str]:
        """THE per-epoch file order: deterministic seeded reshuffle
        (reference shuffles the file list once at :373-377; here it varies
        per epoch). Single source shared by the record path, the chunk
        paths, and the input-service worker assignment — worker-path batch
        reproducibility rests on all of them agreeing on this order."""
        files = list(self._files)
        if self.shuffle_files:
            np.random.default_rng(self.seed + epoch).shuffle(files)
        return files

    def _epoch_file_order(self, epoch: int) -> List[int]:
        """Canonical-file INDICES in ``_epoch_files`` order (shuffling a
        position list consumes the rng identically to shuffling the path
        list, so both views of the per-epoch order always agree)."""
        order = list(range(len(self._files)))
        if self.shuffle_files:
            np.random.default_rng(self.seed + epoch).shuffle(order)
        return order

    # ------------------------------------------------------------------
    # Decoded-epoch cache (tier 1 of the input acceleration layer).
    # ------------------------------------------------------------------
    def _make_cache(self):
        from . import cache as cache_lib  # noqa: PLC0415
        return cache_lib.DecodedEpochCache(
            self.decoded_cache, self.decoded_cache_dir, list(self._files),
            field_size=self.field_size, verify_crc=self.verify_crc,
            on_bad_record=self._on_bad_record,
            max_bad_records=self._max_bad_records, health=self.health)

    def _build_cache_columns(self):
        """One frame+decode pass in CANONICAL file order -> contiguous
        columns + per-file counts. Reuses the exact framing/CRC/bad-record
        machinery of the streaming paths, so a cached dataset contains
        precisely the rows a streamed epoch would have trained on."""
        from . import cache as cache_lib  # noqa: PLC0415
        loader = _native_loader() if self._use_native else None
        counts = np.zeros(len(self._files), np.int64)
        labs: List[np.ndarray] = []
        idss: List[np.ndarray] = []
        valss: List[np.ndarray] = []
        for fi, path in enumerate(self._files):
            n_file = 0
            if loader is not None:
                for buf, offsets, lengths in _iter_framed_chunks(
                        path, loader, self.verify_crc,
                        policy=self._bad_policy,
                        retry_policy=self._retry_policy):
                    if len(offsets) == 0:
                        continue
                    lab, ids, vals = loader.decode_spans(
                        buf, offsets, lengths, self.field_size)
                    labs.append(lab)
                    idss.append(ids)
                    valss.append(vals)
                    n_file += len(lab)
            else:
                recs = list(_iter_file_records(
                    path, False, self.verify_crc, policy=self._bad_policy,
                    retry_policy=self._retry_policy))
                if recs:
                    lab, ids, vals = self._decode(recs, self.field_size)
                    labs.append(lab)
                    idss.append(ids.astype(np.int32, copy=False))
                    valss.append(vals)
                    n_file += len(lab)
            counts[fi] = n_file
        if counts.sum() == 0 and len(self._files):
            raise IOError(f"no records found in {len(self._files)} files")
        return cache_lib.CacheColumns(
            np.concatenate(labs).astype(np.float32, copy=False),
            np.concatenate(idss),
            np.concatenate(valss),
            counts)

    def decoded_epoch_columns(self):
        """The dataset as cached columns, building the cache on miss (also
        the upload source for the device-resident fit path). Raises if the
        cache is off."""
        if self.decoded_cache == "off":
            raise RuntimeError("decoded_epoch_columns requires decoded_cache")
        if self._cache_cols is None:
            self._cache_cols = self._make_cache().get_or_build(
                self._build_cache_columns)
        return self._cache_cols

    def decoded_cache_fingerprint(self) -> str:
        """Identity of the cached columns (device-upload cache key)."""
        return self._make_cache().fingerprint

    def device_epoch_indices(self, epoch: int, k: int = 1) -> np.ndarray:
        """Row indices into the cached columns in EXACTLY the order the
        staged pooled path would emit them this epoch — the tiny per-epoch
        upload of the device-resident fit (4 bytes/record vs re-sending
        every row).

        Valid only in the single-drain regime (the pool covers the whole
        epoch: ``n < max(shuffle_buffer, k*batch_size)``), where the final
        drain scatters arrival row j to position perm[j] of one full
        permutation, so the emitted sequence is ``arrival[argsort(perm)]``.
        With a smaller pool the drain points depend on chunk boundaries and
        the caller must keep the staged path instead."""
        cols = self.decoded_epoch_columns()
        starts = np.zeros(len(cols.counts) + 1, np.int64)
        np.cumsum(cols.counts, out=starts[1:])
        arrival = np.concatenate([
            np.arange(starts[fi], starts[fi + 1], dtype=np.int64)
            for fi in self._epoch_file_order(epoch)]) if len(cols.counts) \
            else np.zeros((0,), np.int64)
        if not self.shuffle:
            return arrival.astype(np.int32)
        n = len(arrival)
        if n >= max(self.shuffle_buffer, k * self.batch_size):
            raise ValueError(
                "device_epoch_indices requires the shuffle pool to cover "
                f"the epoch (n={n} >= pool target); use the staged path")
        perm = np.random.default_rng(
            self.seed * 1_000_003 + epoch).permutation(n)
        return arrival[np.argsort(perm)].astype(np.int32)

    def _make_input_service(self, epoch: int):
        """Spawn the decode-worker fleet for one epoch, or None to fall
        back in-process (service start can fail where spawn or POSIX shm
        is restricted — the pipeline must degrade, not die)."""
        from . import workers  # noqa: PLC0415 (keeps module import light)
        try:
            return workers.ShmInputService(
                self._epoch_files(epoch),
                field_size=self.field_size,
                num_workers=self.input_workers,
                slab_records=self.input_worker_slab_records,
                verify_crc=self.verify_crc,
                on_bad_record=self._bad_policy.on_bad,
                max_bad_records=self._bad_policy.max_bad,
                retry_policy=self._retry_policy,
                health=self.health,
                on_worker_death=self.input_worker_death,
                stall_timeout_s=self.stall_timeout_s,
            ).start()
        except Exception as exc:
            import warnings  # noqa: PLC0415
            warnings.warn(
                f"input service unavailable ({exc!r}); falling back to "
                f"in-process decode", RuntimeWarning, stacklevel=2)
            return None

    def _iter_framed_span_chunks(self, epoch: int, loader
                                 ) -> Iterator[Tuple[bytes, np.ndarray,
                                                     np.ndarray]]:
        """Frame (+CRC-check) chunks WITHOUT decoding: yields
        ``(buf, offsets, lengths)`` with the record-shard filter applied to
        the span index arrays. THE single source of file order, CRC
        semantics, and shard selection for the pooled paths —
        ``_iter_decoded_chunks`` consumes this same stream, so the fused
        (decode-at-drain) and eager-decode emissions cannot drift apart."""
        files = self._epoch_files(epoch)
        n_seen = 0
        got_any = False
        for path in files:
            for buf, offsets, lengths in _iter_framed_chunks(
                    path, loader, self.verify_crc,
                    policy=self._bad_policy,
                    retry_policy=self._retry_policy,
                    stats=self.stage_stats):
                if len(offsets) == 0:
                    continue
                got_any = True
                base = n_seen
                n_seen += len(offsets)
                if self._record_shard is not None:
                    world, rank = self._record_shard
                    keep = (np.arange(base, base + len(offsets))
                            % world) == rank
                    offsets, lengths = offsets[keep], lengths[keep]
                    if len(offsets) == 0:
                        continue
                yield buf, offsets, lengths
        if not got_any and files:
            raise IOError(f"no records found in {len(files)} files")

    def close(self) -> None:
        """Kept for API compatibility: the drain-decode executor is now
        per-iterator (``_DrainPool``), owned and released by each
        ``_iter_pooled_raw`` generator — a second live iterator of the
        same pipeline no longer loses its pool when the first one ends
        an epoch (advisor r5)."""

    def _scatter_decode_raw(self, loader, raw, perm: np.ndarray, off: int,
                            labels: np.ndarray, ids: np.ndarray,
                            vals: np.ndarray, pool: "_DrainPool") -> None:
        """Decode every raw span chunk straight into its permuted pool rows.
        Rows are disjoint across chunks and the C calls release the GIL, so
        chunks decode on the reader pool when more than one core is
        available; big single chunks are split into contiguous sub-spans
        (>= _SCATTER_SPLIT_MIN records each) to fill the pool.

        With ``native_assembly`` and a library that exports the fused entry,
        the single-threaded case crosses ctypes ONCE for the whole drain
        (``loader.assemble_spans`` over every chunk) instead of once per
        chunk — each GIL reacquisition after a released C call can stall up
        to a switch interval behind the prefetch consumer, so on a loaded
        1-core host the per-chunk calls cost real wall time. The threaded
        case keeps per-sub-span calls (that's what parallelizes). Both
        routes and the non-fused scatter emit bit-identical pool bytes."""
        jobs = []
        for buf, offsets, lengths in raw:
            m = len(offsets)
            parts = max(1, min(self.reader_threads, m // _SCATTER_SPLIT_MIN))
            step = (m + parts - 1) // parts
            for s in range(0, m, step):
                e = min(s + step, m)
                jobs.append((buf, offsets[s:e], lengths[s:e],
                             perm[off + s:off + e]))
            off += m

        if (self.native_assembly and hasattr(loader, "has_assemble")
                and loader.has_assemble()):
            if len(jobs) <= 1 or self.reader_threads <= 1:
                loader.assemble_spans(jobs, self.field_size,
                                      labels, ids, vals)
            else:
                list(pool.get().map(
                    lambda job: loader.assemble_spans(
                        [job], self.field_size, labels, ids, vals),
                    jobs))
            return

        lab_flat = labels.reshape(-1)

        def run(job):
            buf, offs, lens, dest = job
            loader.decode_spans_scatter(
                buf, offs, lens, self.field_size, dest, lab_flat, ids, vals)

        if len(jobs) <= 1 or self.reader_threads <= 1:
            for job in jobs:
                run(job)
        else:
            list(pool.get().map(run, jobs))

    def _iter_pooled(self, loader, k: int
                     ) -> Iterator[Tuple[Batch, int, int]]:
        """``_iter_pooled_raw`` with the resume skip applied: the first
        ``skip_batches`` batches are trimmed FROM THIS stream (whole
        emissions dropped; a partially-trained group is sliced — the rows
        stay one contiguous block), so the surviving order is exactly what
        an uninterrupted run would have trained after that prefix."""
        yield from _trim_skip(self._iter_pooled_raw(loader, k),
                              self.skip_batches, self.batch_size)

    def _iter_pooled_raw(self, loader, k: int
                         ) -> Iterator[Tuple[Batch, int, int]]:
        """THE pool/permute/drain machinery (single source for both the
        per-batch and the k-step superbatch feeds): yields ``(rows, m,
        n_examples)`` where ``rows`` is ``m`` stacked batches as contiguous
        ``[m*batch_size, ...]`` arrays (``m <= k``; the tail of each epoch
        emits single batches, the last possibly short). Non-final drains
        emit only full ``k*bs`` groups so k-groups stay contiguous pool
        slices; the per-epoch file shuffle and pool permutation are seeded
        from (seed, epoch + epoch_offset) exactly like the record path."""
        bs = self.batch_size
        sb = bs * max(k, 1)
        # Multi-process path (opt-in): decode runs in worker processes and
        # this generator pools zero-copy shared-memory views. The chunk
        # stream the service yields is exactly the in-process
        # ``_iter_decoded_chunks`` stream (same files, order, chunk
        # boundaries), so pooling it through the eager branch below emits
        # bit-identical batches — the parity the bench asserts. Disabled
        # under record-sharding (workers see per-file streams, not the
        # global record index the 1/world filter needs).
        # Cached columns trump every decode path: no framing, no decode,
        # no worker fleet — chunks are zero-copy views into the slab.
        cached_cols = None
        if self.decoded_cache != "off":
            from . import cache as cache_lib  # noqa: PLC0415
            cached_cols = self.decoded_epoch_columns()
        use_shm = (cached_cols is None and self.input_workers > 0
                   and loader is not None and self._record_shard is None)
        # Fused scatter-decode (r5): with shuffle on and the native decoder
        # available, the proto decode is DEFERRED to drain time and each
        # record decodes straight into its permuted pool row — one pass per
        # record instead of decode-then-scatter (two full passes over the
        # pool; the scatter was ~30% of the staged-path ns/record). The
        # permutation, chunk arrival order, and rng stream are identical to
        # the decode-then-scatter path, so the emission is bit-identical
        # (pinned by TestPooledEmissionGolden) and the resume layout
        # version is unchanged. Disabled under record-sharding: the fused
        # pool holds RAW chunk buffers until drain, and with a 1/world
        # filter those buffers hold ~world x the rows that count toward
        # pool_target — a world-fold RSS regression; the eager path decodes
        # (only) the kept rows and frees each buffer immediately.
        fused = (not use_shm and self.shuffle and loader is not None
                 and self._record_shard is None and self.num_labels == 1
                 and not self.history
                 and hasattr(loader, "decode_spans_scatter"))
        # Drain-decode executor: per-ITERATOR, not per-pipeline — two live
        # iterators of one pipeline must not share (advisor r5: the first
        # one's epoch-end close() killed the second's in-flight drain).
        drain_pool = _DrainPool(self.reader_threads)
        stats = self.stage_stats
        stall_ns = self._synth_stall_ns
        try:
            for e in range(self.num_epochs):
                epoch = e + self.epoch_offset
                rng = np.random.default_rng(self.seed * 1_000_003 + epoch)
                pool_target = (max(self.shuffle_buffer, sb)
                               if self.shuffle else sb)
                pend: "collections.deque" = collections.deque()
                raw: List[Tuple[bytes, np.ndarray, np.ndarray]] = []
                n_pend = 0
                service = self._make_input_service(epoch) if use_shm else None

                def drain(final: bool, service=service
                          ) -> Iterator[Tuple[Batch, int, int]]:
                    nonlocal pend, raw, n_pend
                    if self.shuffle and n_pend > 0 and (pend or raw):
                        # Single-scatter permutation: each row lands at its
                        # shuffled destination in ONE preallocated pool write
                        # (vs concatenate-then-gather = two full copies).
                        # Uniform: row j goes to position perm[j] of a full
                        # permutation. The drain-remainder (pend, already
                        # decoded) scatters first, then raw chunks decode
                        # directly to their rows — matching the arrival order
                        # the permutation indexes.
                        with _timed(stats, "decode_assemble"):
                            perm = rng.permutation(n_pend)
                            # Transfer-layout pool: the label column is
                            # [n, 1] so a batch slice IS the emitted
                            # ``label`` array (the 1-D pool forced a full
                            # reshape+astype copy per emission). Same bytes,
                            # one less pass per batch.
                            labels = np.empty((n_pend, self.num_labels),
                                              np.float32)
                            lab_col = labels.reshape(-1)
                            ids = np.empty((n_pend, self._pool_cols),
                                           np.int32)
                            vals = np.empty((n_pend, self._pool_cols),
                                            np.float32)
                            off = 0
                            for lab, idx, val in pend:
                                dest = perm[off:off + len(lab)]
                                if self.num_labels == 1:
                                    lab_col[dest] = lab.reshape(-1)
                                else:
                                    labels[dest] = lab.reshape(len(lab), -1)
                                ids[dest] = idx
                                vals[dest] = val
                                off += len(lab)
                            if raw:
                                self._scatter_decode_raw(
                                    loader, raw, perm, off, labels, ids,
                                    vals, drain_pool)
                        pend = collections.deque([(labels, ids, vals)])
                        raw = []
                        if service is not None:
                            # Every held slab view has been scattered into
                            # the fresh pool arrays above — hand the slots
                            # back so workers refill them while we slice.
                            service.release_consumed()
                    hl = self.history_max_len if self.history else 0
                    while n_pend >= sb:
                        with _timed(stats, "emit"):
                            rows = self._assemble_batch(pend, sb, hl)
                        if stall_ns:
                            time.sleep(stall_ns * sb * 1e-9)
                        yield rows, k, sb
                        n_pend -= sb
                    if final:
                        while n_pend >= bs:
                            with _timed(stats, "emit"):
                                rows = self._assemble_batch(pend, bs, hl)
                            if stall_ns:
                                time.sleep(stall_ns * bs * 1e-9)
                            yield rows, 1, bs
                            n_pend -= bs
                        if n_pend and not self.drop_remainder:
                            with _timed(stats, "emit"):
                                rows = self._assemble_batch(pend, n_pend, hl)
                            if stall_ns:
                                time.sleep(stall_ns * n_pend * 1e-9)
                            yield rows, 1, n_pend
                            n_pend = 0

                if cached_cols is not None:
                    for chunk in cache_lib.epoch_chunks(
                            cached_cols, self._epoch_file_order(epoch)):
                        pend.append(chunk)
                        n_pend += len(chunk[0])
                        if n_pend >= pool_target:
                            yield from drain(final=False)
                    yield from drain(final=True)
                elif service is not None:
                    with service:
                        # shuffle=False never scatters, so views would stay
                        # referenced by batch slices indefinitely: copy out
                        # of the slabs instead of holding them.
                        for chunk in service.chunks(copy=not self.shuffle):
                            pend.append(chunk)
                            n_pend += len(chunk[0])
                            if n_pend >= pool_target:
                                yield from drain(final=False)
                        yield from drain(final=True)
                elif fused:
                    for span in self._iter_framed_span_chunks(epoch, loader):
                        raw.append(span)
                        n_pend += len(span[1])
                        if n_pend >= pool_target:
                            yield from drain(final=False)
                    yield from drain(final=True)
                else:
                    for chunk in self._iter_decoded_chunks(epoch, loader):
                        pend.append(chunk)
                        n_pend += len(chunk[0])
                        if n_pend >= pool_target:
                            yield from drain(final=False)
                    yield from drain(final=True)
        finally:
            # Release the drain-decode executor when the generator ends OR
            # is abandoned (GeneratorExit lands here). It persists across
            # every pool drain of every epoch of THIS iterator.
            drain_pool.shutdown()

    def iter_superbatches(self, k: int
                          ) -> Iterator[Tuple[Batch, int, int]]:
        """Yield ``(rows, m, n_examples)`` where ``rows`` holds ``m`` stacked
        batches as contiguous ``[m*batch_size, ...]`` arrays (``m <= k``;
        tail emissions may be single short batches with ``m == 1``).

        This is the zero-copy feed for the K-step dispatch loop: after the
        shuffle pool is permuted it is ONE contiguous array, so slicing
        ``k*bs`` rows and reshaping to ``[k, bs, ...]`` at transfer time
        costs nothing — versus ``np.stack`` over k single batches, which
        re-copies every row on the host core that is also doing the decode
        (the e2e bottleneck on small hosts; VERDICT r2 #5).
        """
        loader = _native_loader() if self._use_native else None
        if (loader is None and self.decoded_cache == "off") or k <= 1:
            # Per-record path: group plain batches (stack copy at transfer;
            # skip/prefetch handled by __iter__).
            yield from _group_plain_batches(iter(self), k, self.batch_size)
            return
        # Native pooled path bypasses __iter__'s prefetch; add the
        # decode-ahead stage here (depth in k-groups) so decode overlaps the
        # consumer's transfer+dispatch work. The fallback above iterates
        # ``self`` and is therefore already prefetched.
        src = self._iter_pooled(loader, k)
        if self.prefetch_batches > 0:
            src = _prefetch(src, max(1, self.prefetch_batches // k))
        yield from src

    @staticmethod
    def _assemble_batch(pend: "collections.deque",
                        bs: int, hist_len: int = 0) -> Batch:
        """Pop exactly ``bs`` rows off the front of the pending chunk
        deque (O(1) per chunk; a list's pop(0) re-shifts the whole pool
        every batch). With ``hist_len > 0`` the chunks carry packed
        feat||hist columns (see ``__init__``); the trailing ``hist_len``
        columns split out into the ``hist_ids``/``hist_mask`` batch keys."""
        take: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        need = bs
        while need:
            labels, ids, vals = pend[0]
            if len(labels) <= need:
                take.append(pend.popleft())
                need -= len(labels)
            else:
                take.append((labels[:need], ids[:need], vals[:need]))
                pend[0] = (labels[need:], ids[need:], vals[need:])
                need = 0
        if len(take) == 1:
            labels, ids, vals = take[0]
        else:
            labels = np.concatenate([t[0] for t in take])
            ids = np.concatenate([t[1] for t in take])
            vals = np.concatenate([t[2] for t in take])
        if hist_len:
            fs = ids.shape[1] - hist_len
            return {
                "feat_ids": np.ascontiguousarray(ids[:, :fs], np.int32),
                "feat_vals": np.ascontiguousarray(vals[:, :fs], np.float32),
                "hist_ids": np.ascontiguousarray(ids[:, fs:], np.int32),
                "hist_mask": np.ascontiguousarray(vals[:, fs:], np.float32),
                "label": np.ascontiguousarray(
                    labels.reshape(-1, 1), np.float32),
            }
        # ascontiguousarray, not astype: a contiguous float32 pool slice
        # (the shuffled drain's [n, 1] label column, and all ids/vals)
        # passes through as a zero-copy view — same bytes, no per-emission
        # label copy. Non-contiguous or 1-D chunk labels still normalize
        # to the same [bs, 1] float32 layout.
        if labels.ndim == 2 and labels.shape[1] > 1:
            # Multi-label chunks ([n, 2] columns): split into the batch
            # contract's named [bs, 1] label columns.
            return {
                "feat_ids": np.ascontiguousarray(ids, np.int32),
                "feat_vals": np.ascontiguousarray(vals, np.float32),
                "label": np.ascontiguousarray(labels[:, :1], np.float32),
                "label2": np.ascontiguousarray(labels[:, 1:2], np.float32),
            }
        return {
            "feat_ids": np.ascontiguousarray(ids, np.int32),
            "feat_vals": np.ascontiguousarray(vals, np.float32),
            "label": np.ascontiguousarray(labels.reshape(-1, 1), np.float32),
        }

    # ------------------------------------------------------------------
    def _iter_raw_records(self, epoch: int) -> Iterator[bytes]:
        files = self._epoch_files(epoch)
        n_seen = 0
        for path in files:
            for rec in _iter_file_records(path, self._use_native,
                                          self.verify_crc,
                                          policy=self._bad_policy,
                                          retry_policy=self._retry_policy):
                keep = (
                    self._record_shard is None
                    or n_seen % self._record_shard[0] == self._record_shard[1]
                )
                n_seen += 1
                if keep:
                    yield rec
        if n_seen == 0 and files:
            raise IOError(f"no records found in {len(files)} files")

    def _iter_shuffled(self, epoch: int) -> Iterator[bytes]:
        """Buffered uniform shuffle (tf.data.Dataset.shuffle semantics)."""
        if not self.shuffle or self.shuffle_buffer <= 1:
            yield from self._iter_raw_records(epoch)
            return
        rng = np.random.default_rng(self.seed * 1_000_003 + epoch)
        buf: List[bytes] = []
        for rec in self._iter_raw_records(epoch):
            if len(buf) < self.shuffle_buffer:
                buf.append(rec)
                continue
            j = int(rng.integers(0, len(buf)))
            yield buf[j]
            buf[j] = rec
        rng.shuffle(buf)
        yield from buf

    def _iter_batches_sync(self) -> Iterator[Batch]:
        skip = self.skip_batches
        for e in range(self.num_epochs):
            epoch = e + self.epoch_offset
            pending: List[bytes] = []
            for rec in self._iter_shuffled(epoch):
                pending.append(rec)
                if len(pending) == self.batch_size:
                    if skip:
                        skip -= 1
                    else:
                        yield self._make_batch(pending)
                    pending = []
            if pending and not self.drop_remainder:
                if skip:
                    skip -= 1
                else:
                    yield self._make_batch(pending)

    def _make_batch(self, records: List[bytes]) -> Batch:
        if self.num_labels > 1:
            labels, labels2, ids, vals = self._decode2(
                records, self.field_size)
            return {
                "feat_ids": np.ascontiguousarray(ids, np.int32),
                "feat_vals": np.ascontiguousarray(vals, np.float32),
                "label": labels.reshape(-1, 1).astype(np.float32),
                "label2": labels2.reshape(-1, 1).astype(np.float32),
            }
        if self.history:
            labels, ids, vals, hid, hmask, _ = self._decode_hist(
                records, self.field_size, self.history_max_len)
            return {
                "feat_ids": np.ascontiguousarray(ids, np.int32),
                "feat_vals": np.ascontiguousarray(vals, np.float32),
                "hist_ids": np.ascontiguousarray(hid, np.int32),
                "hist_mask": np.ascontiguousarray(hmask, np.float32),
                "label": labels.reshape(-1, 1).astype(np.float32),
            }
        labels, ids, vals = self._decode(records, self.field_size)
        return {
            "feat_ids": np.ascontiguousarray(ids, np.int32),
            "feat_vals": np.ascontiguousarray(vals, np.float32),
            "label": labels.reshape(-1, 1).astype(np.float32),
        }

    def _batch_source(self) -> Iterator[Batch]:
        """Vectorized native path when available (whole chunks decoded to
        arrays, numpy-level shuffle — the reference's 'vectorized map'
        insight taken to its conclusion); per-record Python path otherwise.
        Shuffle note: the vectorized path permutes within ~64MB decode
        chunks (typically >> the 10k-record buffer of the record path),
        plus the per-epoch file-order shuffle."""
        loader = _native_loader() if self._use_native else None
        if loader is not None or self.decoded_cache != "off":
            # Cached columns need no decoder, so the pooled path also
            # serves toolchain-less hosts once the cache is warm.
            return self._iter_batches_vectorized(loader)
        return self._iter_batches_sync()

    # ------------------------------------------------------------------
    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch_batches <= 0:
            yield from self._batch_source()
            return
        yield from _prefetch(self._batch_source(), self.prefetch_batches)

    def count_examples(self) -> int:
        """One full pass counting records (respecting the shard)."""
        return sum(1 for _ in self._iter_raw_records(epoch=0))


class ChainedFileStream:
    """Sequential read()-only view over a list of files, replayed N times.

    The producer side of the Pipe-mode analog: SageMaker's FIFO replays the
    channel once per epoch (``num_epochs`` lives with the producer, not the
    consumer — the FIFO cannot be re-opened, ``2-hvd-gpu/...py:396``). The
    consumer (``StreamingCtrPipeline``) sees one continuous byte stream.
    """

    def __init__(self, files: Sequence[str], *, num_epochs: int = 1,
                 shuffle_each_epoch: bool = False, seed: int = 42,
                 epoch_offset: int = 0, retry_policy=None,
                 health: Optional[DataHealth] = None):
        if not files:
            raise ValueError("ChainedFileStream needs at least one file")
        self._files: List[str] = []
        for e in range(num_epochs):
            epoch = e + epoch_offset  # continues across resumed invocations
            fs = list(files)
            if shuffle_each_epoch:
                # Seeded per-epoch reshuffle of the replay order: strictly
                # better for convergence than byte-identical epochs (the
                # reference FIFO replays identically; see ADVICE r1).
                np.random.default_rng(seed + epoch).shuffle(fs)
            self._files.extend(fs)
        self._idx = 0
        self._fh: Optional[BinaryIO] = None
        self._retry_policy = retry_policy
        self._health = health

    def _open_next(self, path: str) -> BinaryIO:
        # Per-file resilient opens: a transient mid-file fault heals inside
        # the producer, so the consumer's single-pass stream never breaks.
        on_retry = None
        if self._health is not None:
            health = self._health
            on_retry = lambda exc, n, p=path: health.record_retry(p)  # noqa: E731
        return fileio.open_resilient(path, policy=self._retry_policy,
                                     on_retry=on_retry)

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            raise ValueError("ChainedFileStream only supports bounded reads")
        out = bytearray()
        while len(out) < n:
            if self._fh is None:
                if self._idx >= len(self._files):
                    break
                self._fh = self._open_next(self._files[self._idx])
                self._idx += 1
            chunk = self._fh.read(n - len(out))
            if not chunk:
                self._fh.close()
                self._fh = None
                continue
            out += chunk
        return bytes(out)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class StreamingCtrPipeline:
    """Pipe-mode analog: decode batches from a sequential byte stream.

    Single pass only — the reference's FIFO cannot be re-opened per epoch
    (``2-hvd-gpu/...py:396`` comment); callers wanting multiple epochs pass
    ``num_epochs`` to the *producer* side, exactly like SageMaker Pipe mode
    replays the channel.
    """

    def __init__(
        self,
        stream: BinaryIO,
        *,
        field_size: int,
        batch_size: int,
        drop_remainder: bool = True,
        prefetch_batches: int = 4,
        use_native_decoder: bool = True,
        record_shard: Optional[Tuple[int, int]] = None,
        verify_crc: bool = False,  # speed-over-parity default (see Config); codec fns keep True
        skip_batches: int = 0,
        on_bad_record: str = "raise",
        max_bad_records: int = 0,
        stream_label: str = "<stream>",
        health: Optional[DataHealth] = None,
        num_labels: int = 1,
    ):
        self.stream = stream
        self.field_size = field_size
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.prefetch_batches = prefetch_batches
        self._use_native = use_native_decoder
        self._decode = _get_decoder(use_native_decoder)
        self._decode2 = _get_decoder2(use_native_decoder)
        self.num_labels = max(1, int(num_labels))
        if self.num_labels > 2:
            raise ValueError(
                f"num_labels must be 1 or 2, got {num_labels}")
        self._record_shard = record_shard
        self.verify_crc = verify_crc
        self.skip_batches = skip_batches  # resume: drop the trained prefix
        self._consumed = False
        # Shared-health option: ChainedFileStream heals retries on the
        # producer side; passing its DataHealth here gives one unified
        # stats object across the stream's producer and consumer.
        self.health = health if health is not None else DataHealth()
        self._stream_label = stream_label
        self._bad_policy = BadRecordPolicy(
            on_bad_record, max_bad_records, self.health)

    def _iter_records(self) -> Iterator[bytes]:
        """Stream records, applying the (world, rank) record shard when this
        process shares the stream with others (the dataset.shard analog for
        Pipe mode — without it every rank would train the identical bytes)."""
        it = tfrecord.iter_records_from_stream(
            self.stream, verify_crc=self.verify_crc,
            path=self._stream_label, policy=self._bad_policy)
        if self._record_shard is None:
            yield from it
            return
        world, rank = self._record_shard
        for i, rec in enumerate(it):
            if i % world == rank:
                yield rec

    def _iter_vectorized(self, loader) -> Iterator[Batch]:
        for rows, _, _ in self._iter_vectorized_grouped(loader, 1):
            yield rows

    def _iter_vectorized_grouped(self, loader, k: int
                                 ) -> Iterator[Tuple[Batch, int, int]]:
        """Native streaming fast path: C-speed chunked framing + vectorized
        decode straight off the byte stream — the same machinery as the
        file path (the reference's PipeModeDataset is a C++ reader, X3;
        round 1 framed pipe-mode records one-by-one in Python). Emits
        ``(rows, m, n_ex)`` groups of up to ``k`` stacked batches; since
        there is no shuffle, the batch sequence is stream order regardless
        of k (only the grouping differs)."""
        bs = self.batch_size
        sb = bs * max(k, 1)
        pend: "collections.deque" = collections.deque()
        n_pend = 0
        n_seen = 0
        for buf, offsets, lengths in _iter_framed_stream(
                self.stream, loader, self.verify_crc,
                path=self._stream_label, policy=self._bad_policy):
            if len(offsets) == 0:
                continue
            if self.num_labels > 1:
                lab1, lab2, ids, vals = loader.decode_spans2(
                    buf, offsets, lengths, self.field_size)
                labels = np.stack([lab1, lab2], axis=1)
            else:
                labels, ids, vals = loader.decode_spans(
                    buf, offsets, lengths, self.field_size)
            if self._record_shard is not None:
                world, rank = self._record_shard
                keep = (np.arange(n_seen, n_seen + len(labels))
                        % world) == rank
                labels, ids, vals = labels[keep], ids[keep], vals[keep]
            n_seen += len(offsets)
            if not len(labels):
                continue
            pend.append((labels, ids, vals))
            n_pend += len(labels)
            while n_pend >= sb:
                yield CtrPipeline._assemble_batch(pend, sb), k, sb
                n_pend -= sb
        while n_pend >= bs:
            yield CtrPipeline._assemble_batch(pend, bs), 1, bs
            n_pend -= bs
        if n_pend and not self.drop_remainder:
            yield CtrPipeline._assemble_batch(pend, n_pend), 1, n_pend

    def _batch_from_records(self, records: List[bytes]) -> Batch:
        if self.num_labels > 1:
            labels, labels2, ids, vals = self._decode2(
                records, self.field_size)
            return {
                "feat_ids": np.ascontiguousarray(ids, np.int32),
                "feat_vals": np.ascontiguousarray(vals, np.float32),
                "label": labels.reshape(-1, 1).astype(np.float32),
                "label2": labels2.reshape(-1, 1).astype(np.float32),
            }
        labels, ids, vals = self._decode(records, self.field_size)
        return {
            "feat_ids": np.ascontiguousarray(ids, np.int32),
            "feat_vals": np.ascontiguousarray(vals, np.float32),
            "label": labels.reshape(-1, 1).astype(np.float32),
        }

    def _iter_record_batches(self) -> Iterator[Batch]:
        """Pure-Python fallback: per-record framing + batched decode."""
        pending: List[bytes] = []
        for rec in self._iter_records():
            pending.append(rec)
            if len(pending) == self.batch_size:
                yield self._batch_from_records(pending)
                pending = []
        if pending and not self.drop_remainder:
            yield self._batch_from_records(pending)

    def _iter_sync(self) -> Iterator[Batch]:
        if self._consumed:
            raise RuntimeError(
                "StreamingCtrPipeline is single-pass (Pipe-mode FIFO semantics); "
                "create a new stream for another epoch")
        self._consumed = True
        loader = _native_loader() if self._use_native else None
        src = (self._iter_vectorized(loader) if loader is not None
               else self._iter_record_batches())
        skip = self.skip_batches
        for b in src:
            if skip:
                skip -= 1
                continue
            yield b

    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch_batches <= 0:
            return self._iter_sync()
        return _prefetch(self._iter_sync(), self.prefetch_batches)

    def iter_superbatches(self, k: int) -> Iterator[Tuple[Batch, int, int]]:
        """Zero-stack superbatch feed for the K-step dispatch loop (same
        contract as CtrPipeline.iter_superbatches). Single-pass like every
        other read of this stream; batch sequence is identical to __iter__
        (stream order, no shuffle), so resume skip counts line up across
        both consumption paths."""
        loader = _native_loader() if self._use_native else None
        if loader is None or k <= 1:
            # skip/single-pass/prefetch handled by __iter__.
            yield from _group_plain_batches(iter(self), k, self.batch_size)
            return
        if self._consumed:
            raise RuntimeError(
                "StreamingCtrPipeline is single-pass (Pipe-mode FIFO "
                "semantics); create a new stream for another epoch")
        self._consumed = True
        src = _trim_skip(self._iter_vectorized_grouped(loader, k),
                         self.skip_batches, self.batch_size)
        if self.prefetch_batches > 0:
            src = _prefetch(src, max(1, self.prefetch_batches // k))
        yield from src


def _prefetch(it: Iterator[Batch], depth: int) -> Iterator[Batch]:
    """Run ``it`` in a daemon thread, keeping up to ``depth`` items ready.

    Consumer-abandonment-safe: if the consumer stops iterating early (e.g.
    ragged-shard min-truncation drops a rank's tail mid-epoch), closing this
    generator sets a stop flag; the producer's bounded put polls it, drops
    out, and closes the source iterator — no permanently-blocked thread, no
    leaked file handle."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            for item in it:
                if not _put(item):
                    return
            _put(_END)
        except BaseException as e:  # propagate into consumer
            _put(e)
        finally:
            if stop.is_set():
                close = getattr(it, "close", None)
                if close is not None:
                    close()

    t = threading.Thread(target=worker, daemon=True,
                         name="pipeline-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                # `from None` severs the misleading implicit context (the
                # queue.Full/Empty juggling above); the note names the
                # producer thread so consumer-side tracebacks distinguish
                # pipeline faults from trainer faults.
                note = (f"raised in pipeline prefetch thread {t.name!r} "
                        "(data pipeline fault, not a trainer fault)")
                if hasattr(item, "add_note"):  # py3.11+
                    item.add_note(note)
                else:
                    notes = getattr(item, "__notes__", None)
                    if isinstance(notes, list):
                        notes.append(note)
                    else:
                        item.__notes__ = [note]
                raise item from None
            yield item
    finally:
        stop.set()
