"""Host-side input pipeline: TFRecord -> fixed-shape numpy batches for TPU.

TPU-native re-design of the reference's two ``input_fn`` flavors
(``1-ps-cpu/...py:76-133`` file/pipe, ``2-hvd-gpu/...py:74-133`` horovod):

  * File mode: per-epoch file-list shuffle, shard policy (``sharding.py``),
    record shuffle buffer, batch -> *vectorized* decode (the reference decodes
    with ``tf.parse_example`` after ``.batch()`` — here the batched decode is
    the native C++ decoder or the pure-Python codec), drop_remainder, repeat.
  * Streaming mode (Pipe analog): sequential non-seekable stream, one pass,
    no re-open per epoch (the FIFO pitfall at ``2-hvd-gpu/...py:396``).
  * Prefetch: a background thread keeps ``prefetch_batches`` ready, the host
    analog of ``dataset.prefetch`` — with TPU async dispatch this overlaps
    host decode with device step time.

Outputs fixed-shape batches ``{"feat_ids": int32[B,F], "feat_vals": f32[B,F],
"label": f32[B,1]}`` — static shapes so every step hits the same XLA program.
"""

from __future__ import annotations

import queue
import threading
from typing import BinaryIO, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import example_codec, sharding, tfrecord

Batch = Dict[str, np.ndarray]


def decode_batch_python(records: Sequence[bytes], field_size: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized-decode fallback: parse each Example with the Python codec."""
    n = len(records)
    labels = np.empty((n,), np.float32)
    ids = np.empty((n, field_size), np.int32)
    vals = np.empty((n, field_size), np.float32)
    for i, rec in enumerate(records):
        lab, rid, rval = example_codec.decode_ctr_example(rec, field_size)
        labels[i] = lab
        ids[i] = rid.astype(np.int32)
        vals[i] = rval
    return labels, ids, vals


def _get_decoder(use_native: bool):
    if use_native:
        try:
            from ..native import loader  # noqa: PLC0415 (lazy: builds .so on first use)
            if loader.available():
                return loader.decode_batch
        except Exception:
            pass
    return decode_batch_python


# Chunk size for the native streaming reader: big enough to amortize the
# per-call framing cost, small enough to keep RSS constant on huge shards.
_NATIVE_CHUNK_BYTES = 64 << 20


def _iter_file_records(path: str, use_native: bool) -> Iterator[bytes]:
    """Per-file record iterator with CRC verified on both paths (same
    integrity guarantee regardless of toolchain). Native path: chunked
    read() + C-speed framing with a carried partial-tail — constant memory
    on multi-GB shards, and plain file I/O errors stay catchable Python
    exceptions (an mmap would turn them into SIGBUS)."""
    if use_native:
        try:
            from ..native import loader  # noqa: PLC0415
            if loader.available():
                with open(path, "rb") as f:
                    carry = b""
                    while True:
                        chunk = f.read(_NATIVE_CHUNK_BYTES)
                        if not chunk:
                            if carry:
                                # Strict parse of the leftover: surfaces
                                # truncated-file as an error, not silence.
                                offsets, lengths = loader.split_frames(
                                    carry, verify_crc=True)
                                for off, ln in zip(offsets.tolist(),
                                                   lengths.tolist()):
                                    yield carry[off:off + ln]
                            return
                        buf = carry + chunk if carry else chunk
                        offsets, lengths, consumed = loader.split_frames_partial(
                            buf, verify_crc=True)
                        for off, ln in zip(offsets.tolist(), lengths.tolist()):
                            yield buf[off:off + ln]
                        carry = buf[consumed:]
                return
        except ImportError:
            pass
    yield from tfrecord.iter_records(path, verify_crc=True)


class CtrPipeline:
    """TFRecord CTR input pipeline producing fixed-shape numpy batches."""

    def __init__(
        self,
        files: Sequence[str],
        *,
        field_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = True,
        shuffle_files: bool = True,
        shuffle_buffer: int = 10000,
        drop_remainder: bool = True,
        seed: int = 42,
        shard: Optional[sharding.ShardSpec] = None,
        prefetch_batches: int = 4,
        use_native_decoder: bool = True,
    ):
        if shard is not None:
            self._files: Tuple[str, ...] = shard.files
            self._record_shard = shard.record_shard
        else:
            self._files = tuple(files)
            self._record_shard = None
        self.field_size = field_size
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.shuffle_files = shuffle_files
        self.shuffle_buffer = shuffle_buffer
        self.drop_remainder = drop_remainder
        self.seed = seed
        self.prefetch_batches = prefetch_batches
        self._use_native = use_native_decoder
        self._decode = _get_decoder(use_native_decoder)

    # ------------------------------------------------------------------
    def _iter_raw_records(self, epoch: int) -> Iterator[bytes]:
        files = list(self._files)
        if self.shuffle_files:
            # Per-epoch reshuffle, seeded: deterministic but epoch-varying
            # (reference shuffles the file list once at :373-377).
            np.random.default_rng(self.seed + epoch).shuffle(files)
        n_seen = 0
        for path in files:
            for rec in _iter_file_records(path, self._use_native):
                keep = (
                    self._record_shard is None
                    or n_seen % self._record_shard[0] == self._record_shard[1]
                )
                n_seen += 1
                if keep:
                    yield rec
        if n_seen == 0 and files:
            raise IOError(f"no records found in {len(files)} files")

    def _iter_shuffled(self, epoch: int) -> Iterator[bytes]:
        """Buffered uniform shuffle (tf.data.Dataset.shuffle semantics)."""
        if not self.shuffle or self.shuffle_buffer <= 1:
            yield from self._iter_raw_records(epoch)
            return
        rng = np.random.default_rng(self.seed * 1_000_003 + epoch)
        buf: List[bytes] = []
        for rec in self._iter_raw_records(epoch):
            if len(buf) < self.shuffle_buffer:
                buf.append(rec)
                continue
            j = int(rng.integers(0, len(buf)))
            yield buf[j]
            buf[j] = rec
        rng.shuffle(buf)
        yield from buf

    def _iter_batches_sync(self) -> Iterator[Batch]:
        for epoch in range(self.num_epochs):
            pending: List[bytes] = []
            for rec in self._iter_shuffled(epoch):
                pending.append(rec)
                if len(pending) == self.batch_size:
                    yield self._make_batch(pending)
                    pending = []
            if pending and not self.drop_remainder:
                yield self._make_batch(pending)

    def _make_batch(self, records: List[bytes]) -> Batch:
        labels, ids, vals = self._decode(records, self.field_size)
        return {
            "feat_ids": np.ascontiguousarray(ids, np.int32),
            "feat_vals": np.ascontiguousarray(vals, np.float32),
            "label": labels.reshape(-1, 1).astype(np.float32),
        }

    # ------------------------------------------------------------------
    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch_batches <= 0:
            yield from self._iter_batches_sync()
            return
        yield from _prefetch(self._iter_batches_sync(), self.prefetch_batches)

    def count_examples(self) -> int:
        """One full pass counting records (respecting the shard)."""
        return sum(1 for _ in self._iter_raw_records(epoch=0))


class StreamingCtrPipeline:
    """Pipe-mode analog: decode batches from a sequential byte stream.

    Single pass only — the reference's FIFO cannot be re-opened per epoch
    (``2-hvd-gpu/...py:396`` comment); callers wanting multiple epochs pass
    ``num_epochs`` to the *producer* side, exactly like SageMaker Pipe mode
    replays the channel.
    """

    def __init__(
        self,
        stream: BinaryIO,
        *,
        field_size: int,
        batch_size: int,
        drop_remainder: bool = True,
        prefetch_batches: int = 4,
        use_native_decoder: bool = True,
    ):
        self.stream = stream
        self.field_size = field_size
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.prefetch_batches = prefetch_batches
        self._decode = _get_decoder(use_native_decoder)
        self._consumed = False

    def _iter_sync(self) -> Iterator[Batch]:
        if self._consumed:
            raise RuntimeError(
                "StreamingCtrPipeline is single-pass (Pipe-mode FIFO semantics); "
                "create a new stream for another epoch")
        self._consumed = True
        pending: List[bytes] = []
        for rec in tfrecord.iter_records_from_stream(self.stream):
            pending.append(rec)
            if len(pending) == self.batch_size:
                labels, ids, vals = self._decode(pending, self.field_size)
                yield {
                    "feat_ids": np.ascontiguousarray(ids, np.int32),
                    "feat_vals": np.ascontiguousarray(vals, np.float32),
                    "label": labels.reshape(-1, 1).astype(np.float32),
                }
                pending = []
        if pending and not self.drop_remainder:
            labels, ids, vals = self._decode(pending, self.field_size)
            yield {
                "feat_ids": np.ascontiguousarray(ids, np.int32),
                "feat_vals": np.ascontiguousarray(vals, np.float32),
                "label": labels.reshape(-1, 1).astype(np.float32),
            }

    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch_batches <= 0:
            return self._iter_sync()
        return _prefetch(self._iter_sync(), self.prefetch_batches)


def _prefetch(it: Iterator[Batch], depth: int) -> Iterator[Batch]:
    """Run ``it`` in a daemon thread, keeping up to ``depth`` items ready."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()

    def worker() -> None:
        try:
            for item in it:
                q.put(item)
            q.put(_END)
        except BaseException as e:  # propagate into consumer
            q.put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item
