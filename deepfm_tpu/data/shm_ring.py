"""Shared-memory slab ring: the transport of the multi-process input service.

One ring pairs ONE producer (a decode worker process) with ONE consumer (the
trainer process). A single ``SharedMemory`` segment is partitioned into
``capacity`` fixed-size *slabs*; each slab holds one decoded chunk (or chunk
fragment) as three contiguous arrays::

    labels  float32[S]            offset 0
    ids     int32  [S, F]         offset 4*S
    vals    float32[S, F]         offset 4*S + 4*S*F

(S = ``slab_records``, F = ``field_size``). Decoded rows never cross the
process boundary through a pickle: the worker decodes straight into a slab
(``decode_spans_scatter``) and sends only a slot *index*; the consumer maps
the same segment and reads ``np.frombuffer`` views.

Credit/sequence protocol (strictly SPSC per ring):

  * ``free_q`` holds slot indices the producer may write, preloaded with all
    ``capacity`` slots. The producer blocking on an empty ``free_q`` IS the
    backpressure: a stalled trainer stops the decode fleet with at most
    ``capacity`` slabs in flight. Free slots are a *set*, not a cursor — the
    consumer may hold shuffle-pool slabs long after later slots recycle.
  * ``filled_q`` carries producer->consumer messages in production order.
    The ring does not interpret them beyond slot bookkeeping; the worker
    protocol (workers.py) stamps each with a monotonically increasing
    sequence number, which is what makes a respawned worker able to skip
    exactly the chunks the consumer already received.

The queue *type* is injectable (``ctx``): production uses a spawn
``multiprocessing`` context; unit tests pass a thread context
(``THREAD_CTX``) so wraparound/backpressure tests are deterministic and
sleep-free.
"""

from __future__ import annotations

import dataclasses
import os
import queue as _queue
from multiprocessing import shared_memory
from typing import Any, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class SlabSpec:
    """Geometry of one slab (shared by producer and consumer)."""

    slab_records: int
    field_size: int

    def __post_init__(self) -> None:
        if self.slab_records <= 0:
            raise ValueError("slab_records must be positive")
        if self.field_size <= 0:
            raise ValueError("field_size must be positive")

    @property
    def labels_bytes(self) -> int:
        return 4 * self.slab_records

    @property
    def ids_bytes(self) -> int:
        return 4 * self.slab_records * self.field_size

    @property
    def slab_bytes(self) -> int:
        # labels + ids + vals (ids and vals are the same size).
        return self.labels_bytes + 2 * self.ids_bytes


class _ThreadCtx:
    """Queue factory making the ring run in-process (tests)."""

    @staticmethod
    def Queue() -> "_queue.Queue":
        return _queue.Queue()


THREAD_CTX = _ThreadCtx()


@dataclasses.dataclass
class RingHandle:
    """Picklable attach token: everything a worker needs to join a ring.

    The queues themselves are mp.Queue objects, picklable only through
    ``Process(args=...)`` inheritance — exactly how workers receive them.
    """

    name: str
    slab_records: int
    field_size: int
    capacity: int
    free_q: Any
    filled_q: Any


def _attach_untracked(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment WITHOUT registering it with this
    process's resource_tracker (bpo-38119: before 3.13 every attach
    registers, so the first attaching process to exit unlinks the segment
    under the owner and the tracker spams KeyError warnings). Ownership
    stays with the creating process, which keeps default tracking — a
    hard-crashed trainer still gets its segments reaped."""
    from multiprocessing import resource_tracker  # noqa: PLC0415

    orig = resource_tracker.register

    def register(rt_name: str, rtype: str) -> None:
        if rtype == "shared_memory":
            return
        orig(rt_name, rtype)

    resource_tracker.register = register
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


class ShmRing:
    """One producer/consumer slab ring over a SharedMemory segment."""

    def __init__(self, shm: shared_memory.SharedMemory, spec: SlabSpec,
                 capacity: int, free_q: Any, filled_q: Any, *, owner: bool):
        self._shm = shm
        self.spec = spec
        self.capacity = capacity
        self.free_q = free_q
        self.filled_q = filled_q
        self._owner = owner
        self._closed = False

    # -- construction ---------------------------------------------------
    @classmethod
    def create(cls, spec: SlabSpec, capacity: int, ctx: Any) -> "ShmRing":
        if capacity < 2:
            # One slot in flight + one being filled is the minimum that
            # lets the producer work while the consumer reads.
            raise ValueError("ring capacity must be >= 2")
        shm = shared_memory.SharedMemory(
            create=True, size=capacity * spec.slab_bytes)
        try:
            free_q = ctx.Queue()
            filled_q = ctx.Queue()
            for slot in range(capacity):
                free_q.put(slot)
        except BaseException:
            shm.close()
            shm.unlink()
            raise
        return cls(shm, spec, capacity, free_q, filled_q, owner=True)

    @classmethod
    def attach(cls, handle: RingHandle) -> "ShmRing":
        shm = _attach_untracked(handle.name)
        spec = SlabSpec(handle.slab_records, handle.field_size)
        return cls(shm, spec, handle.capacity, handle.free_q,
                   handle.filled_q, owner=False)

    @property
    def handle(self) -> RingHandle:
        return RingHandle(self._shm.name, self.spec.slab_records,
                          self.spec.field_size, self.capacity,
                          self.free_q, self.filled_q)

    # -- slab access ----------------------------------------------------
    def arrays(self, slot: int, n: int
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(labels[n], ids[n,F], vals[n,F]) views over slab ``slot``.

        Views alias the shared segment directly — valid until the slot is
        released back to the producer (consumer side) or committed
        (producer side). Callers needing longer-lived rows must copy.
        """
        spec = self.spec
        if not 0 <= slot < self.capacity:
            raise IndexError(f"slot {slot} out of range 0..{self.capacity - 1}")
        if not 0 < n <= spec.slab_records:
            raise ValueError(
                f"n={n} rows does not fit slab_records={spec.slab_records}")
        base = slot * spec.slab_bytes
        buf = self._shm.buf
        F = spec.field_size
        labels = np.frombuffer(buf, np.float32, count=n, offset=base)
        ids = np.frombuffer(buf, np.int32, count=n * F,
                            offset=base + spec.labels_bytes).reshape(n, F)
        vals = np.frombuffer(
            buf, np.float32, count=n * F,
            offset=base + spec.labels_bytes + spec.ids_bytes).reshape(n, F)
        return labels, ids, vals

    # -- producer side --------------------------------------------------
    def acquire(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next writable slot; None on timeout (0 = non-blocking probe)."""
        try:
            if timeout == 0:
                return self.free_q.get_nowait()
            return self.free_q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def send(self, msg: Any) -> None:
        """Publish a message (a committed slot or a control event)."""
        self.filled_q.put(msg)

    # -- consumer side --------------------------------------------------
    def pop(self, timeout: Optional[float] = None) -> Any:
        """Next producer message; raises queue.Empty on timeout."""
        if timeout == 0:
            return self.filled_q.get_nowait()
        return self.filled_q.get(timeout=timeout)

    def release(self, slot: int) -> None:
        """Return a consumed slot to the producer (any order)."""
        if not 0 <= slot < self.capacity:
            raise IndexError(f"slot {slot} out of range 0..{self.capacity - 1}")
        self.free_q.put(slot)

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        """Unmap the segment (owner also unlinks). Never raises: live
        ``arrays()`` views hold exported pointers, which makes mmap close
        a BufferError — the views' GC finishes the unmap later, and the
        unlink below already guarantees the segment is reclaimed."""
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.close()
        except BufferError:
            # numpy views still alias the mapping, so mmap.close() refuses
            # ("exported pointers exist"). SharedMemory.close() raised
            # before reaching its os.close, and its __del__ would retry at
            # GC and spam unraisables — so finish the job by hand: close
            # the fd now, drop the wrapper's mmap reference, and let the
            # mapping deallocate silently once the last view dies.
            fd = getattr(self._shm, "_fd", -1)
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
                self._shm._fd = -1
            self._shm._mmap = None
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
