"""TFRecord container I/O: framing + masked CRC32C, reader/writer.

On-disk format identical to TensorFlow's TFRecord so data produced for the
reference pipeline (``1-ps-cpu/...py:108 TFRecordDataset``) is readable here
and vice versa:

    uint64  length (little-endian)
    uint32  masked_crc32c(length bytes)
    bytes   data[length]
    uint32  masked_crc32c(data)

Pure-Python CRC32C (Castagnoli, reflected poly 0x82F63B78) with a table;
the C++ fast path (``deepfm_tpu/native``) does hardware-speed decode.
"""

from __future__ import annotations

import os
import struct
from typing import BinaryIO, Iterator, List, Optional, Union

import numpy as np

_CRC_TABLE = None
_CRC_TABLES8 = None


def _crc32c_table() -> np.ndarray:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = np.empty(256, dtype=np.uint32)
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table[i] = crc
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c_tables8():
    """Slice-by-8 tables (plain int lists — faster than np scalars here)."""
    global _CRC_TABLES8
    if _CRC_TABLES8 is None:
        t0 = [int(x) for x in _crc32c_table()]
        tables = [t0]
        for _ in range(7):
            prev = tables[-1]
            tables.append([t0[v & 0xFF] ^ (v >> 8) for v in prev])
        _CRC_TABLES8 = tables
    return _CRC_TABLES8


def crc32c(data: bytes) -> int:
    """Pure-Python CRC32C, slice-by-8: one Python iteration per 8 bytes.

    Still ~20x slower than the native library, but fast enough that the
    no-toolchain fallback can keep CRC verification on (the pipeline
    guarantees the same integrity check on both decode paths)."""
    t = _crc32c_tables8()
    t0, t1, t2, t3, t4, t5, t6, t7 = t
    c = 0xFFFFFFFF
    n8 = len(data) >> 3
    if n8:
        for (w,) in struct.iter_unpack("<Q", memoryview(data)[:n8 * 8]):
            w ^= c
            c = (t7[w & 0xFF] ^ t6[(w >> 8) & 0xFF]
                 ^ t5[(w >> 16) & 0xFF] ^ t4[(w >> 24) & 0xFF]
                 ^ t3[(w >> 32) & 0xFF] ^ t2[(w >> 40) & 0xFF]
                 ^ t1[(w >> 48) & 0xFF] ^ t0[(w >> 56) & 0xFF])
    for b in memoryview(data)[n8 * 8:]:
        c = (c >> 8) ^ t0[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


class TFRecordWriter:
    """Append serialized records to a TFRecord file (writer side of X4).
    Local paths or object-store URLs (``gs://``) via the fileio seam."""

    def __init__(self, path: str):
        from . import fileio  # noqa: PLC0415 (avoid import cycle at load)
        self._path = path
        if fileio.is_remote(path):
            self._f: Optional[BinaryIO] = fileio.open_stream(path, "wb")
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(path, "wb")

    def write(self, record: bytes) -> None:
        assert self._f is not None, "writer closed"
        length = struct.pack("<Q", len(record))
        self._f.write(length)
        self._f.write(struct.pack("<I", masked_crc32c(length)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc32c(record)))

    def flush(self) -> None:
        if self._f:
            self._f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def __enter__(self) -> "TFRecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _frame_fault(policy, path: str, offset: int, reason: str, *,
                 nbytes: int = 0, truncated: bool = False) -> None:
    """Route one bad frame through the policy, or raise with path+offset."""
    if policy is not None:
        policy.bad_record(path, offset, reason, nbytes=nbytes,
                         truncated=truncated)
        return
    label = path or "<stream>"
    raise IOError(f"corrupt TFRecord: {reason} in {label} at byte {offset}")


def iter_records_from_stream(stream: BinaryIO, *, verify_crc: bool = True,
                             path: str = "", policy=None) -> Iterator[bytes]:
    """Sequential record iterator over any non-seekable byte stream.

    This is the streaming/Pipe-mode primitive: it never seeks, so it works on
    FIFOs and sockets exactly like the reference's PipeModeDataset C++ reader
    (X3). Truncated tail is treated as EOF only if the stream ends exactly at
    a record boundary header. ``path`` labels error messages with the source
    plus the absolute byte offset of the bad frame; ``policy`` (a
    ``health.BadRecordPolicy``) turns raises into counted skips — a data-CRC
    mismatch skips just that record, while a length-CRC mismatch or a
    truncated frame discards the rest of the stream (framing cannot resync).
    """
    pos = 0
    while True:
        header = stream.read(12)
        if not header:
            return
        if len(header) < 12:
            _frame_fault(policy, path, pos, "truncated TFRecord header",
                         nbytes=len(header), truncated=True)
            return
        (length,) = struct.unpack("<Q", header[:8])
        (len_crc,) = struct.unpack("<I", header[8:12])
        if verify_crc and masked_crc32c(header[:8]) != len_crc:
            _frame_fault(policy, path, pos,
                         "length CRC mismatch (cannot resync; "
                         "discarding rest of file)", truncated=True)
            return
        payload = stream.read(length + 4)
        if len(payload) < length + 4:
            _frame_fault(policy, path, pos, "truncated TFRecord payload",
                         nbytes=12 + len(payload), truncated=True)
            return
        data, (data_crc,) = payload[:length], struct.unpack("<I", payload[length:])
        if verify_crc and masked_crc32c(data) != data_crc:
            _frame_fault(policy, path, pos, "data CRC mismatch",
                         nbytes=12 + length + 4)
            pos += 12 + length + 4
            continue
        pos += 12 + length + 4
        yield data


def iter_records(path: str, *, verify_crc: bool = True,
                 policy=None, resilient: bool = False,
                 retry_policy=None, on_retry=None) -> Iterator[bytes]:
    """Iterate records of a TFRecord file (local or gs://).

    ``resilient=True`` reads through :class:`fileio.ResilientStream` so
    transient mid-file errors heal by reopen-and-seek.
    """
    from . import fileio  # noqa: PLC0415 (avoid import cycle at module load)
    if resilient:
        with fileio.open_resilient(path, policy=retry_policy,
                                   on_retry=on_retry) as f:
            yield from iter_records_from_stream(
                f, verify_crc=verify_crc, path=path, policy=policy)
        return
    if fileio.is_remote(path):
        with fileio.open_stream(path, "rb") as f:
            yield from iter_records_from_stream(
                f, verify_crc=verify_crc, path=path, policy=policy)
        return
    with open(path, "rb", buffering=1 << 20) as f:
        yield from iter_records_from_stream(
            f, verify_crc=verify_crc, path=path, policy=policy)


def read_all_records(path: str, *, verify_crc: bool = True) -> List[bytes]:
    return list(iter_records(path, verify_crc=verify_crc))


def split_record_frames(buf: bytes, *, verify_crc: bool = False,
                        path: str = "") -> List[bytes]:
    """Split a whole-file byte buffer into record payloads (no copies of buf)."""
    label = path or "<buffer>"
    out: List[bytes] = []
    pos, end = 0, len(buf)
    while pos < end:
        if end - pos < 12:
            raise IOError(f"truncated TFRecord header in {label} "
                          f"at byte {pos}")
        (length,) = struct.unpack_from("<Q", buf, pos)
        if verify_crc:
            (len_crc,) = struct.unpack_from("<I", buf, pos + 8)
            if masked_crc32c(buf[pos:pos + 8]) != len_crc:
                raise IOError(f"corrupt TFRecord: length CRC mismatch in "
                              f"{label} at byte {pos}")
        pos += 12
        if end - pos < length + 4:
            raise IOError(f"truncated TFRecord payload in {label} "
                          f"at byte {pos - 12}")
        data = buf[pos:pos + length]
        if verify_crc:
            (data_crc,) = struct.unpack_from("<I", buf, pos + length)
            if masked_crc32c(data) != data_crc:
                raise IOError(f"corrupt TFRecord: data CRC mismatch in "
                              f"{label} at byte {pos - 12}")
        out.append(data)
        pos += length + 4
    return out


def scan_frames_partial(buf, *, verify_crc: bool = True, final: bool = False,
                        base_offset: int = 0, path: str = "", policy=None):
    """Pure-Python analog of ``native.loader.split_frames_partial`` with
    bad-record policy support.

    Frames as many complete records out of ``buf`` as possible and returns
    ``(offsets, lengths, consumed, abort)`` where ``offsets``/``lengths``
    are int64 arrays of payload spans within ``buf``, ``consumed`` is how
    many bytes of ``buf`` were fully processed (skipped bad records count as
    consumed), and ``abort`` means framing cannot continue past ``consumed``
    (length-CRC corruption or, when ``final``, a truncated tail) — the
    caller must stop reading this stream. ``base_offset`` is the absolute
    stream offset of ``buf[0]`` so error messages and health entries carry
    true file offsets. The pipeline only calls this when the native framer
    rejects a chunk, so the Python re-scan both locates the exact bad byte
    and applies the same skip/raise policy as the pure-Python decode path.
    """
    offsets: List[int] = []
    lengths: List[int] = []
    pos, end = 0, len(buf)
    abort = False
    while True:
        avail = end - pos
        if avail < 12:
            if final and avail > 0:
                _frame_fault(policy, path, base_offset + pos,
                             "truncated TFRecord header", nbytes=avail,
                             truncated=True)
                pos, abort = end, True
            break
        (length,) = struct.unpack_from("<Q", buf, pos)
        if verify_crc:
            (len_crc,) = struct.unpack_from("<I", buf, pos + 8)
            if masked_crc32c(bytes(buf[pos:pos + 8])) != len_crc:
                _frame_fault(policy, path, base_offset + pos,
                             "length CRC mismatch (cannot resync; "
                             "discarding rest of file)", truncated=True)
                pos, abort = end, True
                break
        total = 12 + length + 4
        if avail < total:
            if final:
                _frame_fault(policy, path, base_offset + pos,
                             "truncated TFRecord payload", nbytes=avail,
                             truncated=True)
                pos, abort = end, True
            break
        if verify_crc:
            data = bytes(buf[pos + 12:pos + 12 + length])
            (data_crc,) = struct.unpack_from("<I", buf, pos + 12 + length)
            if masked_crc32c(data) != data_crc:
                _frame_fault(policy, path, base_offset + pos,
                             "data CRC mismatch", nbytes=total)
                pos += total
                continue
        offsets.append(pos + 12)
        lengths.append(length)
        pos += total
    return (np.asarray(offsets, dtype=np.int64),
            np.asarray(lengths, dtype=np.int64), pos, abort)
