"""Input-shard policy: the reference's 4-way decision matrix, TPU-native.

Reference: Horovod flavor ``2-hvd-gpu/DeepFM-hvd-tfrecord-vectorized-map.py:92-120``
keyed on (``enable_data_multi_path`` x ``enable_s3_shard``), documented as a
decision table in ``README-EN.md:86-91``; PS flavor host-level shard at
``1-ps-cpu/...py:114-117``. Here ``rank``/``world_size`` come from
``jax.process_index()``/``jax.process_count()`` instead of ``hvd.rank()``/
``hvd.size()``, collapsing both reference code paths into one.

Policy matrix (matching README-EN.md:86-91):

  multi_path  s3_shard   behavior
  ----------  --------   -----------------------------------------------------
  True        True       each worker reads its private channel dir AND storage
                         already sharded per host; no shard
  True        False      private channel dir per worker, but the same channel
                         name maps to the same storage on every host: shard
                         across hosts (num_hosts, host_index) — reference
                         2-hvd-gpu/...py:98-102
  False       True       storage already sharded files per host; shard the
                         host's files among its local workers by local_rank
  False       False      every worker sees all files; shard files by global
                         rank, falling back to record-level sharding when
                         there are fewer files than workers
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Result of the policy: which files to read and an optional record-level
    (modulus, index) shard to apply while reading."""

    files: Tuple[str, ...]
    record_shard: Optional[Tuple[int, int]] = None  # (num_shards, index)

    def shard_records(self, n_seen: int) -> bool:
        """True if record index ``n_seen`` belongs to this shard."""
        if self.record_shard is None:
            return True
        num, idx = self.record_shard
        return n_seen % num == idx


def shard_files(
    files: Sequence[str],
    *,
    enable_data_multi_path: bool = False,
    enable_s3_shard: bool = False,
    rank: int = 0,
    local_rank: int = 0,
    world_size: int = 1,
    workers_per_host: int = 1,
) -> ShardSpec:
    files = tuple(sorted(files))
    if world_size <= 1 and workers_per_host <= 1:
        return ShardSpec(files)
    if enable_data_multi_path:
        # Each worker gets its own channel (2-hvd-gpu/...py:376-380,403):
        # the caller passed this worker's private file list. With S3-sharded
        # storage that is already disjoint per host — no further shard. With
        # replicated storage, worker i on EVERY host reads channel i, so the
        # channel must still be split across hosts (reference :98-102).
        if enable_s3_shard:
            return ShardSpec(files)
        num_hosts = max(world_size // max(workers_per_host, 1), 1)
        if num_hosts <= 1:
            return ShardSpec(files)
        host_index = rank // max(workers_per_host, 1)
        if len(files) >= num_hosts:
            return ShardSpec(files[host_index::num_hosts])
        return ShardSpec(files, record_shard=(num_hosts, host_index))
    if enable_s3_shard:
        # Files were distributed per host by storage (ShardedByS3Key analog,
        # deepfm-sagemaker-ps-cpu.ipynb:135). Split the host's files among its
        # local workers (2-hvd-gpu/...py:101-106).
        if workers_per_host <= 1:
            return ShardSpec(files)
        if len(files) >= workers_per_host:
            return ShardSpec(files[local_rank::workers_per_host])
        return ShardSpec(files, record_shard=(workers_per_host, local_rank))
    # Unsharded storage: all workers see all files (2-hvd-gpu/...py:108-120).
    if len(files) >= world_size:
        return ShardSpec(files[rank::world_size])
    return ShardSpec(files, record_shard=(world_size, rank))


def validate_shard_coverage(specs: Sequence[ShardSpec], all_files: Sequence[str]) -> None:
    """Assert the per-worker specs jointly cover every file exactly once
    (file-level shards) — the property the README decision table guarantees."""
    seen: List[str] = []
    for s in specs:
        if s.record_shard is not None:
            return  # record-level sharding covers by construction
        seen.extend(s.files)
    if sorted(seen) != sorted(all_files):
        raise AssertionError(f"shard coverage mismatch: {sorted(seen)} vs {sorted(all_files)}")
