"""Hot/cold tiered embedding storage: HBM-resident hot-row cache over a
host-RAM cold store, with prefetch keyed off the staged batch lookahead.

The embedding tables (and their lazy-Adam m/v/tau slots) live on the host;
only a ``--embedding_hot_rows``-row hot cache is device-resident. The fit
loop's staging thread already sees batch groups ``transfer_ahead`` dispatches
early, so the runtime plans each group there: look up which ids are already
hot, pick LRU victims for the misses, FETCH the missing rows from the cold
store (this is the overlap — the host memcpy/dequant for dispatch t+1 runs
while the device computes dispatch t), and remap the group's ``feat_ids``
from global ids to hot SLOT ids. The main loop then applies the queued plan
(evicted-row write-back + fetched-row install) right before its dispatch.

Correctness hinges on three orderings, all enforced here:

* Plans are FIFO: ``apply_next`` consumes them in the exact order
  ``plan_group`` queued them, which is the dispatch order.
* A row evicted by a still-pending plan cannot be re-fetched from the cold
  store early (its write-back hasn't happened) — those rows are marked
  late-fetch and read at apply time, after the pending write-back.
* Slots referenced by any not-yet-applied plan are pinned (refcounted) and
  never chosen as victims; if a group's working set cannot fit in the
  unpinned slots the runtime raises instead of silently corrupting.

The device step programs are unchanged: staged ``feat_ids`` are slot ids,
the sparse-update plan's OOB fill (``padded_vocab`` > hot_rows) still drops
in the hot-table scatter, and JAX's immutable arrays make installs for
dispatch t+1 invisible to the already-enqueued dispatch t.

Optional quantized cold storage quarters the host bytes of the weight
tables with a scale-per-row dequant on fetch / requant on write-back:
``--embedding_cold_dtype int8`` (fixed-step symmetric) or ``fp8_e4m3``
(float8, scale = row-max/448 — relative precision within the row, so rows
mixing tiny and large coordinates quantize better). The m/v moment slots
stay float32 (quantizing the second moment distorts the Adam denominator
far more than the weights).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..obs import trace as trace_lib
from ..ops import pallas_embedding as pemb
from ..utils import faults
from ..utils import logging as ulog


def _pow2_pad(n: int) -> int:
    """Smallest power of two >= n (>= 1): bounds the number of compiled
    install/evict program shapes to O(log max_group)."""
    p = 1
    while p < n:
        p *= 2
    return p


try:  # fp8 cold tier needs ml_dtypes (ships with jax; gated anyway)
    import ml_dtypes as _mld
    _FP8_DTYPE = np.dtype(_mld.float8_e4m3fn)
    _FP8_MAX = float(_mld.finfo(_mld.float8_e4m3fn).max)  # 448.0
except ImportError:  # pragma: no cover - baked into the image
    _mld = None
    _FP8_DTYPE = None
    _FP8_MAX = 0.0

# __init__ quantizes the adopted table through write() in chunks of this
# many rows, so the write scratch stays bounded instead of growing to a
# full-vocab float32 temp (which would cancel the quantized tiers' memory
# saving at adopt time).
_INIT_WRITE_CHUNK = 8192


class ColdStore:
    """Host-RAM row store for ONE table: float32, or a quantized tier with
    a per-row float32 scale — ``int8`` (row-max/127 symmetric, rint) or
    ``fp8_e4m3`` (row-max/448, cast-rounded; fp8 keeps ~3 mantissa bits
    everywhere in the row instead of int8's fixed step, so small
    coordinates in a row with one large outlier survive quantization).

    fetch()/write() run on every cache transaction, so both work out of
    per-store scratch buffers: ``fetch`` returns a VIEW into the scratch,
    valid until the next fetch/write on this store — callers copy out
    (every runtime call site assigns into its own array immediately)."""

    def __init__(self, array: np.ndarray, dtype: str):
        a = np.asarray(array, np.float32)
        self.shape = a.shape
        self.dtype = dtype
        self._trail = tuple(range(1, a.ndim))
        self._fetch_f32: Optional[np.ndarray] = None  # fetch dequant out
        self._fetch_q: Optional[np.ndarray] = None    # fetch raw-row stage
        self._write_f32: Optional[np.ndarray] = None  # write quant stage
        if dtype in ("int8", "fp8_e4m3"):
            if dtype == "fp8_e4m3":
                if _mld is None:
                    raise RuntimeError(
                        "embedding_cold_dtype=fp8_e4m3 needs ml_dtypes")
                self._qdt, self._qmax = _FP8_DTYPE, _FP8_MAX
            else:
                self._qdt, self._qmax = np.dtype(np.int8), 127.0
            self._scale = np.empty(a.shape[:1], np.float32)
            self._q = np.empty(a.shape, self._qdt)
            for lo in range(0, a.shape[0], _INIT_WRITE_CHUNK):
                hi = min(lo + _INIT_WRITE_CHUNK, a.shape[0])
                self.write(np.arange(lo, hi), a[lo:hi])
        elif dtype == "float32":
            self._data = a.copy()
        else:
            raise ValueError(f"unknown cold dtype {dtype!r}")

    def nbytes(self) -> int:
        if self.dtype != "float32":
            return self._q.nbytes + self._scale.nbytes
        return self._data.nbytes

    def _scratch(self, which: str, n: int) -> np.ndarray:
        """First-n-rows view of the named scratch buffer, growing it to the
        next power of two when the request outsizes it (so steady-state
        transactions of any mix of sizes stop allocating)."""
        buf = getattr(self, which)
        if buf is None or buf.shape[0] < n:
            cap = _pow2_pad(n)
            dt = self._qdt if which == "_fetch_q" else np.float32
            buf = np.empty((cap,) + self.shape[1:], dt)
            setattr(self, which, buf)
        return buf[:n]

    def fetch(self, ids: np.ndarray) -> np.ndarray:
        """float32 rows at ``ids`` (dequantized for the quantized tiers),
        as a reused-scratch VIEW (see class docstring). The fault seam
        fires here — callers retry via :meth:`TieredEmbeddingRuntime`."""
        faults.check_cold_fetch()
        ids = np.asarray(ids, np.int64)
        out = self._scratch("_fetch_f32", ids.size)
        if self.dtype != "float32":
            q = self._scratch("_fetch_q", ids.size)
            np.take(self._q, ids, axis=0, out=q)
            np.copyto(out, q, casting="unsafe")
            out *= self._scale[ids].reshape((-1,) + (1,) * len(self._trail))
        else:
            np.take(self._data, ids, axis=0, out=out)
        return out

    def write(self, ids: np.ndarray, rows: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows, np.float32)
        if self.dtype == "float32":
            self._data[ids] = rows
            return
        w = self._scratch("_write_f32", ids.size)
        np.abs(rows, out=w)
        amax = w.max(axis=self._trail) if self._trail else w.copy()
        scale = np.maximum(amax, 1e-12, out=amax)
        scale /= self._qmax
        self._scale[ids] = scale
        np.divide(rows, scale.reshape((-1,) + (1,) * len(self._trail)),
                  out=w)
        if self.dtype == "int8":
            np.rint(w, out=w)  # fp8 rounds in the cast; int8 truncates
        np.clip(w, -self._qmax, self._qmax, out=w)
        self._q[ids] = w  # casts on assignment, no full-size temp

    def dense(self) -> np.ndarray:
        """The whole table as float32 (eval/export densification)."""
        if self.dtype != "float32":
            return self._q.astype(np.float32) * self._scale.reshape(
                (-1,) + (1,) * len(self._trail))
        return self._data.copy()


class _InstallPlan:
    """One dispatch group's queued cache transaction (built on the staging
    thread, applied on the main thread in FIFO order)."""

    __slots__ = ("evict_slots", "evict_ids", "install_slots", "install_ids",
                 "late_idx", "values", "group_slots")

    def __init__(self):
        self.evict_slots: np.ndarray = np.zeros((0,), np.int32)
        self.evict_ids: np.ndarray = np.zeros((0,), np.int32)
        self.install_slots: np.ndarray = np.zeros((0,), np.int32)
        self.install_ids: np.ndarray = np.zeros((0,), np.int32)
        self.late_idx: np.ndarray = np.zeros((0,), np.int64)
        # name -> {"w","m","v","tau"} arrays [I, ...] (late rows filled at
        # apply time, after the pending eviction's write-back).
        self.values: Dict[str, Dict[str, np.ndarray]] = {}
        self.group_slots: np.ndarray = np.zeros((0,), np.int32)


class TieredEmbeddingRuntime:
    """Owns the id->slot directory, the per-param cold stores, and the
    plan/apply protocol described in the module docstring."""

    def __init__(self, cfg: Config, model: Any):
        if cfg.embedding_bucket_sizes:
            raise ValueError("hot/cold tiering supports the monolithic "
                             "table layout only")
        self.cfg = cfg
        self.model = model
        self.names: Tuple[str, ...] = tuple(model.embedding_param_names())
        self.hot_rows = int(cfg.embedding_hot_rows)
        self.feature_size = int(cfg.feature_size)
        # Directory (staging thread owns mutations after adopt()).
        self.id_to_slot = np.full((self.feature_size,), -1, np.int32)
        self.slot_to_id = np.full((self.hot_rows,), -1, np.int32)
        self.last_used = np.zeros((self.hot_rows,), np.int64)
        self.pin_count = np.zeros((self.hot_rows,), np.int32)
        self.clock = 0
        self._free: List[int] = list(range(self.hot_rows - 1, -1, -1))
        self._pending: "collections.deque[_InstallPlan]" = collections.deque()
        self._pending_evicted: Dict[int, int] = {}  # id -> pending count
        self._lock = threading.Lock()
        # Signaled by apply_next when it releases a plan's slot pins; the
        # staging thread waits on it when the lookahead has pinned too much
        # of the cache for the next group to fit.
        self._cond = threading.Condition(self._lock)
        self.cold: Dict[str, ColdStore] = {}
        self.cold_m: Dict[str, np.ndarray] = {}
        self.cold_v: Dict[str, np.ndarray] = {}
        self.cold_tau: Dict[str, np.ndarray] = {}
        self.stats: Dict[str, float] = {
            "lookups": 0, "hits": 0, "misses": 0, "evictions": 0,
            "installs": 0, "plans": 0, "fetch_retries": 0,
            "prefetch_fetch_s": 0.0,   # cold fetches on the staging thread
            "apply_fetch_s": 0.0,      # late fetches on the main thread
            "apply_s": 0.0,            # total main-thread apply time
        }
        self._adopted = False

    # -- state adoption -------------------------------------------------
    def adopt(self, state):
        """Move the full tables (and their lazy-Adam slots) to the cold
        store and shrink the device-resident state to ``hot_rows`` rows.
        Called once by ``Trainer.init_state``."""
        if self._adopted:
            raise RuntimeError("TieredEmbeddingRuntime.adopt called twice")
        params = dict(state.params)
        opt = dict(state.opt_state)
        embed = dict(opt["embed"])
        for name in self.names:
            full = np.asarray(jax.device_get(params[name]), np.float32)
            real = full[: self.feature_size]  # pad rows are zero; drop them
            self.cold[name] = ColdStore(real, self.cfg.embedding_cold_dtype)
            # Seed the cold moment slots from the state being adopted: zeros
            # for a fresh init (unchanged behavior), the restored Adam
            # moments when the state came from a densified checkpoint — the
            # dense->tiered restore direction is then bit-exact.
            entry = embed[name]["table"]
            self.cold_m[name] = np.asarray(
                jax.device_get(entry.m), np.float32)[: self.feature_size].copy()
            self.cold_v[name] = np.asarray(
                jax.device_get(entry.v), np.float32)[: self.feature_size].copy()
            self.cold_tau[name] = np.asarray(
                jax.device_get(entry.tau), np.int32)[: self.feature_size].copy()
            hot_shape = (self.hot_rows,) + real.shape[1:]
            params[name] = jnp.zeros(hot_shape, jnp.float32)
            from ..train import optimizers as opt_lib  # noqa: PLC0415
            embed[name] = {"table": opt_lib.EmbedAdamEntry(
                m=jnp.zeros(hot_shape, jnp.float32),
                v=jnp.zeros(hot_shape, jnp.float32),
                tau=jnp.zeros((self.hot_rows,), jnp.int32))}
            ulog.info(
                f"hot/cold: {name} cold={self.cold[name].nbytes() / 2**20:.1f}"
                f" MiB host ({self.cfg.embedding_cold_dtype}), hot="
                f"{self.hot_rows} rows device-resident")
        opt["embed"] = embed
        self._adopted = True
        return state.replace(params=params, opt_state=opt)

    # -- staging-thread side --------------------------------------------
    def _fetch(self, store: ColdStore, ids: np.ndarray) -> np.ndarray:
        """Cold fetch with bounded retry healing of injected/transient
        faults (the cold store is host RAM here, but the seam models a
        remote parameter tier where fetches can transiently fail)."""
        attempts = 3
        for i in range(attempts):
            try:
                return store.fetch(ids)
            except faults.InjectedFault as exc:
                if i == attempts - 1:
                    raise
                self.stats["fetch_retries"] += 1
                ulog.warning(f"cold fetch failed ({exc}); retrying")

    def plan_group(self, group: List[Dict[str, np.ndarray]]
                   ) -> List[Dict[str, np.ndarray]]:
        """Plan one dispatch group's cache transaction and remap its
        ``feat_ids`` to hot slot ids. Runs on the staging thread; the cold
        fetches issued here are the prefetch that overlaps device compute."""
        with trace_lib.span("hotcold.plan"), self._lock:
            return self._plan_group_locked(group)

    def _plan_group_locked(self, group):
        self.clock += 1
        self.stats["plans"] += 1
        flat = np.concatenate([b["feat_ids"].ravel() for b in group])
        uids = np.unique(flat.astype(np.int64))
        if uids.size and (uids[0] < 0 or uids[-1] >= self.feature_size):
            raise ValueError("feat_ids outside [0, feature_size) under "
                             "hot/cold tiering")
        self.stats["lookups"] += int(uids.size)
        plan = _InstallPlan()
        slots = self.id_to_slot[uids]
        resident = slots >= 0
        self.stats["hits"] += int(resident.sum())
        missing = uids[~resident]
        self.stats["misses"] += int(missing.size)
        # Pin + refresh everything this group touches BEFORE victim
        # selection so the group can never evict its own working set.
        self.last_used[slots[resident]] = self.clock
        if missing.size:
            evict_slots: List[int] = []
            evict_ids: List[int] = []
            new_slots = np.empty((missing.size,), np.int32)

            def evictable():
                # Unpinned resident slots, excluding the rows this very
                # group just refreshed. Only this (staging) thread mutates
                # residency/last_used; apply_next only releases pins.
                cand = np.flatnonzero(
                    (self.pin_count == 0) & (self.slot_to_id >= 0))
                return cand[self.last_used[cand] < self.clock]

            # The prefetch lookahead pins every pending group's working
            # set; if the next group doesn't fit in what's left, wait for
            # the main thread to apply a plan and release its pins (the
            # staging thread simply stops running ahead). Only when no
            # pins are outstanding is the cache GENUINELY too small.
            while len(self._free) + evictable().size < missing.size:
                # Pins outstanding (even if the plan was already popped and
                # is mid-apply) mean the main thread will free slots; only
                # a pin-free shortfall is a genuine capacity error.
                if not self._pending and int(self.pin_count.sum()) == 0:
                    raise RuntimeError(
                        f"hot cache too small: group needs {missing.size} "
                        f"installs but only {len(self._free)} free + "
                        f"{evictable().size} evictable slots "
                        f"(embedding_hot_rows={self.hot_rows}; raise it "
                        f"above one dispatch group's unique-id working set)")
                if not self._cond.wait(timeout=120.0):
                    raise RuntimeError(
                        "hot/cold tiering stalled waiting for slot pins to "
                        "release (main loop not applying plans?)")
            n_free = min(len(self._free), missing.size)
            for i in range(n_free):
                new_slots[i] = self._free.pop()
            need = missing.size - n_free
            if need > 0:
                cand = evictable()
                victims = cand[np.argsort(
                    self.last_used[cand], kind="stable")][:need]
                for j, s in enumerate(victims):
                    vid = int(self.slot_to_id[s])
                    evict_slots.append(int(s))
                    evict_ids.append(vid)
                    self.id_to_slot[vid] = -1
                    self._pending_evicted[vid] = \
                        self._pending_evicted.get(vid, 0) + 1
                    new_slots[n_free + j] = s
            self.stats["evictions"] += len(evict_ids)
            self.stats["installs"] += int(missing.size)
            self.id_to_slot[missing] = new_slots
            self.slot_to_id[new_slots] = missing
            self.last_used[new_slots] = self.clock
            plan.evict_slots = np.asarray(evict_slots, np.int32)
            plan.evict_ids = np.asarray(evict_ids, np.int32)
            plan.install_slots = new_slots
            plan.install_ids = missing.astype(np.int32)
            # Rows whose write-back is still pending must be fetched at
            # apply time (their cold copy is stale until then). Evicted and
            # installed ids are disjoint within one plan (resident vs not),
            # so any pending entry here is from an OLDER plan.
            late = np.asarray(
                [i for i, mid in enumerate(missing)
                 if self._pending_evicted.get(int(mid), 0) > 0], np.int64)
            plan.late_idx = late
            early = np.setdiff1d(np.arange(missing.size), late)
            t0 = time.time()
            for name in self.names:
                vals = {
                    "w": np.zeros((missing.size,)
                                  + self.cold[name].shape[1:], np.float32),
                    "m": np.zeros((missing.size,)
                                  + self.cold[name].shape[1:], np.float32),
                    "v": np.zeros((missing.size,)
                                  + self.cold[name].shape[1:], np.float32),
                    "tau": np.zeros((missing.size,), np.int32),
                }
                if early.size:
                    eids = missing[early]
                    vals["w"][early] = self._fetch(self.cold[name], eids)
                    vals["m"][early] = self.cold_m[name][eids]
                    vals["v"][early] = self.cold_v[name][eids]
                    vals["tau"][early] = self.cold_tau[name][eids]
                plan.values[name] = vals
            self.stats["prefetch_fetch_s"] += time.time() - t0
        # Pin every slot the group references until its plan is applied.
        group_slots = self.id_to_slot[uids]
        self.pin_count[group_slots] += 1
        plan.group_slots = group_slots.astype(np.int32)
        self._pending.append(plan)
        # Remap the group's ids to slot ids (the arrays staged to device).
        out = []
        for b in group:
            nb = dict(b)
            nb["feat_ids"] = self.id_to_slot[
                b["feat_ids"].astype(np.int64)].astype(np.int32)
            out.append(nb)
        return out

    # -- main-thread side -----------------------------------------------
    def _pad_slots(self, slots: np.ndarray) -> np.ndarray:
        """Slot list padded to the next power of two with the OOB slot id
        ``hot_rows`` (dropped by the scatter), so compile count stays
        O(log max_group) per table shape."""
        p = _pow2_pad(max(slots.size, 1))
        ps = np.full((p,), self.hot_rows, np.int32)
        ps[: slots.size] = slots
        return ps

    @staticmethod
    def _pad_vals(p: int, n: int, vals: np.ndarray) -> np.ndarray:
        pv = np.zeros((p,) + vals.shape[1:], vals.dtype)
        pv[:n] = vals
        return pv

    def _install(self, table: jax.Array, slots: np.ndarray,
                 vals: np.ndarray) -> jax.Array:
        """Per-array padded scatter-install (the ``--embedding_kernels
        off`` seed path; the kernel path batches a whole transaction
        through ops.pallas_embedding.install_rows instead)."""
        ps = self._pad_slots(slots)
        return _jit_install(
            table, ps, self._pad_vals(ps.size, slots.size, vals))

    def apply_next(self, state):
        """Apply the oldest queued plan to ``state``: write evicted rows
        back to the cold store (reading the post-previous-dispatch values —
        device_get blocks on the producing program), late-fetch any rows
        whose cold copy only just became current, then install the fetched
        rows (weights + m/v/tau) into their hot slots."""
        if not self._pending:
            return state
        with trace_lib.span("hotcold.install"):
            return self._apply_next_traced(state)

    def _apply_next_traced(self, state):
        t_apply = time.time()
        plan = self._pending.popleft()
        params = dict(state.params)
        opt = dict(state.opt_state)
        embed = dict(opt["embed"])
        if plan.evict_slots.size:
            es = plan.evict_slots
            for name in self.names:
                oe = embed[name]["table"]
                self.cold[name].write(
                    plan.evict_ids,
                    np.asarray(jax.device_get(params[name][es]), np.float32))
                self.cold_m[name][plan.evict_ids] = np.asarray(
                    jax.device_get(oe.m[es]), np.float32)
                self.cold_v[name][plan.evict_ids] = np.asarray(
                    jax.device_get(oe.v[es]), np.float32)
                self.cold_tau[name][plan.evict_ids] = np.asarray(
                    jax.device_get(oe.tau[es]), np.int32)
            with self._lock:
                for vid in plan.evict_ids:
                    vid = int(vid)
                    left = self._pending_evicted.get(vid, 0) - 1
                    if left <= 0:
                        self._pending_evicted.pop(vid, None)
                    else:
                        self._pending_evicted[vid] = left
        if plan.late_idx.size:
            t0 = time.time()
            lids = plan.install_ids[plan.late_idx].astype(np.int64)
            for name in self.names:
                vals = plan.values[name]
                vals["w"][plan.late_idx] = self._fetch(self.cold[name], lids)
                vals["m"][plan.late_idx] = self.cold_m[name][lids]
                vals["v"][plan.late_idx] = self.cold_v[name][lids]
                vals["tau"][plan.late_idx] = self.cold_tau[name][lids]
            self.stats["apply_fetch_s"] += time.time() - t0
        if plan.install_slots.size:
            s = plan.install_slots
            from ..train import optimizers as opt_lib  # noqa: PLC0415
            kmode = self.cfg.embedding_kernels
            ps = self._pad_slots(s)
            for name in self.names:
                vals = plan.values[name]
                oe = embed[name]["table"]
                out = None
                if kmode != "off":
                    # ONE launch per (table, transaction): the weight rows
                    # and all three lazy-Adam companions install together
                    # (ops.pallas_embedding.install_rows); element-identical
                    # to the seed per-array scatters, so the tiering parity
                    # pins hold across the kill switch.
                    out = pemb.install_rows(
                        params[name], oe.m, oe.v, oe.tau, ps,
                        self._pad_vals(ps.size, s.size, vals["w"]),
                        self._pad_vals(ps.size, s.size, vals["m"]),
                        self._pad_vals(ps.size, s.size, vals["v"]),
                        self._pad_vals(ps.size, s.size, vals["tau"]),
                        mode=kmode)
                if out is not None:
                    w_new, m_new, v_new, tau_new = out
                    params[name] = w_new
                    embed[name] = {"table": opt_lib.EmbedAdamEntry(
                        m=m_new, v=v_new, tau=tau_new)}
                else:
                    params[name] = self._install(params[name], s, vals["w"])
                    embed[name] = {"table": opt_lib.EmbedAdamEntry(
                        m=self._install(oe.m, s, vals["m"]),
                        v=self._install(oe.v, s, vals["v"]),
                        tau=self._install(oe.tau, s, vals["tau"]))}
        with self._cond:
            self.pin_count[plan.group_slots] -= 1
            self._cond.notify_all()
        opt["embed"] = embed
        self.stats["apply_s"] += time.time() - t_apply
        return state.replace(params=params, opt_state=opt)

    # -- eval / export --------------------------------------------------
    def flush(self, state) -> None:
        """Write every resident hot row (weights + moments) back to the
        cold store. Leaves residency unchanged (the hot copy stays the
        authoritative one for training)."""
        with self._lock:
            res = np.flatnonzero(self.slot_to_id >= 0)
            ids = self.slot_to_id[res].astype(np.int64)
        if not res.size:
            return
        embed = state.opt_state["embed"]
        for name in self.names:
            self.cold[name].write(ids, np.asarray(
                jax.device_get(state.params[name][res]), np.float32))
            oe = embed[name]["table"]
            self.cold_m[name][ids] = np.asarray(
                jax.device_get(oe.m[res]), np.float32)
            self.cold_v[name][ids] = np.asarray(
                jax.device_get(oe.v[res]), np.float32)
            self.cold_tau[name][ids] = np.asarray(
                jax.device_get(oe.tau[res]), np.int32)

    def densified(self, state):
        """A state whose embedding params are the FULL ``[padded_vocab,...]``
        float32 tables (flushed hot rows + cold rows + zero pad rows) — the
        offline eval/predict path runs the ordinary dense forward on it."""
        self.flush(state)
        params = dict(state.params)
        pv = self.model.emb.padded_vocab
        for name in self.names:
            real = self.cold[name].dense()
            full = np.zeros((pv,) + real.shape[1:], np.float32)
            full[: self.feature_size] = real
            params[name] = jnp.asarray(full)
        return state.replace(params=params)

    def checkpoint_state(self, state):
        """The state an UNTIERED run would checkpoint: full densified
        params PLUS full-shape embedding Adam slots (hot window flushed
        back, cold rows merged, pad rows zero). A checkpoint written from
        this state restores bit-exactly into a dense run, a differently
        sized hot cache, or back into this one (via adopt-after-restore)."""
        state = self.densified(state)  # flush() inside syncs cold_m/v/tau
        opt = dict(state.opt_state)
        embed = dict(opt["embed"])
        pv = self.model.emb.padded_vocab
        from ..train import optimizers as opt_lib  # noqa: PLC0415
        for name in self.names:
            m = np.zeros((pv,) + self.cold[name].shape[1:], np.float32)
            v = np.zeros((pv,) + self.cold[name].shape[1:], np.float32)
            tau = np.zeros((pv,), np.int32)
            m[: self.feature_size] = self.cold_m[name]
            v[: self.feature_size] = self.cold_v[name]
            tau[: self.feature_size] = self.cold_tau[name]
            embed[name] = {"table": opt_lib.EmbedAdamEntry(
                m=jnp.asarray(m), v=jnp.asarray(v), tau=jnp.asarray(tau))}
        opt["embed"] = embed
        return state.replace(opt_state=opt)

    def hit_rate(self) -> float:
        n = self.stats["lookups"]
        return float(self.stats["hits"] / n) if n else 0.0

    def overlap_fraction(self) -> float:
        """Fraction of total cold-fetch wall time that ran on the staging
        thread (i.e. overlapped device compute instead of stalling the
        dispatch loop)."""
        tot = self.stats["prefetch_fetch_s"] + self.stats["apply_fetch_s"]
        return float(self.stats["prefetch_fetch_s"] / tot) if tot else 1.0


@jax.jit
def _jit_install(table: jax.Array, slots: jax.Array,
                 vals: jax.Array) -> jax.Array:
    """table.at[slots].set(vals) with OOB-padded slots dropped."""
    return table.at[slots].set(vals)
