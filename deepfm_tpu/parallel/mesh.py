"""Device mesh construction + parameter sharding rules.

The TPU-native replacement for both reference comm topologies: the 2-D mesh
``('data', 'model')`` carries synchronous data parallelism (psum over 'data'
replaces Horovod's NCCL ring, X2) and embedding-table row-sharding (rows over
'model' replace the PS-hosted table, X1). On real hardware XLA lays both
collectives on ICI; across slices they ride DCN — no separate comm library.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config

DATA_AXIS = "data"
MODEL_AXIS = "model"

# (intra-host, inter-host) axis_index_groups for a two-stage 'data' reduce.
HierGroups = Tuple[List[List[int]], List[List[int]]]


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Mesh plus the axis names the step functions reduce over."""
    mesh: Optional[Mesh]

    @property
    def data_size(self) -> int:
        return self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1

    @property
    def model_size(self) -> int:
        return self.mesh.shape[MODEL_AXIS] if self.mesh is not None else 1

    @property
    def data_axis(self) -> Optional[str]:
        return DATA_AXIS if self.mesh is not None else None

    @property
    def model_axis(self) -> Optional[str]:
        return MODEL_AXIS if self.mesh is not None else None

    def sharding(self, spec: P) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec)


def build_mesh(cfg: Config, devices: Optional[list] = None) -> MeshInfo:
    """Build the ('data', 'model') mesh from cfg.mesh_data x cfg.mesh_model.

    ``mesh_data=0`` means "all remaining devices". A 1x1 mesh degenerates to
    no mesh (plain single-device jit).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    model = max(cfg.mesh_model, 1)
    if n % model != 0:
        raise ValueError(f"mesh_model={model} does not divide device count {n}")
    data = cfg.mesh_data if cfg.mesh_data > 0 else n // model
    if data * model > n:
        raise ValueError(
            f"mesh {data}x{model} needs {data*model} devices, have {n}")
    if data * model == 1:
        return MeshInfo(mesh=None)
    dev_array = np.asarray(devices[: data * model]).reshape(data, model)
    return MeshInfo(mesh=Mesh(dev_array, (DATA_AXIS, MODEL_AXIS)))


# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------


def param_pspecs(params: Any, embedding_names: Tuple[str, ...],
                 model_size: int = 1) -> Any:
    """PartitionSpec tree for a param tree: embedding tables row-sharded over
    MODEL_AXIS (dim 0) when the model axis is real (size > 1), everything
    else replicated. A size-1 model axis uses replicated specs so shard_map's
    replication inference (check_vma) sees the un-psum'ed lookup as invariant.
    """

    def spec_for(path: Tuple, leaf: Any) -> P:
        names = {getattr(p, "key", getattr(p, "name", None)) for p in path}
        if model_size > 1 and names & set(embedding_names):
            return P(MODEL_AXIS, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_pspecs(batch: Any) -> Any:
    """Batches are sharded along the data axis on dim 0."""
    return jax.tree.map(lambda x: P(DATA_AXIS, *([None] * (x.ndim - 1))), batch)


# ---------------------------------------------------------------------------
# Hierarchical (DCN-aware) cross-host reduction
# ---------------------------------------------------------------------------


def data_axis_host_groups(info: MeshInfo) -> Optional[HierGroups]:
    """Derive (intra-host, inter-host) axis_index_groups for the data axis.

    On a multi-host mesh the flat ``psum`` over 'data' mixes fast intra-host
    links (ICI) with the slow cross-host fabric (DCN) in one ring. Splitting
    it into an intra-host reduce followed by an inter-host reduce over one
    representative per host keeps the DCN stage at 1/L of the flat traffic
    (L = data-axis rows per host) at the cost of one extra fast stage.

    Returns None when the topology doesn't decompose cleanly: single host,
    a host owning a non-contiguous or unequal run of data-axis rows, or a
    data-axis row whose model columns straddle hosts (the group index must
    mean the same thing for every model column).
    """
    if info.mesh is None:
        return None
    dev_array = np.asarray(info.mesh.devices)  # [data, model]
    D = dev_array.shape[0]
    # Host of each data-axis row; every model column in a row must agree.
    row_host = []
    for d in range(D):
        procs = {dev.process_index for dev in dev_array[d]}
        if len(procs) != 1:
            return None
        row_host.append(procs.pop())
    hosts = sorted(set(row_host))
    if len(hosts) < 2 or len(hosts) >= D:
        return None
    # Rows per host must be equal and contiguous for rectangular groups.
    per_host = D // len(hosts)
    if per_host * len(hosts) != D:
        return None
    intra: List[List[int]] = []
    for h_start in range(0, D, per_host):
        block = row_host[h_start:h_start + per_host]
        if len(set(block)) != 1:
            return None
        intra.append(list(range(h_start, h_start + per_host)))
    if len({row_host[g[0]] for g in intra}) != len(intra):
        return None
    inter = [[g[k] for g in intra] for k in range(per_host)]
    return intra, inter


def hierarchical_psum(tree: Any, axis_name: str, groups: HierGroups) -> Any:
    """Two-stage psum over ``axis_name``: intra-host then inter-host.

    Numerically this sums the same terms as the flat psum, just reassociated
    by host: equal to within 1-2 ULP (XLA orders the two reductions
    differently even on the virtual CPU mesh — pinned in tests), never
    bit-guaranteed.
    """
    intra, inter = groups
    tree = jax.tree.map(
        lambda x: jax.lax.psum(x, axis_name, axis_index_groups=intra), tree)
    return jax.tree.map(
        lambda x: jax.lax.psum(x, axis_name, axis_index_groups=inter), tree)


def hierarchical_pmean(tree: Any, axis_name: str, groups: HierGroups,
                       axis_size: int) -> Any:
    """pmean implemented as hierarchical_psum / axis_size."""
    tree = hierarchical_psum(tree, axis_name, groups)
    inv = 1.0 / float(axis_size)
    return jax.tree.map(lambda x: x * inv, tree)


def grad_payload_bytes(params: Any, embedding_names: Tuple[str, ...],
                       model_size: int = 1, *,
                       embedding_shard: str = "off") -> int:
    """Per-device bytes moved by one gradient all-reduce over 'data'.

    Dense path: embedding tables row-sharded over 'model' reduce only
    their 1/model_size slice; everything else is replicated and reduced in
    full. Analytic (ring algorithms move ~2x this; we report payload).

    Under ``embedding_shard="rows"`` the sparse step never reduces a dense
    row-space gradient: each owner psums its LOCAL table-space
    contribution — every global row counted exactly once, on its owner,
    whatever the mesh shape (with mesh_model=1 that is the full table, NOT
    divided) — plus ONE touched-union mask per physical table (int32
    [rows_local], shared by all embedding names, counted against the
    first). The forward row exchange is separate traffic over 'model'
    (ops.embedding.exchange_payload_bytes), not part of this reduce.
    """
    first = embedding_names[0] if embedding_names else None

    def leaf_bytes(path: Tuple, leaf: Any) -> int:
        names = {getattr(p, "key", getattr(p, "name", None)) for p in path}
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        if not names & set(embedding_names):
            return nbytes
        if embedding_shard == "rows":
            shards = max(model_size, 1)
            owned = nbytes // shards
            if first in names:
                owned += (int(leaf.shape[0]) // shards) * 4
            return owned
        if model_size > 1:
            return nbytes // model_size
        return nbytes

    sizes = jax.tree_util.tree_map_with_path(leaf_bytes, params)
    return int(sum(jax.tree.leaves(sizes)))


def opt_state_pspecs(opt_state: Any, params: Any, param_specs: Any) -> Any:
    """Specs for optimizer state: leaves that mirror a param keep that param's
    spec (matched by shape), scalars/steps are replicated.

    Works for every optimizer in the zoo (adam/adagrad/momentum/ftrl) whose
    states are param-shaped accumulators plus scalar counters.
    """
    shape_to_spec = {}
    for p_leaf, s_leaf in zip(jax.tree.leaves(params), jax.tree.leaves(param_specs)):
        shape_to_spec.setdefault(tuple(p_leaf.shape), s_leaf)

    def spec_for(leaf: Any) -> P:
        if hasattr(leaf, "shape") and tuple(leaf.shape) in shape_to_spec:
            return shape_to_spec[tuple(leaf.shape)]
        return P()

    return jax.tree.map(spec_for, opt_state)
