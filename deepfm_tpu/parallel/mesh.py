"""Device mesh construction + parameter sharding rules.

The TPU-native replacement for both reference comm topologies: the 2-D mesh
``('data', 'model')`` carries synchronous data parallelism (psum over 'data'
replaces Horovod's NCCL ring, X2) and embedding-table row-sharding (rows over
'model' replace the PS-hosted table, X1). On real hardware XLA lays both
collectives on ICI; across slices they ride DCN — no separate comm library.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Mesh plus the axis names the step functions reduce over."""
    mesh: Optional[Mesh]

    @property
    def data_size(self) -> int:
        return self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1

    @property
    def model_size(self) -> int:
        return self.mesh.shape[MODEL_AXIS] if self.mesh is not None else 1

    @property
    def data_axis(self) -> Optional[str]:
        return DATA_AXIS if self.mesh is not None else None

    @property
    def model_axis(self) -> Optional[str]:
        return MODEL_AXIS if self.mesh is not None else None

    def sharding(self, spec: P) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec)


def build_mesh(cfg: Config, devices: Optional[list] = None) -> MeshInfo:
    """Build the ('data', 'model') mesh from cfg.mesh_data x cfg.mesh_model.

    ``mesh_data=0`` means "all remaining devices". A 1x1 mesh degenerates to
    no mesh (plain single-device jit).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    model = max(cfg.mesh_model, 1)
    if n % model != 0:
        raise ValueError(f"mesh_model={model} does not divide device count {n}")
    data = cfg.mesh_data if cfg.mesh_data > 0 else n // model
    if data * model > n:
        raise ValueError(
            f"mesh {data}x{model} needs {data*model} devices, have {n}")
    if data * model == 1:
        return MeshInfo(mesh=None)
    dev_array = np.asarray(devices[: data * model]).reshape(data, model)
    return MeshInfo(mesh=Mesh(dev_array, (DATA_AXIS, MODEL_AXIS)))


# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------


def param_pspecs(params: Any, embedding_names: Tuple[str, ...],
                 model_size: int = 1) -> Any:
    """PartitionSpec tree for a param tree: embedding tables row-sharded over
    MODEL_AXIS (dim 0) when the model axis is real (size > 1), everything
    else replicated. A size-1 model axis uses replicated specs so shard_map's
    replication inference (check_vma) sees the un-psum'ed lookup as invariant.
    """

    def spec_for(path: Tuple, leaf: Any) -> P:
        names = {getattr(p, "key", getattr(p, "name", None)) for p in path}
        if model_size > 1 and names & set(embedding_names):
            return P(MODEL_AXIS, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_pspecs(batch: Any) -> Any:
    """Batches are sharded along the data axis on dim 0."""
    return jax.tree.map(lambda x: P(DATA_AXIS, *([None] * (x.ndim - 1))), batch)


def opt_state_pspecs(opt_state: Any, params: Any, param_specs: Any) -> Any:
    """Specs for optimizer state: leaves that mirror a param keep that param's
    spec (matched by shape), scalars/steps are replicated.

    Works for every optimizer in the zoo (adam/adagrad/momentum/ftrl) whose
    states are param-shaped accumulators plus scalar counters.
    """
    shape_to_spec = {}
    for p_leaf, s_leaf in zip(jax.tree.leaves(params), jax.tree.leaves(param_specs)):
        shape_to_spec.setdefault(tuple(p_leaf.shape), s_leaf)

    def spec_for(leaf: Any) -> P:
        if hasattr(leaf, "shape") and tuple(leaf.shape) in shape_to_spec:
            return shape_to_spec[tuple(leaf.shape)]
        return P()

    return jax.tree.map(spec_for, opt_state)
