from . import bootstrap, mesh  # noqa: F401
from .mesh import MeshInfo, build_mesh, param_pspecs  # noqa: F401
