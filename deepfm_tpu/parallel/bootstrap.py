"""Multi-process bootstrap: the L4 cluster-topology layer, TPU-native.

The reference consumed SageMaker's TF_CONFIG/SM_* contract and shipped a
vestigial local bootstrap (`set_dist_env`, 1-ps-cpu/...py:294-339) that
hand-built TF_CONFIG with chief/evaluator role rewriting. On TPU none of that
role machinery exists: every process is symmetric SPMD. This module wraps
``jax.distributed.initialize`` and exposes rank helpers; "chief" semantics
(rank-0-only checkpoint/export, reference 2-hvd-gpu/...py:365-368) map to
``is_chief()``.

dist_mode (Config):
  0 — single process (auto-init if TPU env provides topology)
  1 — local multi-process test cluster: processes rendezvous on
      ``coordinator_address`` with explicit num_processes/process_id
      (the `set_dist_env` analog, for CPU multi-process tests)
  2 — managed cluster (GKE/TPU VM): jax.distributed.initialize() discovers
      topology from the environment
"""

from __future__ import annotations

import jax

from ..config import Config

_INITIALIZED = False


def initialize(cfg: Config) -> None:
    """Idempotent jax.distributed bootstrap per cfg.dist_mode."""
    global _INITIALIZED
    if _INITIALIZED or cfg.dist_mode == 0:
        return
    if cfg.dist_mode == 1:
        if not cfg.coordinator_address:
            raise ValueError("dist_mode=1 requires coordinator_address")
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
    elif cfg.dist_mode == 2:
        jax.distributed.initialize()
    else:
        raise ValueError(f"unknown dist_mode {cfg.dist_mode}")
    _INITIALIZED = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    """Rank-0 semantics: checkpoint/eval/export only on the chief process
    (reference rank-0-only model_dir, 2-hvd-gpu/...py:365-368)."""
    return jax.process_index() == 0
