from . import embedding, fm  # noqa: F401
