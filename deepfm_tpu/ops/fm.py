"""FM second-order interaction op.

The O(F*K) factorization-machine identity (reference ``1-ps-cpu/...py:181-187``):

    y_v[b] = 0.5 * sum_k [ (sum_f v[b,f,k]*x[b,f])^2 - sum_f (v[b,f,k]*x[b,f])^2 ]

``fm_interaction`` is the XLA-fused formulation (reduce/square ops fuse into
one HBM pass); ``deepfm_tpu.ops.pallas_fm`` provides a hand-fused Pallas
kernel for the combined first+second-order path, selected by the model when
running on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp


def fm_interaction(xv: jnp.ndarray) -> jnp.ndarray:
    """xv: [B, F, K] = embeddings * feature values. Returns [B]."""
    sum_sq = jnp.square(jnp.sum(xv, axis=1))      # [B, K]
    sq_sum = jnp.sum(jnp.square(xv), axis=1)      # [B, K]
    return 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)  # [B]


def masked_softmax(scores: jnp.ndarray, mask: jnp.ndarray,
                   axis: int = -1) -> jnp.ndarray:
    """Softmax over ``axis`` restricted to positions where ``mask > 0``,
    returning exact ZEROS — not NaN — on fully-masked rows.

    The naive ``softmax(scores + (mask-1)*1e9)`` still divides by ~0 when a
    row is entirely masked (an empty user history), producing NaN that
    poisons every downstream sum. Here masked positions are excluded from
    both the max-subtraction and the normalizer, and the all-masked case is
    resolved with ``where(denom > 0, num/denom, 0)`` so attention over an
    empty sequence contributes nothing instead of NaN. Shared by every
    attention block (DIN/BST target attention).
    """
    valid = (mask > 0).astype(scores.dtype)
    # Masked scores replaced with a finite -inf-ish sentinel BEFORE the
    # max/exp: a fully-masked row then has max == sentinel and shifted == 0
    # everywhere (never `scores - sentinel`, whose exp would overflow to
    # inf and turn inf*0 into NaN).
    neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    masked = jnp.where(valid > 0, scores, neg)
    shifted = masked - jnp.max(masked, axis=axis, keepdims=True)
    num = jnp.exp(shifted) * valid
    denom = jnp.sum(num, axis=axis, keepdims=True)
    return jnp.where(denom > 0, num / jnp.where(denom > 0, denom, 1.0),
                     jnp.zeros((), scores.dtype))
