"""FM second-order interaction op.

The O(F*K) factorization-machine identity (reference ``1-ps-cpu/...py:181-187``):

    y_v[b] = 0.5 * sum_k [ (sum_f v[b,f,k]*x[b,f])^2 - sum_f (v[b,f,k]*x[b,f])^2 ]

``fm_interaction`` is the XLA-fused formulation (reduce/square ops fuse into
one HBM pass); ``deepfm_tpu.ops.pallas_fm`` provides a hand-fused Pallas
kernel for the combined first+second-order path, selected by the model when
running on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp


def fm_interaction(xv: jnp.ndarray) -> jnp.ndarray:
    """xv: [B, F, K] = embeddings * feature values. Returns [B]."""
    sum_sq = jnp.square(jnp.sum(xv, axis=1))      # [B, K]
    sq_sum = jnp.sum(jnp.square(xv), axis=1)      # [B, K]
    return 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)  # [B]
