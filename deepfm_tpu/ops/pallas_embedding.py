"""Pallas TPU kernels for the sparse embedding plane, with XLA fallbacks.

EMBED_r01 measured the sparse update path losing to dense (40.8 vs 14.7
ms/step at V=100k) because its three hot seams ran on XLA defaults:

  1. **plan build** — ``make_plan``'s ``jnp.unique(size=N)`` lowers to a
     sort-based program (~26 ms at N=40k on XLA:CPU);
  2. **gather + segment-sum cotangent** — one forward gather per embedding
     name, and one batch-sized scatter-add per name in the backward;
  3. **cache install** — ``TieredEmbeddingRuntime`` launched one pow2-padded
     jit scatter per array (w/m/v/tau = 4 launches) per transaction.

Each seam here has up to three legs, selected by :func:`resolve`:

  * ``pallas`` — a fused kernel (this module), compiled only on TPU behind
    :func:`supported`; every kernel also runs through the Pallas
    interpreter on CPU (``interpret=True``) so the tier-1 suite checks the
    kernel bodies against NumPy oracles without TPU hardware.
  * ``opt`` — a restructured XLA program with bit-identical outputs: the
    counting plan build (``ops.embedding.make_plan_counting``), the
    select-writeback (``scatter_rows`` on counting plans), and the fused
    multi-array install. These are what ``auto`` picks on non-TPU backends.
  * ``ref`` — the seed formulation, byte-for-byte (``--embedding_kernels
    off`` restores it everywhere: the kill switch).

Selection is static per (backend, shape) from the committed A/B table in
EMBED_r02.json — a leg only becomes the default where it measured a
clean-band win; ties and losses keep the reference leg (TUNING §2.11 has
the table). The one shape-dependent rule: the counting plan build does a
vocab-shaped prefix sum, so it wins only while the physical table is small
relative to the sort cost — above ``PLAN_COUNT_MAX_ROWS`` rows ``auto``
keeps the sort-based ``make_plan`` (and with it the scatter writeback,
whose cost does not scale with the vocab).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import embedding as emb_ops

try:  # pltpu import fails on some non-TPU builds; interpret mode never needs it
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

#: embedding_kernels values (config-validated).
MODES = ("auto", "pallas", "xla", "off")

# The counting plan build costs one [rows+1] prefix sum + one presence
# scatter; the sort-based unique costs O(N log N) independent of rows.
# Measured crossover on XLA:CPU is far above the largest physical table in
# the bench sweep (4x262144 hashed buckets, 100k monolithic); 2M rows keeps
# a safety margin before the vocab-shaped pass could dominate.
PLAN_COUNT_MAX_ROWS = 2_000_000

# VMEM budget for the compiled kernels (per pallas_fm: ~16MB/core, leave
# headroom). The gather/segsum kernels keep the [U, D] row block plus the
# [N, D] batch block live; the plan kernel keeps the [rows+1] count vector.
_VMEM_BUDGET = 14 * 1024 * 1024


def supported(kernel: str, *, num_rows: int = 0, n_ids: int = 0,
              width: int = 1) -> bool:
    """True when ``kernel`` ("plan" | "take" | "install") can run COMPILED
    at this shape — requires a TPU backend and the kernel's working set to
    fit VMEM. CPU/GPU backends always gate the compiled path off (the
    interpreter is a numerics tool, not a fast path)."""
    if pltpu is None or jax.default_backend() != "tpu":
        return False
    if kernel == "plan":
        return 4 * (num_rows + 1) + 3 * 4 * n_ids <= _VMEM_BUDGET
    if kernel == "take":
        return 4 * width * (2 * n_ids) <= _VMEM_BUDGET
    if kernel == "install":
        return 4 * width * (2 * n_ids) <= _VMEM_BUDGET
    raise ValueError(f"unknown kernel {kernel!r}")


def resolve(mode: str, kernel: str, *, num_rows: int = 0, n_ids: int = 0,
            width: int = 1) -> str:
    """Pick the leg ("pallas" | "opt" | "ref") for one seam.

    ``off`` is the kill switch: the seed path everywhere, bit-for-bit.
    ``xla`` forces the optimized XLA legs even on TPU. ``pallas`` and
    ``auto`` take the compiled kernel where :func:`supported` allows and
    degrade to the optimized XLA leg elsewhere — except the plan seam,
    where tables above ``PLAN_COUNT_MAX_ROWS`` keep the sort-based
    reference build (the vocab-shaped counting pass would scale with rows;
    the sort does not)."""
    if mode not in MODES:
        raise ValueError(f"embedding_kernels must be one of {MODES}, "
                         f"got {mode!r}")
    if mode == "off":
        return "ref"
    if kernel == "plan" and num_rows > PLAN_COUNT_MAX_ROWS:
        return "ref"
    if mode in ("auto", "pallas") and supported(
            kernel, num_rows=num_rows, n_ids=n_ids, width=width):
        return "pallas"
    return "opt"


# ---------------------------------------------------------------------------
# Kernel 1: device-side plan build (unique + remap, static shapes)
# ---------------------------------------------------------------------------
# Same counting formulation as make_plan_counting, as one kernel: presence
# marks and the prefix sum stay in VMEM instead of round-tripping three
# HBM-shaped intermediates through XLA op boundaries. Outputs are
# PlanEntry-compatible: uids/inv bit-identical to jnp.unique(size=N,
# fill_value=num_rows), plus the touched/rank writeback companions.


def _plan_kernel(ids_ref, uids_ref, inv_ref, touched_ref, rank_ref,
                 counts_ref):
    # counts_ref is a [1, rows+1] work buffer (an extra kernel output — the
    # wrapper discards it; using an output instead of pltpu scratch keeps
    # the body identical between interpret and compiled modes).
    n = ids_ref.shape[1]
    rows = touched_ref.shape[1]
    counts_ref[...] = jnp.zeros_like(counts_ref)

    def mark(i, _):
        counts_ref[0, ids_ref[0, i]] = 1
        return 0

    jax.lax.fori_loop(0, n, mark, 0)
    csum = jnp.cumsum(counts_ref[...], axis=1)          # [1, rows+1]
    rank = csum - counts_ref[...]                        # exclusive rank
    touched_ref[...] = counts_ref[0, :rows].reshape(1, rows) > 0
    # rank spans the FULL [rows+1] id space: the OOB fill id (= rows) must
    # be remappable too (masked hashed positions carry it).
    rank_ref[...] = rank.astype(jnp.int32)
    # uids: compact the present row ids into their rank slot; unfilled
    # slots keep the OOB fill id (= rows), matching unique's fill_value.
    uids_ref[...] = jnp.full_like(uids_ref, rows)

    def emit(r, _):
        @pl.when(counts_ref[0, r] > 0)
        def _():
            uids_ref[0, rank_ref[0, r]] = r
        return 0

    jax.lax.fori_loop(0, rows, emit, 0)

    def remap(i, _):
        inv_ref[0, i] = rank_ref[0, ids_ref[0, i]]
        return 0

    jax.lax.fori_loop(0, n, remap, 0)


def plan_build_pallas(ids: jax.Array, num_rows: int,
                      mask: Optional[jax.Array] = None,
                      interpret: bool = False) -> emb_ops.PlanEntry:
    """Device-side plan build as ONE kernel launch. ``interpret=True`` runs
    the identical body on CPU (tests); the compiled path is TPU-only
    behind ``supported("plan", ...)``.

    NOTE: rank[r] for rows past the last touched id equals U (one past the
    uid slots) inside the kernel's scratch; the emitted ``rank`` output is
    only read under ``touched`` downstream, same contract as the XLA leg.
    """
    flat = ids.reshape(1, -1).astype(jnp.int32)
    n = flat.shape[1]
    uids, inv, touched, rank, _counts = pl.pallas_call(
        _plan_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((1, num_rows), jnp.bool_),
            jax.ShapeDtypeStruct((1, num_rows + 1), jnp.int32),
            jax.ShapeDtypeStruct((1, num_rows + 1), jnp.int32),
        ],
        interpret=interpret,
    )(flat)
    return emb_ops.PlanEntry(
        uids=uids[0], inv=inv[0].reshape(ids.shape), mask=mask,
        num_rows=num_rows, touched=touched[0], rank=rank[0, :num_rows])


def plan_build(ids: jax.Array, num_rows: int,
               mask: Optional[jax.Array] = None, *,
               mode: str = "auto") -> emb_ops.PlanEntry:
    """Build a sparse-update plan through the selected leg. All legs emit
    bit-identical uids/inv; the counting legs additionally carry the
    touched/rank select-writeback companions."""
    leg = resolve(mode, "plan", num_rows=num_rows, n_ids=ids.size)
    if leg == "pallas":
        return plan_build_pallas(ids, num_rows, mask)
    if leg == "opt":
        return emb_ops.make_plan_counting(ids, num_rows, mask)
    return emb_ops.make_plan(ids, num_rows, mask)


# ---------------------------------------------------------------------------
# Kernel 2: fused gather forward + segment-sum backward (custom VJP)
# ---------------------------------------------------------------------------
# Forward: out[p] = rows[inv[p]] for every batch position p. Backward: the
# batch-sized segment-sum d_rows[u] = sum_{p: inv[p]=u} g[p] — the exact
# transpose XLA's AD emits for the gather, as one accumulate kernel instead
# of a gather + scatter-add pair per embedding name. The XLA legs stay
# plain ``jnp.take`` (AD supplies the identical scatter-add); the fusion
# win there is structural: the trainer concatenates every embedding name's
# rows into ONE [U, D] leaf so a single take/scatter-add pair serves all
# names (train.loop).


def _take_fwd_kernel(rows_ref, inv_ref, out_ref):
    n = inv_ref.shape[1]

    def body(i, _):
        out_ref[i, :] = rows_ref[inv_ref[0, i], :]
        return 0

    jax.lax.fori_loop(0, n, body, 0)


def _take_bwd_kernel(g_ref, inv_ref, out_ref):
    n = inv_ref.shape[1]
    out_ref[...] = jnp.zeros_like(out_ref)

    def body(i, _):
        out_ref[inv_ref[0, i], :] += g_ref[i, :]
        return 0

    jax.lax.fori_loop(0, n, body, 0)


def _take_pallas_fwd(rows: jax.Array, inv2: jax.Array,
                     interpret: bool) -> jax.Array:
    n = inv2.shape[1]
    return pl.pallas_call(
        _take_fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((n, rows.shape[1]), rows.dtype),
        interpret=interpret,
    )(rows, inv2)


def _take_pallas_bwd(g: jax.Array, inv2: jax.Array, u: int,
                     interpret: bool) -> jax.Array:
    return pl.pallas_call(
        _take_bwd_kernel,
        out_shape=jax.ShapeDtypeStruct((u, g.shape[1]), g.dtype),
        interpret=interpret,
    )(g, inv2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def take_rows_pallas(rows: jax.Array, inv: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """rows[inv] with a hand-written segment-sum VJP, both as Pallas
    kernels. rows: [U, D]; inv: int32 [...] -> out [..., D]."""
    inv2 = inv.reshape(1, -1).astype(jnp.int32)
    out = _take_pallas_fwd(rows, inv2, interpret)
    return out.reshape(inv.shape + rows.shape[1:])


def _take_rows_fwd(rows, inv, interpret):
    return take_rows_pallas(rows, inv, interpret), (inv, rows.shape[0])


def _take_rows_bwd(interpret, res, g):
    inv, u = res
    g2 = g.reshape(-1, g.shape[-1])
    inv2 = inv.reshape(1, -1).astype(jnp.int32)
    return _take_pallas_bwd(g2, inv2, u, interpret), None


take_rows_pallas.defvjp(_take_rows_fwd, _take_rows_bwd)


def take_rows(rows: jax.Array, inv: jax.Array, *,
              mode: str = "auto") -> jax.Array:
    """Positionwise view of gathered rows, leg-selected. The XLA legs are
    ``jnp.take`` — its AD transpose IS the batch-sized segment-sum — so
    every leg produces bit-identical values and cotangents."""
    leg = resolve(mode, "take", n_ids=inv.size,
                  width=int(rows.shape[-1]) if rows.ndim > 1 else 1)
    if leg == "pallas":
        return take_rows_pallas(rows, inv)
    return jnp.take(rows, inv, axis=0)


# ---------------------------------------------------------------------------
# Kernel 3: fused install/evict scatter (tiered cache transaction)
# ---------------------------------------------------------------------------
# One launch installs a transaction's weight rows AND the three lazy-Adam
# companions (m, v, tau) at their hot-cache slots; OOB slot ids (the pow2
# padding) are dropped. The XLA "opt" leg fuses the same four scatters into
# one jit program (one dispatch instead of four); "ref" is the seed
# per-array ``_jit_install``.


def _install_kernel(w_ref, m_ref, v_ref, tau_ref, slots_ref,
                    wv_ref, mv_ref, vv_ref, tv_ref,
                    ow_ref, om_ref, ov_ref, otau_ref):
    rows = w_ref.shape[0]
    s = slots_ref.shape[1]
    ow_ref[...] = w_ref[...]
    om_ref[...] = m_ref[...]
    ov_ref[...] = v_ref[...]
    otau_ref[...] = tau_ref[...]

    def body(i, _):
        slot = slots_ref[0, i]

        @pl.when(slot < rows)
        def _():
            ow_ref[slot, :] = wv_ref[i, :]
            om_ref[slot, :] = mv_ref[i, :]
            ov_ref[slot, :] = vv_ref[i, :]
            otau_ref[0, slot] = tv_ref[0, i]
        return 0

    jax.lax.fori_loop(0, s, body, 0)


def install_pallas(w, m, v, tau, slots, wv, mv, vv, tv,
                   interpret: bool = False):
    """One cache transaction as ONE kernel: returns (w, m, v, tau) with
    ``slots`` rows replaced by the fetched values; OOB slots dropped."""
    slots2 = slots.reshape(1, -1).astype(jnp.int32)
    tau2 = tau.reshape(1, -1)
    tv2 = tv.reshape(1, -1)
    ow, om, ov, otau = pl.pallas_call(
        _install_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(w.shape, w.dtype),
            jax.ShapeDtypeStruct(m.shape, m.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
            jax.ShapeDtypeStruct(tau2.shape, tau.dtype),
        ],
        interpret=interpret,
    )(w, m, v, tau2, slots2, wv, mv, vv, tv2)
    return ow, om, ov, otau.reshape(tau.shape)


@functools.partial(jax.jit, donate_argnums=())
def _install_fused_xla(w, m, v, tau, slots, wv, mv, vv, tv):
    """The XLA "opt" install leg: all four scatters in one jit program —
    one dispatch per transaction instead of four. Slot list is pow2-padded
    by the caller (data.hot_cold), so the compile cache stays
    O(log max_group) per table shape."""
    return (w.at[slots].set(wv), m.at[slots].set(mv),
            v.at[slots].set(vv), tau.at[slots].set(tv))


def install_rows(w, m, v, tau, slots, wv, mv, vv, tv, *, mode: str = "auto"):
    """Leg-selected cache install. All legs are element-identical: the same
    rows get the same values, OOB (padding) slots are dropped."""
    leg = resolve(mode, "install", n_ids=int(slots.shape[0]),
                  width=int(w.shape[-1]) if w.ndim > 1 else 1)
    if leg == "pallas":
        return install_pallas(w, m, v, tau, slots, wv, mv, vv, tv)
    if leg == "opt":
        return _install_fused_xla(w, m, v, tau, slots, wv, mv, vv, tv)
    return None  # ref: caller keeps its per-array scatter path


def install_cache_size() -> int:
    """Compiled-variant count of the fused install program (the compile-
    cache bound test asserts the pow2 ladder keeps this O(log max))."""
    return _install_fused_xla._cache_size()


def install_cache_clear() -> None:
    _install_fused_xla.clear_cache()


# ---------------------------------------------------------------------------
# NumPy oracle (tests)
# ---------------------------------------------------------------------------


def reference_plan_numpy(ids, num_rows):
    """np.unique-based oracle for the plan builders (tests)."""
    import numpy as np
    flat = np.asarray(ids).reshape(-1).astype(np.int64)
    uniq, inv = np.unique(flat, return_inverse=True)
    n = flat.size
    uids = np.full((n,), num_rows, np.int32)
    uids[: uniq.size] = uniq
    touched = np.zeros((num_rows,), bool)
    touched[uniq[uniq < num_rows]] = True
    rank = np.zeros((num_rows,), np.int32)
    rank[uniq[uniq < num_rows]] = np.arange(uniq.size)[uniq < num_rows]
    return (uids, inv.reshape(np.asarray(ids).shape).astype(np.int32),
            touched, rank)


def reference_exchange_numpy(uids, num_rows, num_shards, shard):
    """Sequential-scan oracle for ``ops.embedding.build_exchange`` (tests).

    Walks this shard's uid slice in order and assigns each valid id the
    next slot of its owner's request bucket — for a SORTED uid list that
    is exactly the searchsorted bucketing the jit builder computes.
    Returns (reqs [D, C] int32, flat_idx [C] int32)."""
    import numpy as np
    uids = np.asarray(uids, np.int64)
    cap = -(-uids.size // num_shards)
    rows_local = num_rows // num_shards
    pad = np.full((num_shards * cap,), num_rows, np.int64)
    pad[:uids.size] = uids
    sl = pad[shard * cap:(shard + 1) * cap]
    reqs = np.full((num_shards, cap), num_rows, np.int32)
    flat_idx = np.full((cap,), num_shards * cap, np.int32)
    counts = [0] * num_shards
    for j, uid in enumerate(sl):
        if uid >= num_rows:
            continue
        owner = int(uid // rows_local)
        reqs[owner, counts[owner]] = uid
        flat_idx[j] = owner * cap + counts[owner]
        counts[owner] += 1
    return reqs, flat_idx
